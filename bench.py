"""Driver benchmark: ResNet-50 synthetic throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's published sample throughput for its benchmark
methodology is 1656.82 images/sec on 16 Pascal GPUs (ResNet-101, batch 64,
reference docs/benchmarks.rst:27-41) ≈ 103.55 img/sec/GPU; the in-repo
synthetic benchmark's default model is ResNet-50 (reference
examples/tensorflow2_synthetic_benchmark.py:32-35).  vs_baseline =
our img/sec/chip ÷ 103.55.
"""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

BASELINE_IMG_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.rst:27-41


def main() -> None:
    from examples.synthetic_benchmark import parse_args, run

    args = parse_args([
        "--batch-size", "256",
        "--num-warmup-batches", "3",
        "--num-batches-per-iter", "10",
        "--num-iters", "3",
    ])
    result = run(args)
    per_chip = result["img_sec_per_chip"]
    print(json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_DEVICE, 3),
    }))


if __name__ == "__main__":
    main()
