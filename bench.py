"""Driver benchmark: ResNet-50 synthetic throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the reference's published sample throughput for its benchmark
methodology is 1656.82 images/sec on 16 Pascal GPUs (ResNet-101, batch 64,
reference docs/benchmarks.rst:27-41) ≈ 103.55 img/sec/GPU; the in-repo
synthetic benchmark's default model is ResNet-50 (reference
examples/tensorflow2_synthetic_benchmark.py:32-35).  vs_baseline =
our img/sec/chip ÷ 103.55.

Configuration (from the round-2 profiling study, docs/PERF.md): batch 128
(measured sweet spot on the v5e: the 56x56-stage activations are HBM-
bound, smaller batch wins), bf16 compute, 100 optimizer steps compiled
into one program via lax.scan.  Round 4 measured k=50 over k=10 (+15%);
round 5 re-measured interleaved: k=100 beats k=50 by +2.6% (47.47 vs
48.72 ms/step, min-of-4 in one process) and k=200 adds only ~0.5% —
below the tunnel's window drift — so 100 is the knee.

MFU accounting: ResNet-50 training ≈ 3 x 4.09 GFLOPs forward = 12.27
GFLOPs/image of model math (the usual analytic count; XLA's own
cost_analysis reports 23.9 GFLOPs/image because strided-conv gradients
lower to dilated convs that multiply zeros).  Peak = 197 TFLOPS bf16 per
v5e chip.

Outage handling (round-5): the tunneled chip has TWO failure modes —
``jax.devices()`` raising UNAVAILABLE, and ``jax.devices()`` HANGING
(the axon plugin's make_c_api_client blocks forever when the tunnel is
down).  Both the probe and the measurement therefore run in CHILD
processes under hard timeouts, with a bounded retry, so a transient blip
at capture time degrades to one structured JSON error line (rc 0)
instead of a traceback or a hung driver.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

BASELINE_IMG_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.rst:27-41
MODEL_FLOPS_PER_IMG = 12.27e9               # 3x forward, analytic
V5E_PEAK_FLOPS = 197e12                     # bf16 per chip

PROBE_TIMEOUT_S = 90       # jax.devices() normally returns in seconds
RUN_TIMEOUT_S = 560        # compile (~40 s) + 3 measured iters, generous
ATTEMPTS = 3
RETRY_DELAY_S = 75         # 3 probes spread over ~5 minutes


def _measure() -> None:
    """Child-process entry: touch the TPU and print the result line."""
    import jax

    platform = jax.devices()[0].platform
    if platform == "cpu":  # same guard as the probe, in-process
        raise RuntimeError(
            "refusing to publish a CPU number as the per-chip TPU metric"
        )
    from examples.synthetic_benchmark import parse_args, run

    args = parse_args([
        "--batch-size", "128",
        "--num-in-graph-steps", "100",
        "--num-warmup-batches", "1",
        "--num-batches-per-iter", "1",
        "--num-iters", "3",
    ])
    result = run(args)
    per_chip = result["img_sec_per_chip"]
    mfu = per_chip * MODEL_FLOPS_PER_IMG / V5E_PEAK_FLOPS
    print("RESULT " + json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_DEVICE, 3),
        "mfu": round(mfu, 4),
        "mfu_note": "12.27 GF/img analytic / 197 TFLOPS v5e peak; "
                    "see docs/PERF.md for the profile",
    }))


def _probe() -> str:
    """'ok' if a child process can enumerate an ACCELERATOR within the
    timeout; otherwise a short reason ('hang', 'unavailable',
    'cpu_only').  A CPU-only backend (e.g. the axon plugin not
    registered because PALLAS_AXON_POOL_IPS is unset) must read as an
    outage — otherwise the benchmark would silently publish a CPU
    number as the per-chip TPU metric."""
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM', d[0].platform)")
    try:
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=PROBE_TIMEOUT_S, cwd=os.path.dirname(
                os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return "hang"
    if p.returncode != 0 or "PLATFORM" not in p.stdout:
        return "unavailable"
    platform = p.stdout.split("PLATFORM", 1)[1].strip().split()[0]
    return "ok" if platform != "cpu" else "cpu_only"


def main() -> None:
    errors = []
    for attempt in range(ATTEMPTS):
        if attempt:
            time.sleep(RETRY_DELAY_S)
        status = _probe()
        if status != "ok":
            errors.append(f"probe {attempt + 1}: {status}")
            continue
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                capture_output=True, text=True, timeout=RUN_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            errors.append(f"run {attempt + 1}: timeout after "
                          f"{RUN_TIMEOUT_S}s")
            continue
        lines = [ln for ln in p.stdout.splitlines()
                 if ln.startswith("RESULT ")]
        if p.returncode == 0 and lines:
            print(lines[-1][len("RESULT "):])
            return
        tail = (p.stderr or p.stdout).strip().splitlines()[-1:]
        errors.append(
            f"run {attempt + 1}: rc={p.returncode} {' '.join(tail)[:200]}")
    # every attempt failed: one structured line, clean exit — the driver
    # records a skip, not a crash (round-4 lost its number to a traceback)
    print(json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": "tpu_unavailable",
        "attempts": errors,
        "note": "TPU tunnel unreachable at capture time; last driver-"
                "verified value 2474.8 (BENCH_r03), builder-measured "
                "2636 (docs/PERF.md)",
    }))


if __name__ == "__main__":
    if "--child" in sys.argv:
        _measure()
    else:
        main()
