"""Driver benchmark: ResNet-50 synthetic throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the reference's published sample throughput for its benchmark
methodology is 1656.82 images/sec on 16 Pascal GPUs (ResNet-101, batch 64,
reference docs/benchmarks.rst:27-41) ≈ 103.55 img/sec/GPU; the in-repo
synthetic benchmark's default model is ResNet-50 (reference
examples/tensorflow2_synthetic_benchmark.py:32-35).  vs_baseline =
our img/sec/chip ÷ 103.55.

Configuration (from the round-2 profiling study, docs/PERF.md): batch 128
(measured sweet spot on the v5e: the 56x56-stage activations are HBM-
bound, smaller batch wins), bf16 compute, 100 optimizer steps compiled
into one program via lax.scan.  Round 4 measured k=50 over k=10 (+15%);
round 5 re-measured interleaved: k=100 beats k=50 by +2.6% (47.47 vs
48.72 ms/step, min-of-4 in one process) and k=200 adds only ~0.5% —
below the tunnel's window drift — so 100 is the knee.

MFU accounting: ResNet-50 training ≈ 3 x 4.09 GFLOPs forward = 12.27
GFLOPs/image of model math (the usual analytic count; XLA's own
cost_analysis reports 23.9 GFLOPs/image because strided-conv gradients
lower to dilated convs that multiply zeros).  Peak = 197 TFLOPS bf16 per
v5e chip.

Outage handling (round-5): the tunneled chip has TWO failure modes —
``jax.devices()`` raising UNAVAILABLE, and ``jax.devices()`` HANGING
(the axon plugin's make_c_api_client blocks forever when the tunnel is
down).  Both the probe and the measurement therefore run in CHILD
processes under hard timeouts, with a bounded retry, so a transient blip
at capture time degrades to one structured JSON error line (rc 0)
instead of a traceback or a hung driver.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])

BASELINE_IMG_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.rst:27-41
# MFU constants live in horovod_tpu/utils/flops.py (single-sourced with
# the hvd_mfu gauge and the comm report; HVD_PEAK_FLOPS overrides the
# peak) — every leg's mfu field routes through _mfu() below


def _mfu(img_sec_per_chip) -> "float | None":
    """First-class MFU for a bench leg, computed through utils/flops so
    the bench JSON and the ``hvd_mfu`` gauge can never disagree; None on
    any failure (same null-on-failure contract as the delta legs)."""
    try:
        from horovod_tpu.utils import flops as _flops

        v = _flops.image_model_mfu(float(img_sec_per_chip))
        return round(v, 4) if v > 0 else None
    except Exception:  # noqa: BLE001 — mfu must never cost the number
        return None

PROBE_TIMEOUT_S = 90       # jax.devices() normally returns in seconds
RUN_TIMEOUT_S = 560        # compile (~40 s) + 3 measured iters, generous
AUTOTUNE_TIMEOUT_S = 420   # autotuned comparison run (re-jits a few times)
COMPRESSION_TIMEOUT_S = 420  # compressed comparison run (one compile)
SERVE_TIMEOUT_S = 180      # serving fixture: a few MLP compiles + ~1.5 s trace
PROJECTION_TIMEOUT_S = 240  # digital-twin leg: two traced MLP drives (1 + 8 dev)
COMPUTE_OPT_TIMEOUT_S = 240  # compute-path A/B: two MLP drives + a profiler window
CONTROL_TIMEOUT_S = 120    # control-plane churn: ~5k loopback HTTP requests
WATCH_TIMEOUT_S = 90       # watchdog leg: pure host-side detector replay
RESTORE_TIMEOUT_S = 120    # peer-restore leg: snapshot/restore fixture
CHAOS_TIMEOUT_S = 240      # chaos leg: 8-scenario in-process campaign
ATTEMPTS = 3
RETRY_DELAY_S = 75         # 3 probes spread over ~5 minutes


def _measure() -> None:
    """Child-process entry: touch the TPU and print the result line."""
    import jax

    platform = jax.devices()[0].platform
    if platform == "cpu":  # same guard as the probe, in-process
        raise RuntimeError(
            "refusing to publish a CPU number as the per-chip TPU metric"
        )
    from examples.synthetic_benchmark import parse_args, run

    args = parse_args([
        "--batch-size", "128",
        "--num-in-graph-steps", "100",
        "--num-warmup-batches", "1",
        "--num-batches-per-iter", "1",
        "--num-iters", "3",
    ])
    result = run(args)
    per_chip = result["img_sec_per_chip"]
    print("RESULT " + json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_DEVICE, 3),
        "mfu": _mfu(per_chip),
        "mfu_note": "12.27 GF/img analytic / peak from utils/flops "
                    "(197 TFLOPS v5e unless HVD_PEAK_FLOPS); "
                    "see docs/PERF.md for the profile",
    }))


def _measure_autotuned() -> None:
    """Child-process entry for the autotuned comparison leg: the same
    synthetic benchmark with the live Bayesian autotuner (warm-started
    from the α–β model, docs/autotune.md) moving the fusion knobs.  A
    shorter run — the point is the autotuned-vs-default delta, not a
    second absolute number — with a small sample budget so the re-jit
    cost stays inside AUTOTUNE_TIMEOUT_S."""
    import jax

    if jax.devices()[0].platform == "cpu":
        raise RuntimeError("refusing to benchmark autotune on CPU")
    os.environ.setdefault("HVD_AUTOTUNE_WARMUP_SAMPLES", "0")
    os.environ.setdefault("HVD_AUTOTUNE_STEPS_PER_SAMPLE", "1")
    os.environ.setdefault("HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "4")
    from examples.synthetic_benchmark import parse_args, run

    args = parse_args([
        "--batch-size", "128",
        "--num-in-graph-steps", "100",
        "--num-warmup-batches", "6",   # tuner samples + freeze happen here
        "--num-batches-per-iter", "1",
        "--num-iters", "2",
        "--autotune",
    ])
    result = run(args)
    print("RESULT " + json.dumps(
        {"img_sec_per_chip": round(result["img_sec_per_chip"], 2),
         "mfu": _mfu(result["img_sec_per_chip"])}))


def _measure_compressed() -> None:
    """Child-process entry for the compressed comparison leg: the same
    synthetic benchmark with error-feedback int8 gradient compression
    (docs/compression.md) — the wire-efficiency tier's headline delta.
    Single-chip, so the delta isolates the quantize/dequantize overhead
    (the wire saving needs a multi-chip run to show up); a shorter run,
    same contract as the autotune leg: the point is the delta, not a
    second absolute number."""
    import jax

    if jax.devices()[0].platform == "cpu":
        raise RuntimeError("refusing to benchmark compression on CPU")
    from examples.synthetic_benchmark import parse_args, run

    args = parse_args([
        "--batch-size", "128",
        "--num-in-graph-steps", "100",
        "--num-warmup-batches", "1",
        "--num-batches-per-iter", "1",
        "--num-iters", "2",
        "--compression", "int8",
    ])
    result = run(args)
    print("RESULT " + json.dumps(
        {"img_sec_per_chip": round(result["img_sec_per_chip"], 2),
         "mfu": _mfu(result["img_sec_per_chip"])}))


def _measure_serving() -> None:
    """Child-process entry for the serving leg: the seeded bursty
    open-loop load-generator fixture against a small jitted MLP
    replica set (docs/inference.md) — p50/p99 request latency and
    goodput-under-burst are the serving plane's headline numbers.
    Latency of a tiny MLP is host-dominated, so this leg runs on
    whatever platform the child gets (CPU included): it benchmarks the
    batching/queueing plane, not the chip."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from horovod_tpu.serving.plane import run_bench_fixture

    out = run_bench_fixture()
    print("RESULT " + json.dumps({
        "serve_p50_ms": out["serve_p50_ms"],
        "serve_p99_ms": out["serve_p99_ms"],
        "goodput_under_burst": out["goodput_under_burst"],
        "serve_offered": out["offered"],
        "serve_completed": out["completed"],
    }))


def _measure_projection() -> None:
    """Child-process entry for the digital-twin accuracy leg: drive the
    1-device → 8-device CPU-mesh validation (timeline/replay/projection
    live_validation, docs/projection.md) and report the twin's
    projected-vs-measured step-time error.  Like the serving leg this
    benchmarks a host-side plane, not the chip, so it runs on the CPU
    mesh regardless of TPU availability — the twin's ACCURACY is the
    tracked number, the same way autotune_delta_pct tracks the tuner."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from horovod_tpu.timeline.replay.projection import live_validation

    out = live_validation()
    print("RESULT " + json.dumps({
        "projection_err_pct": out["err_pct"],
        "projected_step_us": out["projected_step_us"],
        "measured_step_us": out["measured_step_us"],
    }))


def _measure_compute_opt() -> None:
    """Child-process entry for the compute-path A/B leg: the same tiny
    MLP job with the fused-update + async-pipeline path ON vs OFF on
    the dev CPU mesh (optim/compute_knobs.py run_bench_fixture,
    docs/PERF.md compute tier).  Like the serving/projection legs this
    benchmarks host-side machinery, not the chip — the delta isolates
    what the per-leaf optimizer traversal, the per-step loss sync, and
    the unprefetched loader cost, and the profiler window's
    host_gap_pct is the async pipeline's proof."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from horovod_tpu.optim.compute_knobs import run_bench_fixture

    out = run_bench_fixture()
    print("RESULT " + json.dumps({
        "compute_opt_delta_pct": out["compute_opt_delta_pct"],
        "host_gap_pct": out["host_gap_pct"],
        "compute_opt_loss_equal": out["loss_equal"],
    }))


def _measure_control() -> None:
    """Child-process entry for the control-plane churn leg: the
    simulated 64-host/512-rank heartbeat/metrics/fingerprint storm of
    scripts/control_plane_bench.py against a real sharded rendezvous
    server (docs/control_plane.md).  Pure host-side machinery — no
    accelerator involved — so it runs anywhere; the tracked numbers are
    the relay-vs-per-rank request reduction and the p99 lease-renewal /
    epoch-commit latencies."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"))
    from control_plane_bench import run_bench

    out = run_bench(hosts=64, ranks=512, ticks=3)
    print("RESULT " + json.dumps({
        "control_p99_lease_ms": out["p99_lease_renewal_ms"],
        "control_p99_epoch_ms": out["p99_epoch_commit_ms"],
        "control_abort_ms": out["abort_propagation_ms"],
        "control_request_reduction_x": out["request_reduction_x"],
    }))


def _measure_watch() -> None:
    """Child-process entry for the watchdog leg: a scripted step-time
    regression (2 ranks, 200 quiet steps at ~0.100 s, then rank 0
    degrading to 0.200 s) replayed through a real rendezvous server +
    Watchdog (observe/watchdog.py) — pure host-side machinery, runs
    anywhere.  Tracked numbers: detection latency in steps past the
    regression onset, false positives over the quiet phase, and the
    per-append cost of the always-on ring buffer (the ONLY thing the
    step path pays)."""
    import json as _json
    import time as _time

    os.environ["HVD_WATCH_INTERVAL_SECONDS"] = "999"  # tick() driven by hand
    from horovod_tpu.metrics import timeseries as ts_mod
    from horovod_tpu.observe.watchdog import Watchdog
    from horovod_tpu.run.http_server import RendezvousServer

    # ring-buffer append cost: the step path's entire overhead
    n = 200_000
    t0 = _time.perf_counter()
    for i in range(n):
        ts_mod.record(ts_mod.STEP_SECONDS, 0.1, step=i)
    append_us = (_time.perf_counter() - t0) / n * 1e6
    ts_mod.store.reset()

    server = RendezvousServer()
    server.start()
    try:
        dog = Watchdog(server)        # not started: ticks driven below
        stores = {r: ts_mod.TimeseriesStore(enabled=True)
                  for r in ("0", "1")}
        quiet_steps, onset_extra, chunk = 200, 100, 5

        def _feed(step):
            for rank, st in stores.items():
                dt = 0.100 if step % 2 else 0.101
                if rank == "0" and step > quiet_steps:
                    dt = 0.200             # the scripted regression
                st.record(ts_mod.STEP_SECONDS, dt, step=step)
                server.put("timeseries", rank,
                           _json.dumps(st.snapshot()).encode())

        false_positives = 0
        detect_step = None
        for step in range(1, quiet_steps + onset_extra + 1):
            _feed(step)
            if step % chunk:
                continue
            alerts = dog.tick()
            if step <= quiet_steps:
                false_positives += len(alerts)
            elif detect_step is None and any(
                    a["signal"] in ("step_time_regression",
                                    "straggler_drift") for a in alerts):
                detect_step = step
        print("RESULT " + _json.dumps({
            "watch_detect_steps": (detect_step - quiet_steps)
            if detect_step is not None else None,
            "watch_false_positives": false_positives,
            "watch_armed": dog.arms > 0,
            "watch_append_us": round(append_us, 3),
            "watch_overhead_pct_1ms_step": round(append_us / 1e3 * 100, 4),
        }))
    finally:
        server.stop()


def _watch_leg() -> dict:
    """The watchdog tail fields, from a separately-timed child so a
    hung or failed detector replay can never cost the main number
    (HVD_BENCH_WATCH=0 skips).  Null-on-failure, same contract as
    every other leg."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_WATCH, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-watch", WATCH_TIMEOUT_S)
        if payload is not None:
            return {
                "watch_detect_steps": payload.get("watch_detect_steps"),
                "watch_false_positives":
                    payload.get("watch_false_positives"),
                "watch_armed": payload.get("watch_armed"),
                "watch_append_us": payload.get("watch_append_us"),
                "watch_overhead_pct_1ms_step":
                    payload.get("watch_overhead_pct_1ms_step"),
            }
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"watch_detect_steps": None, "watch_false_positives": None,
            "watch_armed": None, "watch_append_us": None,
            "watch_overhead_pct_1ms_step": None, "watch_error": reason}


def _measure_restore() -> None:
    """Child-process entry for the peer-state-plane leg: a 3-worker
    in-process fixture (one rendezvous + three peer shard servers,
    elastic/peerstate.py) snapshotting a ~4 MB state every 5 steps and
    then restoring from peers under churn — pure host-side machinery,
    runs anywhere.  Tracked numbers: the step-path stall of a snapshot
    enqueue in µs (the ONLY checkpoint cost a step pays on the peer
    tier), restore-to-training p99 in ms, and steps lost on a failure
    at the worst point of the snapshot interval."""
    import json as _json
    import time as _time

    import numpy as np

    os.environ["HVD_NUM_PROCESSES"] = "3"   # gen committed = all 3 ranks
    from horovod_tpu.elastic.peerstate import PeerSnapshotManager
    from horovod_tpu.run.http_server import RendezvousServer

    secret = b"bench-restore"
    server = RendezvousServer(secret=secret)
    port = server.start()
    managers = [
        PeerSnapshotManager(replicas_k=2, nshards=4, addr="127.0.0.1",
                            port=port, secret=secret, worker=f"w{r}",
                            rank=r)
        for r in range(3)
    ]
    try:
        for m in managers:
            m.start()
        rng = np.random.default_rng(0)
        state = {"params": rng.standard_normal(500_000),  # ~4 MB
                 "opt": rng.standard_normal(2)}
        # 59 steps with a 5-step cadence: the last committed generation
        # is 55, so the measured steps-lost is the honest worst point of
        # the interval (a crash just before the next snapshot)
        steps, interval = 59, 5
        stalls_us = []
        for step in range(1, steps + 1):
            _time.sleep(0.001)                    # the fake train step
            if step % interval == 0:
                for m in managers:
                    s = m.snapshot(state, step)
                    if m is managers[0]:
                        stalls_us.append(s * 1e6)
        ok = all(m.drain(30.0) for m in managers)
        restores_ms = []
        restored_gen = None
        for _ in range(20):
            # a fresh manager each round: the post-crash relaunch shape
            fresh = PeerSnapshotManager(replicas_k=2, nshards=4,
                                        addr="127.0.0.1", port=port,
                                        secret=secret, worker="w0", rank=0)
            t0 = _time.perf_counter()
            got = fresh.restore()
            restores_ms.append((_time.perf_counter() - t0) * 1e3)
            if got is not None:
                restored_gen = got[1]
        restores_ms.sort()
        got_all = restored_gen is not None and bool(restores_ms)
        p99_i = min(int(len(restores_ms) * 0.99), len(restores_ms) - 1) \
            if restores_ms else 0
        print("RESULT " + _json.dumps({
            "restore_ckpt_stall_us":
                round(sum(stalls_us) / len(stalls_us), 3)
                if stalls_us else None,
            "restore_p99_ms": round(restores_ms[p99_i], 3)
                if got_all else None,
            "restore_p50_ms": round(
                restores_ms[len(restores_ms) // 2], 3)
                if got_all else None,
            # a crash at the worst point loses the steps since the last
            # committed snapshot — the target is one interval
            "restore_steps_lost": (steps - restored_gen)
                if restored_gen is not None else None,
            "restore_snapshot_interval": interval,
            "restore_drained": ok,
        }))
    finally:
        for m in managers:
            m.stop()
        server.stop()


def _restore_leg() -> dict:
    """The peer-state-plane tail fields, from a separately-timed child
    so a hung snapshot fixture can never cost the main number
    (HVD_BENCH_RESTORE=0 skips).  Null-on-failure, same contract as
    every other leg."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_RESTORE, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-restore", RESTORE_TIMEOUT_S)
        if payload is not None:
            return {
                "restore_ckpt_stall_us":
                    payload.get("restore_ckpt_stall_us"),
                "restore_p99_ms": payload.get("restore_p99_ms"),
                "restore_steps_lost": payload.get("restore_steps_lost"),
                "restore_snapshot_interval":
                    payload.get("restore_snapshot_interval"),
            }
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"restore_ckpt_stall_us": None, "restore_p99_ms": None,
            "restore_steps_lost": None, "restore_snapshot_interval": None,
            "restore_error": reason}


def _measure_chaos() -> None:
    """Child-process entry for the chaos-campaign leg: a fixed-seed
    8-scenario campaign (elastic/chaos.py) against the in-process
    elastic control plane — crashes, hangs, partitions, preemptions,
    a primary kill, and a relay kill, all invariant-checked.  Tracked
    numbers: MTTR p50/p99 across every recovery (trigger evidence to
    the last survivor resume), the worst steps-lost of any resume, and
    the violation count (which must be 0 for the leg to report)."""
    import json as _json

    import logging as _logging

    _logging.disable(_logging.ERROR)   # scenario churn is all expected
    from horovod_tpu.elastic import chaos

    scenarios = chaos.generate_campaign(1234, count=8)
    campaign = chaos.run_campaign(scenarios, seed=1234)
    mttrs = sorted(r["mttr_ms"] for res in campaign.results
                   for r in res.recoveries if r["mttr_ms"] is not None)
    losses = [lost for res in campaign.results
              for r in res.recoveries for lost in r["steps_lost"]]
    n_viol = sum(len(res.violations) for res in campaign.results)
    ok = campaign.ok and bool(mttrs)
    p99_i = min(int(len(mttrs) * 0.99), len(mttrs) - 1) if mttrs else 0
    print("RESULT " + _json.dumps({
        "chaos_mttr_p50_ms": round(mttrs[len(mttrs) // 2], 1)
            if ok else None,
        "chaos_mttr_p99_ms": round(mttrs[p99_i], 1) if ok else None,
        "chaos_steps_lost_max": max(losses) if ok and losses else None,
        "chaos_scenarios": len(campaign.results),
        "chaos_recoveries": len(mttrs),
        "chaos_violations": n_viol,
    }))


def _chaos_leg() -> dict:
    """The chaos-campaign tail fields, from a separately-timed child so
    a wedged scenario can never cost the main number
    (HVD_BENCH_CHAOS=0 skips).  Null-on-failure, same contract as
    every other leg."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_CHAOS, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-chaos", CHAOS_TIMEOUT_S)
        if payload is not None:
            return {
                "chaos_mttr_p50_ms": payload.get("chaos_mttr_p50_ms"),
                "chaos_mttr_p99_ms": payload.get("chaos_mttr_p99_ms"),
                "chaos_steps_lost_max":
                    payload.get("chaos_steps_lost_max"),
                "chaos_scenarios": payload.get("chaos_scenarios"),
                "chaos_violations": payload.get("chaos_violations"),
            }
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"chaos_mttr_p50_ms": None, "chaos_mttr_p99_ms": None,
            "chaos_steps_lost_max": None, "chaos_error": reason}


def _control_leg() -> dict:
    """The control-plane tail fields, from a separately-timed child so
    a hung or failed churn run can never cost the main number
    (HVD_BENCH_CONTROL=0 skips).  ``control_p99_*`` are null on any
    failure — same contract as every other leg."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_CONTROL, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-control", CONTROL_TIMEOUT_S)
        if payload is not None:
            return {
                "control_p99_lease_ms": payload.get("control_p99_lease_ms"),
                "control_p99_epoch_ms": payload.get("control_p99_epoch_ms"),
                "control_abort_ms": payload.get("control_abort_ms"),
                "control_request_reduction_x":
                    payload.get("control_request_reduction_x"),
            }
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"control_p99_lease_ms": None, "control_p99_epoch_ms": None,
            "control_abort_ms": None, "control_request_reduction_x": None,
            "control_error": reason}


def _compute_opt_leg() -> dict:
    """The compute-path tail fields (compute_opt_delta_pct +
    host_gap_pct), from a separately-timed child so a hung or failed
    A/B can never cost the main number (HVD_BENCH_COMPUTE_OPT=0
    skips).  Null-on-failure, same contract as every other leg."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_COMPUTE_OPT, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-compute-opt",
                                     COMPUTE_OPT_TIMEOUT_S)
        if payload is not None:
            return {
                "compute_opt_delta_pct":
                    payload.get("compute_opt_delta_pct"),
                "host_gap_pct": payload.get("host_gap_pct"),
                "compute_opt_loss_equal":
                    payload.get("compute_opt_loss_equal"),
            }
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"compute_opt_delta_pct": None, "host_gap_pct": None,
            "compute_opt_error": reason}


def _projection_leg() -> dict:
    """The projection-accuracy tail field, from a separately-timed child
    so a hung or failed twin drive can never cost the main number
    (HVD_BENCH_PROJECTION=0 skips).  ``projection_err_pct`` is null on
    any failure — same contract as the autotune/compression legs."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_PROJECTION, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-projection",
                                     PROJECTION_TIMEOUT_S)
        if payload is not None:
            return {"projection_err_pct": payload.get("projection_err_pct")}
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"projection_err_pct": None, "projection_error": reason}


def _serving_leg() -> dict:
    """The serving tail fields, from a separately-timed child so a hung
    or failed serving fixture can never cost the training number
    (HVD_BENCH_SERVE=0 skips).  Null-on-failure, same contract as the
    autotune/compression legs."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_SERVE, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-serve", SERVE_TIMEOUT_S)
        if payload is not None:
            return {
                "serve_p50_ms": payload.get("serve_p50_ms"),
                "serve_p99_ms": payload.get("serve_p99_ms"),
                "goodput_under_burst": payload.get("goodput_under_burst"),
            }
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"serve_p50_ms": None, "serve_p99_ms": None,
            "goodput_under_burst": None, "serve_error": reason}


def _compression_delta(default_per_chip: float) -> dict:
    """The compressed-vs-default tail fields, from a separately-timed
    child so a hung or failed compression leg can never cost the main
    number (HVD_BENCH_COMPRESSION=0 skips).  Returns the fields to
    merge into the RESULT payload — ``compression_delta_pct`` is null
    on any failure, same contract as the autotune leg."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_COMPRESSION, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled or default_per_chip <= 0:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-compression",
                                     COMPRESSION_TIMEOUT_S)
        if payload is not None:
            at = float(payload["img_sec_per_chip"])
            return {
                "compressed_img_sec_per_chip": round(at, 2),
                "compressed_mfu": payload.get("mfu"),
                "compression_delta_pct": round(
                    (at - default_per_chip) / default_per_chip * 100.0, 2),
            }
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"compression_delta_pct": None, "compressed_mfu": None,
            "compression_error": reason}


def _run_child(flag: str, timeout_s: float):
    """Run this file as a child process with ``flag`` and parse its
    ``RESULT`` line.  Returns ``(payload, None)`` on success or
    ``(None, reason)`` — the one copy of the child protocol that both
    the main measurement and the autotune leg share."""
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), flag],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s:g}s"
    except Exception as e:  # noqa: BLE001 — callers degrade, never crash
        return None, f"{type(e).__name__}: {e}"
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("RESULT ")]
    if p.returncode == 0 and lines:
        try:
            return json.loads(lines[-1][len("RESULT "):]), None
        except ValueError as e:
            return None, f"unparseable result: {e}"
    tail = (p.stderr or p.stdout).strip().splitlines()[-1:]
    return None, f"rc={p.returncode} {' '.join(tail)[:200]}"


def _autotune_delta(default_per_chip: float) -> dict:
    """The autotuned-vs-default tail fields, from a separately-timed
    child so a hung or failed autotune leg can never cost the main
    number.  Returns the fields to merge into the RESULT payload."""
    try:
        from horovod_tpu.utils import env as env_util

        enabled = env_util.get_bool(env_util.HVD_BENCH_AUTOTUNE, True)
    except Exception:  # noqa: BLE001
        enabled = True
    if not enabled or default_per_chip <= 0:
        return {}
    reason = None
    try:
        payload, reason = _run_child("--child-autotune", AUTOTUNE_TIMEOUT_S)
        if payload is not None:
            at = float(payload["img_sec_per_chip"])
            return {
                "autotuned_img_sec_per_chip": round(at, 2),
                "autotuned_mfu": payload.get("mfu"),
                "autotune_delta_pct": round(
                    (at - default_per_chip) / default_per_chip * 100.0, 2),
            }
    except Exception as e:  # noqa: BLE001 — the leg can never cost the main number
        reason = f"{type(e).__name__}: {e}"
    return {"autotune_delta_pct": None, "autotuned_mfu": None,
            "autotune_error": reason}


def _probe() -> str:
    """'ok' if a child process can enumerate an ACCELERATOR within the
    timeout; otherwise a short reason ('hang', 'unavailable',
    'cpu_only').  A CPU-only backend (e.g. the axon plugin not
    registered because PALLAS_AXON_POOL_IPS is unset) must read as an
    outage — otherwise the benchmark would silently publish a CPU
    number as the per-chip TPU metric."""
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM', d[0].platform)")
    try:
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=PROBE_TIMEOUT_S, cwd=os.path.dirname(
                os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return "hang"
    if p.returncode != 0 or "PLATFORM" not in p.stdout:
        return "unavailable"
    platform = p.stdout.split("PLATFORM", 1)[1].strip().split()[0]
    return "ok" if platform != "cpu" else "cpu_only"


def main() -> None:
    errors = []
    for attempt in range(ATTEMPTS):
        if attempt:
            time.sleep(RETRY_DELAY_S)
        status = _probe()
        if status != "ok":
            errors.append(f"probe {attempt + 1}: {status}")
            continue
        out, reason = _run_child("--child", RUN_TIMEOUT_S)
        if out is not None:
            # autotuned-vs-default tail (HVD_BENCH_AUTOTUNE=0 skips):
            # did the profile-guided/Bayesian loop move the MFU number?
            out.update(_autotune_delta(float(out.get("value", 0.0))))
            # compressed-vs-default tail (HVD_BENCH_COMPRESSION=0 skips):
            # what does error-feedback int8 cost/buy on this chip?
            out.update(_compression_delta(float(out.get("value", 0.0))))
            # serving tail (HVD_BENCH_SERVE=0 skips): p50/p99 request
            # latency + goodput-under-burst of the serving plane fixture
            out.update(_serving_leg())
            # digital-twin tail (HVD_BENCH_PROJECTION=0 skips): the
            # projection engine's accuracy on the world being benched
            out.update(_projection_leg())
            # compute-path tail (HVD_BENCH_COMPUTE_OPT=0 skips):
            # fused-update + async-pipeline on-vs-off delta and the
            # async pipeline's host_gap_pct, alongside mfu
            out.update(_compute_opt_leg())
            # control-plane tail (HVD_BENCH_CONTROL=0 skips): churn-
            # harness p99 lease/epoch latencies + relay request
            # reduction — the control plane's own tracked numbers
            out.update(_control_leg())
            # watchdog tail (HVD_BENCH_WATCH=0 skips): detection
            # latency + false positives on a scripted regression trace,
            # and the ring-buffer append cost the step path pays
            out.update(_watch_leg())
            # peer-state-plane tail (HVD_BENCH_RESTORE=0 skips):
            # snapshot enqueue stall µs/step, restore-from-peers p99,
            # and steps lost to a worst-point failure
            out.update(_restore_leg())
            # chaos-campaign tail (HVD_BENCH_CHAOS=0 skips): MTTR
            # p50/p99 and worst steps-lost across a fixed-seed
            # composed-fault campaign, invariant-checked
            out.update(_chaos_leg())
            print(json.dumps(out))
            return
        errors.append(f"run {attempt + 1}: {reason}")
    # every attempt failed: one structured line, clean exit — the driver
    # records a skip, not a crash (round-4 lost its number to a traceback)
    print(json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": "tpu_unavailable",
        "attempts": errors,
        "note": "TPU tunnel unreachable at capture time; last driver-"
                "verified value 2474.8 (BENCH_r03), builder-measured "
                "2636 (docs/PERF.md)",
    }))


if __name__ == "__main__":
    if "--child-autotune" in sys.argv:
        _measure_autotuned()
    elif "--child-compression" in sys.argv:
        _measure_compressed()
    elif "--child-serve" in sys.argv:
        _measure_serving()
    elif "--child-projection" in sys.argv:
        _measure_projection()
    elif "--child-compute-opt" in sys.argv:
        _measure_compute_opt()
    elif "--child-control" in sys.argv:
        _measure_control()
    elif "--child-watch" in sys.argv:
        _measure_watch()
    elif "--child-restore" in sys.argv:
        _measure_restore()
    elif "--child-chaos" in sys.argv:
        _measure_chaos()
    elif "--child" in sys.argv:
        _measure()
    else:
        main()
