"""Driver benchmark: ResNet-50 synthetic throughput on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the reference's published sample throughput for its benchmark
methodology is 1656.82 images/sec on 16 Pascal GPUs (ResNet-101, batch 64,
reference docs/benchmarks.rst:27-41) ≈ 103.55 img/sec/GPU; the in-repo
synthetic benchmark's default model is ResNet-50 (reference
examples/tensorflow2_synthetic_benchmark.py:32-35).  vs_baseline =
our img/sec/chip ÷ 103.55.

Configuration (from the round-2 profiling study, docs/PERF.md): batch 128
(measured sweet spot on the v5e: the 56x56-stage activations are HBM-
bound, smaller batch wins), bf16 compute, 50 optimizer steps compiled
into one program via lax.scan.  Round 4 re-measured the in-graph step
count interleaved on a quiet chip: k=50 beats k=10 by ~15% (2645/2611 vs
2300/2204 img/s across two windows each) — at k=10 the tunnel's per-call
dispatch+sync overhead still costs a double-digit share of the step.

MFU accounting: ResNet-50 training ≈ 3 x 4.09 GFLOPs forward = 12.27
GFLOPs/image of model math (the usual analytic count; XLA's own
cost_analysis reports 23.9 GFLOPs/image because strided-conv gradients
lower to dilated convs that multiply zeros).  Peak = 197 TFLOPS bf16 per
v5e chip.
"""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])

BASELINE_IMG_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.rst:27-41
MODEL_FLOPS_PER_IMG = 12.27e9               # 3x forward, analytic
V5E_PEAK_FLOPS = 197e12                     # bf16 per chip


def main() -> None:
    from examples.synthetic_benchmark import parse_args, run

    args = parse_args([
        "--batch-size", "128",
        "--num-in-graph-steps", "50",
        "--num-warmup-batches", "1",
        "--num-batches-per-iter", "1",
        "--num-iters", "3",
    ])
    result = run(args)
    per_chip = result["img_sec_per_chip"]
    mfu = per_chip * MODEL_FLOPS_PER_IMG / V5E_PEAK_FLOPS
    print(json.dumps({
        "metric": "resnet50_synthetic_img_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_SEC_PER_DEVICE, 3),
        "mfu": round(mfu, 4),
        "mfu_note": "12.27 GF/img analytic / 197 TFLOPS v5e peak; "
                    "see docs/PERF.md for the profile",
    }))


if __name__ == "__main__":
    main()
