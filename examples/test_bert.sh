#!/bin/bash
# BERT trace-collection sweep — analog of the reference's
# examples/test_bert.sh (gluon-nlp BERT with synthetic data under the
# byteprofile tracer).  Sweeps attention/sequence-parallel variants.
set -e
cd "$(dirname "$0")/.."

export HVD_TIMELINE="${TRACE_DIR:-/tmp/hvd_traces/bert}"
export HVD_TRACE_START_STEP="${HVD_TRACE_START_STEP:-5}"
export HVD_TRACE_END_STEP="${HVD_TRACE_END_STEP:-15}"

MODEL="${MODEL:-base}"
BATCH="${BATCH:-8}"
SEQ="${SEQ:-512}"

for ATTN in xla pallas; do
    echo "=== bert-$MODEL attn=$ATTN ==="
    python examples/bert_synthetic_benchmark.py \
        --model "$MODEL" --batch-size "$BATCH" --seq-len "$SEQ" \
        --attn "$ATTN" "$@"
done

echo "traces in $HVD_TIMELINE"
