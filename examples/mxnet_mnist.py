"""MNIST through the MXNet/Gluon API surface.

Mirror of the reference's mxnet_mnist.py recipe on the
``horovod_tpu.mxnet`` shim: ``DistributedTrainer`` wrapping gluon
training (gradient allreduce before the update), parameter broadcast
from root, metric averaging via the binding's allreduce (reference
examples/mxnet_mnist.py:60-130: hvd.DistributedTrainer,
hvd.broadcast_parameters, rank-sharded data).

mxnet is not part of this image; without it the example installs the
audited in-repo stand-in (tests/fake_mxnet.py) so the recipe executes
everywhere — with real mxnet on the path, the same code runs unchanged.
The TPU compute path for real training is the JAX API
(examples/mnist.py); this example is API parity for migrating gluon
scripts.

Run:  python examples/mxnet_mnist.py --epochs 1
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

try:
    import mxnet  # noqa: F401
except ImportError:  # CI image: use the audited fake
    sys.path.insert(0, __file__.rsplit("/", 2)[0] + "/tests")
    import fake_mxnet

    fake_mxnet.install()

import mxnet as mx  # noqa: E402

import horovod_tpu.mxnet as hvd_mx  # noqa: E402
from examples.datasets import synthetic_mnist  # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="horovod_tpu mxnet MNIST")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--num-samples", type=int, default=512)
    return p.parse_args(argv)


def run(args) -> dict:
    import jax

    hvd_mx.init(devices=jax.devices("cpu"))

    x, y = synthetic_mnist(args.num_samples)
    # per-rank shard (reference mxnet_mnist.py splits via rank/size)
    shard = slice(hvd_mx.rank(), None, hvd_mx.size())
    xs = x[shard].reshape(len(x[shard]), -1).astype(np.float32)
    ys = y[shard].astype(np.int32)

    # one-layer softmax regression: enough to exercise the full recipe
    # (the reference's conv net needs the real gluon HybridBlock zoo)
    rng = np.random.default_rng(0)
    w = mx.gluon.parameter.Parameter("w", shape=(784, 10))
    w.initialize()
    w.set_data(rng.normal(scale=0.01, size=(784, 10)).astype(np.float32))
    b = mx.gluon.parameter.Parameter("b", shape=(10,))
    b.initialize()

    # root-rank weight sync (reference mxnet_mnist.py broadcast)
    hvd_mx.broadcast_parameters({"w": w, "b": b}, root_rank=0)

    trainer = hvd_mx.DistributedTrainer(
        [w, b], "sgd", {"learning_rate": args.lr})

    def forward(bw, bb, bx):
        logits = bx @ bw + bb
        z = logits - logits.max(axis=1, keepdims=True)
        p = np.exp(z)
        return logits, p / p.sum(axis=1, keepdims=True)

    if len(xs) < args.batch_size:
        raise ValueError(
            f"per-rank shard ({len(xs)} samples at size={hvd_mx.size()}) "
            f"is smaller than --batch-size {args.batch_size}; raise "
            "--num-samples or lower --batch-size"
        )
    losses = []
    for epoch in range(args.epochs):
        batch_losses = []
        for i in range(0, len(xs) - args.batch_size + 1, args.batch_size):
            bx = xs[i:i + args.batch_size]
            by = ys[i:i + args.batch_size]
            bw = w.data().asnumpy()
            bb = b.data().asnumpy()
            _, probs = forward(bw, bb, bx)
            batch_losses.append(
                -np.log(probs[np.arange(len(by)), by] + 1e-9).mean())
            # manual softmax-xent gradient (the fake has no autograd;
            # with real mxnet an autograd.record() block replaces this)
            g = probs.copy()
            g[np.arange(len(by)), by] -= 1.0
            g /= len(by)
            w.list_grad()[0][:] = bx.T @ g
            b.list_grad()[0][:] = g.sum(axis=0)
            trainer.step(batch_size=1)  # grads already batch-averaged
        avg = hvd_mx.allreduce(
            mx.nd.array(np.asarray([np.mean(batch_losses)], np.float32)),
            name=f"epoch_loss.{epoch}")
        losses.append(float(avg.asnumpy()[0]))
        if hvd_mx.rank() == 0:
            print(f"epoch {epoch} loss {losses[-1]:.4f}")
    return {"final_loss": losses[-1], "initial_ok": len(losses) > 0}


if __name__ == "__main__":
    run(parse_args())
