"""Synthetic, dependency-free datasets for the examples.

The reference examples download MNIST (e.g. reference
examples/tensorflow2_mnist.py:28-34, pytorch_mnist.py:98-108); this image
has zero egress, so the examples use a procedurally generated stand-in with
the same shape contract (28x28x1 images, 10 classes) and a *learnable*
structure: labels are the argmax of 10 fixed random linear probes of the
image, so a model can actually drive the loss down and the examples behave
like real training runs (loss curves, accuracy climbing), deterministically.
"""

from __future__ import annotations

import numpy as np


def synthetic_mnist(n: int = 4096, seed: int = 1234):
    """Returns ``(x, y)``: x ``[n, 28, 28, 1]`` float32 in [0, 1],
    y ``[n]`` int32 in [0, 10)."""
    rng = np.random.default_rng(seed)
    x = rng.uniform(size=(n, 28, 28, 1)).astype(np.float32)
    probes = rng.normal(size=(10, 28 * 28)).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ probes.T, axis=1).astype(np.int32)
    return x, y


def synthetic_tokens(n: int = 1024, seq_len: int = 128, vocab: int = 1024,
                     seed: int = 99):
    """Token-id sequences for the BERT examples: ``[n, seq_len]`` int32."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, size=(n, seq_len)).astype(np.int32)
