"""ResNet-50 ImageNet training — the reference's flagship full-recipe
example (reference examples/keras_imagenet_resnet50.py), TPU-native.

Brings together the same distributed-training concepts the reference's
script demonstrates, each mapped to its horovod_tpu form:

reference (keras/horovod)                  | here (TPU-native)
------------------------------------------|----------------------------------
hvd.init + GPU pinning per local rank      | hvd.init() builds the mesh
checkpoint scan + broadcast resume epoch   | utils.checkpoint.latest_step on
                                           |   rank 0, broadcast to all
DistributedOptimizer(+fp16 compression)    | make_train_step fused-bucket
                                           |   allreduce (+bf16 compression,
                                           |   autotune, hierarchical ICI/DCN)
LearningRateWarmupCallback + staircase     | optax schedule: linear warmup →
  Schedule callbacks (Goyal et al. recipe) |   30/60/80-epoch staircase
MetricAverageCallback                      | in-step cross-rank loss average
rank-0 ModelCheckpoint                     | rank-0 Orbax save_checkpoint
ImageDataGenerator directories             | ShardedLoader over npz/synthetic
                                           |   shards (Join-safe tail)

With no --train-dir the script runs on synthetic data (the reference's
benchmark methodology) so the full recipe — warmup, schedule, resume,
checkpointing — is exercisable on any mesh, e.g.:

    tpurun -np 8 python examples/keras_imagenet_resnet50.py \
        --epochs 2 --steps-per-epoch 20 --checkpoint-dir /tmp/ckpt
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="TPU-native Keras-ImageNet-ResNet50 recipe",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--train-dir", default=None,
                   help="directory of .npz shards with arrays 'x' "
                        "(NHWC float) and 'y' (int labels); synthetic "
                        "data when unset")
    p.add_argument("--checkpoint-dir", default="./checkpoints",
                   help="Orbax checkpoint directory (rank 0 writes)")
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="bf16 wire compression for the gradient allreduce")
    p.add_argument("--hierarchical-allreduce", action="store_true",
                   help="two-level ICI/DCN gradient reduction")
    p.add_argument("--autotune", action="store_true",
                   help="live GP autotuning of the fusion threshold")
    # Goyal et al. (arXiv:1706.02677) hyperparameters, as the reference
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-chip batch size")
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--steps-per-epoch", type=int, default=100,
                   help="steps per epoch (synthetic mode)")
    p.add_argument("--base-lr", type=float, default=0.0125,
                   help="learning rate per chip (scaled by world size)")
    p.add_argument("--warmup-epochs", type=float, default=5)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=0.00005)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--platform", default=None)
    p.add_argument("--model", default="ResNet50",
                   help="registry name; the recipe is ResNet-50, but CI "
                        "smoke-runs it on ResNet-18 (CPU compiles of the "
                        "full model take tens of minutes on a 1-core host)")
    return p.parse_args(argv)


def lr_schedule(args, size: int, steps_per_epoch: int):
    """Linear warmup to base_lr*size over warmup_epochs, then the
    reference's staircase: x1 until epoch 30, x0.1, x0.01, x0.001
    (reference LearningRateScheduleCallback stack)."""
    import optax

    peak = args.base_lr * size
    warm = int(args.warmup_epochs * steps_per_epoch)
    # join_schedules rebases the second schedule's step count to the
    # boundary, so absolute-epoch decay points must subtract the warmup
    bounds = {int(e * steps_per_epoch) - warm: m
              for e, m in ((30, 0.1), (60, 0.1), (80, 0.1))
              if int(e * steps_per_epoch) > warm}
    return optax.join_schedules(
        [optax.linear_schedule(peak / size, peak, warm),
         optax.piecewise_constant_schedule(peak, bounds)],
        [warm])


def run(args) -> dict:
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.data.loader import ShardedLoader
    from horovod_tpu.models import MODELS
    from horovod_tpu.training import (
        init_train_state, make_train_step, shard_batch,
    )
    from horovod_tpu.utils import checkpoint as ckpt

    hvd.init(platform=args.platform)
    verbose = hvd.rank() == 0
    g = args.batch_size * hvd.size()

    data = None
    if args.train_dir:
        import glob

        files = sorted(glob.glob(os.path.join(args.train_dir, "*.npz")))
        assert files, f"no .npz shards under {args.train_dir}"
        xs, ys = zip(*((d["x"], d["y"]) for d in map(np.load, files)))
        data = (np.concatenate(xs).astype(np.float32),
                np.concatenate(ys).astype(np.int32))

    # LR boundaries are in real optimizer steps: with data, an epoch is
    # what the loader yields, not the synthetic-mode flag
    steps_per_epoch = (data[0].shape[0] // g if data is not None
                       else args.steps_per_epoch)

    model = MODELS[args.model](num_classes=args.num_classes,
                               dtype=jnp.bfloat16)
    sched = lr_schedule(args, hvd.size(), steps_per_epoch)
    opt = optax.chain(
        optax.add_decayed_weights(args.wd),
        optax.sgd(sched, momentum=args.momentum),
    )

    step = make_train_step(
        apply_fn=model.apply,
        loss_fn=lambda logits, y:
            optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean(),
        optimizer=opt,
        has_batch_stats=True,
        compression=(hvd.Compression.fp16 if args.fp16_allreduce
                     else hvd.Compression.none),
        hierarchical=args.hierarchical_allreduce,
        autotune=args.autotune or None,
    )

    state = init_train_state(
        model, opt, jnp.zeros((2, args.image_size, args.image_size, 3)),
        has_batch_stats=True)

    # resume: rank 0 scans the checkpoint dir, everyone agrees via
    # broadcast (reference: resume_from_epoch hvd.broadcast)
    start_epoch = 0
    have = ckpt.latest_step(args.checkpoint_dir) if verbose else None
    if hvd.process_size() > 1:
        from horovod_tpu import eager

        have = eager.broadcast_object(have)
    if have is not None:
        state = ckpt.restore_checkpoint(args.checkpoint_dir, state,
                                        step=have)
        start_epoch = have + 1
        if verbose:
            print(f"resumed from epoch {have}", flush=True)

    rng = np.random.default_rng(1)

    def epoch_batches(epoch: int):
        """Yield (x_sharded, y_sharded) global batches."""
        if data is not None:
            loader = ShardedLoader(*data, batch_size=args.batch_size,
                                   shuffle=True, seed=epoch,
                                   drop_remainder=True)
            for xb, yb, _active in loader:
                yield xb, yb
        else:
            for _ in range(args.steps_per_epoch):
                x = rng.uniform(
                    size=(g, args.image_size, args.image_size, 3)
                ).astype(np.float32)
                y = rng.integers(0, args.num_classes, size=(g,)
                                 ).astype(np.int32)
                yield shard_batch(x), shard_batch(y)

    last_loss = float("nan")
    for epoch in range(start_epoch, args.epochs):
        loss = None
        for x, y in epoch_batches(epoch):
            state, loss = step(state, x, y)
        if loss is None:
            raise ValueError(
                f"epoch {epoch} yielded no batches: need at least "
                f"{g} rows (batch_size x world size)")
        last_loss = float(np.asarray(loss))
        if verbose:
            print(f"epoch {epoch}: loss {last_loss:.4f}", flush=True)
        ckpt.save_checkpoint(args.checkpoint_dir, state, step=epoch)
    return {"last_loss": last_loss, "epochs_run": args.epochs - start_epoch}


if __name__ == "__main__":
    run(parse_args())
