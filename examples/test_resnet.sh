#!/bin/bash
# ResNet trace-collection sweep — analog of the reference's profiling
# sweep script (reference examples/test_resnet.sh: runs the synthetic
# benchmark under BYTEPS_TRACE_* so the byteprofile tracer captures a
# step window per rank).  Here the tracer is the built-in timeline:
# per-rank Chrome traces land in $TRACE_DIR/<rank>/comm.json.
set -e
cd "$(dirname "$0")/.."

export HVD_TIMELINE="${TRACE_DIR:-/tmp/hvd_traces/resnet}"
export HVD_TRACE_START_STEP="${HVD_TRACE_START_STEP:-10}"
export HVD_TRACE_END_STEP="${HVD_TRACE_END_STEP:-20}"
export HVD_TIMELINE_MARK_CYCLES=1

MODEL="${MODEL:-ResNet50}"
BATCH="${BATCH:-32}"

python examples/synthetic_benchmark.py \
    --model "$MODEL" \
    --batch-size "$BATCH" \
    --num-warmup-batches 5 --num-batches-per-iter 5 --num-iters 4 "$@"

echo "traces in $HVD_TIMELINE"
