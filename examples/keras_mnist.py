"""MNIST with the Keras-style callback API.

TPU-native analog of the reference Keras examples (reference
examples/keras_mnist.py, keras_mnist_advanced.py): the training loop is
driven by the callback set — root-rank weight broadcast on train begin,
cross-rank metric averaging each epoch, LR warmup over the first epochs,
LR schedule decay, rank-0-only checkpointing — exactly the reference's
callback stack (reference horovod/_keras/callbacks.py:21-60).

Run:  python examples/keras_mnist.py --epochs 3
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

import horovod_tpu as hvd
from examples.datasets import synthetic_mnist
from horovod_tpu.callbacks import (
    BroadcastGlobalVariablesCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
from horovod_tpu.data.loader import ShardedLoader
from horovod_tpu.training import init_train_state, make_train_step


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256)(x))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(10)(x)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="horovod_tpu Keras-style MNIST")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.0005)
    p.add_argument("--warmup-epochs", type=int, default=1)
    p.add_argument("--num-samples", type=int, default=2048)
    p.add_argument("--checkpoint-dir", type=str, default=None)
    return p.parse_args(argv)


def run(args) -> dict:
    hvd.init()
    x, y = synthetic_mnist(args.num_samples)

    model = MLP()
    warmup = LearningRateWarmupCallback(
        initial_lr=args.lr * hvd.size(),
        multiplier=1.0,
        warmup_epochs=args.warmup_epochs,
        steps_per_epoch=max(
            1, args.num_samples // (args.batch_size * hvd.size())),
    )
    opt = optax.adam(learning_rate=warmup.as_optax_schedule())

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    step = make_train_step(apply_fn=lambda vars_, bx, **kw: model.apply(
        vars_, bx), loss_fn=loss_fn, optimizer=opt)
    state = init_train_state(model, opt, jnp.zeros((1, 28, 28, 1)))

    callbacks = [
        BroadcastGlobalVariablesCallback(root_rank=0),
        MetricAverageCallback(),
    ]
    ckpt_dir = args.checkpoint_dir or tempfile.mkdtemp(prefix="hvd_mnist_")

    for cb in callbacks:
        state = cb.on_train_begin(state)

    loader = ShardedLoader(x, y, batch_size=args.batch_size, shuffle=True,
                           seed=3, drop_remainder=True)
    metrics = {}
    gstep = 0
    for epoch in range(args.epochs):
        for bx, by, _active in loader:
            state, loss = step(state, bx, by)
            for cb in callbacks:
                cb.on_batch_end(gstep, state)
            gstep += 1
        # report the lr actually driving the optimizer (the warmup
        # schedule is stepped per batch)
        metrics = {"loss": float(np.asarray(jax.device_get(loss))),
                   "lr": warmup.lr(gstep)}
        for cb in callbacks:
            metrics = cb.on_epoch_end(epoch, state, metrics)
        # rank-0-only checkpointing, as the reference examples gate
        # ModelCheckpoint on hvd.rank() == 0 (keras_mnist.py:77-79)
        if hvd.rank() == 0:
            path = os.path.join(ckpt_dir, f"checkpoint-{epoch}.npz")
            flat = jax.tree_util.tree_leaves(
                jax.device_get(state.params))
            np.savez(path, *[np.asarray(a) for a in flat])
        if hvd.rank() == 0:
            print(f"epoch {epoch}: {metrics}")
    return {"final_loss": metrics["loss"], "checkpoint_dir": ckpt_dir}


if __name__ == "__main__":
    run(parse_args())
