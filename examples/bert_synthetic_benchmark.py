"""BERT synthetic benchmark: masked-LM pretraining throughput.

The analog of the reference's BERT profiling target (reference
examples/test_bert.sh drives gluon-nlp BERT with synthetic data and the
byteprofile tracer), built TPU-native on the in-repo flax BertEncoder:

* masked-LM objective over synthetic token streams,
* data parallelism over the mesh via the fused gradient allreduce,
* optional sequence parallelism (``--seq-parallel ring|ulysses``) on a
  (dp, sp) factorized world, and
* optional Pallas flash-attention kernels (``--attn pallas``).

Prints img-style "sentences/sec" iteration lines like the synthetic
ResNet benchmark.

Run:  python examples/bert_synthetic_benchmark.py --model tiny
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from examples.datasets import synthetic_tokens
from horovod_tpu.models.bert import BertEncoder, bert_base, bert_tiny
from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.ops.fusion import allreduce_pytree
from horovod_tpu.parallel.ring_attention import (
    ring_attention, ulysses_attention,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="horovod_tpu BERT synthetic benchmark")
    p.add_argument("--model", choices=["tiny", "base"], default="base")
    p.add_argument("--batch-size", type=int, default=8,
                   help="per-rank sentences")
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--attn", choices=["xla", "pallas"], default="xla")
    p.add_argument("--seq-parallel", choices=["none", "ring", "ulysses"],
                   default="none")
    p.add_argument("--mask-prob", type=float, default=0.15)
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--dtype", choices=["bfloat16", "float32"],
                   default="bfloat16")
    p.add_argument("--adasum", action="store_true", default=False,
                   help="Adasum gradient reduction (BASELINE.json config "
                        "4: Adasum allreduce on BERT)")
    p.add_argument("--num-in-graph-steps", type=int, default=1,
                   help="optimizer steps compiled into one program "
                        "(lax.scan); amortizes host dispatch over the "
                        "tunnel, as the ResNet bench does")
    return p.parse_args(argv)


def _attention_fn(args):
    """Pick the attention implementation for the encoder hook."""
    if args.seq_parallel == "ring":
        return lambda q, k, v, mask: ring_attention(
            q, k, v, causal=False, impl=args.attn)
    if args.seq_parallel == "ulysses":
        return lambda q, k, v, mask: ulysses_attention(
            q, k, v, causal=False, impl=args.attn)
    if args.attn == "pallas":
        return lambda q, k, v, mask: flash_attention(q, k, v, causal=False)
    return None  # default dense path inside SelfAttention


def run(args) -> dict:
    hvd.init()
    dtype = jnp.dtype(args.dtype)
    factory = bert_tiny if args.model == "tiny" else bert_base
    model = factory(dtype=dtype, attention_fn=_attention_fn(args),
                    max_len=max(args.seq_len, 512))
    vocab = model.vocab_size

    tokens = synthetic_tokens(
        n=args.batch_size * hvd.size() * 4, seq_len=args.seq_len,
        vocab=vocab)
    rng = np.random.default_rng(5)
    mask = (rng.uniform(size=tokens.shape) < args.mask_prob)
    mask_id = vocab - 1
    inputs = np.where(mask, mask_id, tokens).astype(np.int32)

    opt = optax.adamw(1e-4)
    # init with a hook-free twin: the attention_fn (which may need the SPMD
    # mesh axis) doesn't change the parameter structure
    init_model = factory(dtype=dtype, max_len=max(args.seq_len, 512))
    variables = init_model.init(jax.random.PRNGKey(0), inputs[:1])
    params = variables["params"]
    opt_state = opt.init(params)

    # MLM head: tie to a fresh projection — predictions over the vocab
    head = jax.random.normal(jax.random.PRNGKey(1),
                             (model.hidden_dim, vocab), jnp.float32) * 0.02

    def loss_fn(params, head, ids_in, ids_tgt, mask):
        hidden = model.apply({"params": params}, ids_in)
        logits = hidden @ head
        raw = optax.softmax_cross_entropy_with_integer_labels(
            logits, ids_tgt)
        denom = jnp.maximum(mask.sum(), 1)
        return (raw * mask).sum() / denom

    # sequence dim sharded only under seq-parallel; batch dim under dp
    if args.seq_parallel == "none":
        data_spec = P(hvd.AXIS)       # batch sharded
    else:
        data_spec = P(None, hvd.AXIS)  # sequence sharded

    def one_step(params, opt_state, ids_in, ids_tgt, m):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, head, ids_in, ids_tgt, m)
        if args.adasum:
            from horovod_tpu.ops import collectives as _coll

            grads = jax.tree_util.tree_map(
                lambda g: _coll.allreduce(g, op=hvd.Adasum), grads)
        else:
            grads = allreduce_pytree(grads, op=hvd.Average)
        from horovod_tpu.ops import collectives
        loss = collectives.allreduce(loss, op=hvd.Average)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    k = max(args.num_in_graph_steps, 1)
    from horovod_tpu.training import scan_steps

    def step_of(carry, ids_in, ids_tgt, m):
        p, s = carry
        p, s, loss = one_step(p, s, ids_in, ids_tgt, m)
        return (p, s), loss

    scanned = scan_steps(step_of, k)

    @hvd.spmd(in_specs=(P(), P(), data_spec, data_spec, data_spec),
              out_specs=(P(), P(), P()),
              donate_argnums=(0, 1))
    def train_step(params, opt_state, ids_in, ids_tgt, m):
        (params, opt_state), loss = scanned(
            (params, opt_state), ids_in, ids_tgt, m)
        return params, opt_state, loss

    n = args.batch_size * hvd.size()
    ids_in = inputs[:n]
    ids_tgt = tokens[:n]
    m = mask[:n].astype(np.float32)

    if hvd.rank() == 0:
        print(f"Model: bert-{args.model}  seq {args.seq_len}  "
              f"attn {args.attn}  sp {args.seq_parallel}")

    for _ in range(args.num_warmup_batches):
        params, opt_state, loss = train_step(params, opt_state, ids_in,
                                             ids_tgt, m)
    float(np.asarray(jax.device_get(loss)))

    sent_secs = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            params, opt_state, loss = train_step(params, opt_state, ids_in,
                                                 ids_tgt, m)
        float(np.asarray(jax.device_get(loss)))
        dt = time.perf_counter() - t0
        sps = n * k * args.num_batches_per_iter / dt
        sent_secs.append(sps)
        if hvd.rank() == 0:
            print(f"Iter: sentences/sec total: {sps:.1f}")

    mean = float(np.mean(sent_secs))
    from horovod_tpu.utils.flops import param_count, transformer_mfu

    # the MLM head is a separate array outside `params` but its matmuls
    # (fwd + bwd) run every step — count it or MFU undercounts ~10%
    mfu = transformer_mfu(
        mean / hvd.size(), param_count(params) + int(np.prod(head.shape)),
        model.num_layers, model.hidden_dim, args.seq_len,
    )
    if hvd.rank() == 0:
        print(f"sentences/sec per chip: {mean / hvd.size():.1f}  "
              f"(analytic MFU {mfu:.1%} of v5e bf16 peak)")
    return {"sent_sec_total": mean,
            "sent_sec_per_chip": mean / hvd.size(),
            "mfu": mfu,
            "final_loss": float(np.asarray(jax.device_get(loss)))}


if __name__ == "__main__":
    run(parse_args())
