"""PyTorch synthetic benchmark through the torch binding.

Mirror of the reference harness (reference
examples/pytorch_synthetic_benchmark.py: hvd.init → model → wrap
optimizer in hvd.DistributedOptimizer with named_parameters +
compression → broadcast parameters/optimizer state → timed iters).
Defaults match the reference (``--model resnet50 --batch-size 32``,
BASELINE.json config 3); the reference pulls models from torchvision,
which is absent here, so ResNet-50/18 are self-contained plain-torch
implementations (``smallconv`` remains for smoke tests).  Gradients
cross processes on the framework's host data plane — the ~100 MB/step
ResNet-50 gradient volume rides the peer ring (csrc/ring.cc); launch
with ``tpurun -np 2`` for the real multi-process path, or
``scripts/host_plane_bench.py`` for the measured scaling artifact.

Run (full, reference config — ResNet-50 is minutes/iter on CPU torch):
    python examples/pytorch_synthetic_benchmark.py --num-iters 3
Smoke (seconds):
    python examples/pytorch_synthetic_benchmark.py --model smallconv \
        --batch-size 8 --image-size 32 --num-classes 10 --num-iters 1
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="horovod_tpu PyTorch Synthetic Benchmark",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("--model", type=str, default="resnet50",
                        choices=["smallconv", "resnet18", "resnet50"])
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--fp16-allreduce", action="store_true",
                        default=False)
    parser.add_argument("--num-warmup-batches", type=int, default=2)
    parser.add_argument("--num-batches-per-iter", type=int, default=3)
    parser.add_argument("--num-iters", type=int, default=3)
    return parser.parse_args(argv)


def _resnet(layers, num_classes: int, bottleneck: bool):
    """Plain-torch ResNet (the reference uses torchvision.models; the
    architecture is the standard He et al. v1.5 layout)."""
    import torch.nn as nn

    class BasicBlock(nn.Module):
        expansion = 1

        def __init__(self, cin, planes, stride=1):
            super().__init__()
            self.c1 = nn.Conv2d(cin, planes, 3, stride, 1, bias=False)
            self.b1 = nn.BatchNorm2d(planes)
            self.c2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
            self.b2 = nn.BatchNorm2d(planes)
            cout = planes * self.expansion
            self.proj = (
                nn.Sequential(nn.Conv2d(cin, cout, 1, stride, bias=False),
                              nn.BatchNorm2d(cout))
                if (stride != 1 or cin != cout) else nn.Identity()
            )
            self.relu = nn.ReLU(inplace=True)

        def forward(self, x):
            y = self.relu(self.b1(self.c1(x)))
            y = self.b2(self.c2(y))
            return self.relu(y + self.proj(x))

    class Bottleneck(nn.Module):
        expansion = 4

        def __init__(self, cin, planes, stride=1):
            super().__init__()
            cout = planes * self.expansion
            self.c1 = nn.Conv2d(cin, planes, 1, bias=False)
            self.b1 = nn.BatchNorm2d(planes)
            self.c2 = nn.Conv2d(planes, planes, 3, stride, 1, bias=False)
            self.b2 = nn.BatchNorm2d(planes)
            self.c3 = nn.Conv2d(planes, cout, 1, bias=False)
            self.b3 = nn.BatchNorm2d(cout)
            self.proj = (
                nn.Sequential(nn.Conv2d(cin, cout, 1, stride, bias=False),
                              nn.BatchNorm2d(cout))
                if (stride != 1 or cin != cout) else nn.Identity()
            )
            self.relu = nn.ReLU(inplace=True)

        def forward(self, x):
            y = self.relu(self.b1(self.c1(x)))
            y = self.relu(self.b2(self.c2(y)))
            y = self.b3(self.c3(y))
            return self.relu(y + self.proj(x))

    block = Bottleneck if bottleneck else BasicBlock
    stages = []
    cin = 64
    for i, n in enumerate(layers):
        planes = 64 * 2 ** i
        for j in range(n):
            stages.append(block(cin, planes, 2 if i > 0 and j == 0 else 1))
            cin = planes * block.expansion
    return nn.Sequential(
        nn.Conv2d(3, 64, 7, 2, 3, bias=False), nn.BatchNorm2d(64),
        nn.ReLU(inplace=True), nn.MaxPool2d(3, 2, 1),
        *stages,
        nn.AdaptiveAvgPool2d(1), nn.Flatten(),
        nn.Linear(cin, num_classes),
    )


def _make_model(name: str, num_classes: int):
    import torch.nn as nn

    if name == "smallconv":
        return nn.Sequential(
            nn.Conv2d(3, 16, 3, padding=1), nn.ReLU(),
            nn.MaxPool2d(2),
            nn.Conv2d(16, 32, 3, padding=1), nn.ReLU(),
            nn.AdaptiveAvgPool2d(1), nn.Flatten(),
            nn.Linear(32, num_classes),
        )
    if name == "resnet18":
        return _resnet([2, 2, 2, 2], num_classes, bottleneck=False)
    return _resnet([3, 4, 6, 3], num_classes, bottleneck=True)


def run(args) -> dict:
    import torch
    import torch.nn.functional as F

    import horovod_tpu.torch as hvd

    hvd.init()
    torch.manual_seed(42)

    model = _make_model(args.model, args.num_classes)
    opt = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size(),
                          momentum=0.9)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        compression=hvd.Compression.fp16 if args.fp16_allreduce
        else hvd.Compression.none,
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
    target = torch.randint(0, args.num_classes, (args.batch_size,))

    def benchmark_step():
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()
        return float(loss.detach())

    from horovod_tpu import core

    def log(s):
        if core.process_rank() == 0:
            print(s, flush=True)

    log(f"Model: {args.model}  batch {args.batch_size}  "
        f"procs {core.process_size()}")
    for _ in range(args.num_warmup_batches):
        loss = benchmark_step()

    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            loss = benchmark_step()
        dt = time.perf_counter() - t0
        img_sec = args.batch_size * args.num_batches_per_iter / dt
        log(f"Iter: img/sec per proc: {img_sec:.1f}")
        img_secs.append(img_sec)

    mean = float(np.mean(img_secs))
    log(f"Img/sec per proc: {mean:.1f}")
    return {"img_sec_per_proc": mean, "final_loss": loss}


if __name__ == "__main__":
    run(parse_args())
