"""Torch estimator trained from a Spark DataFrame (reference
examples/pytorch_spark_mnist.py: build a Spark DataFrame of
feature-vector/label rows, hand it to TorchEstimator with a Store, call
``fit(df)``, predict with the returned model).

TPU-era shape: ``horovod_tpu.estimator.TorchEstimator.fit(df)`` ingests
the DataFrame through the Store (schema validation + column->tensor
compilation, estimator/dataframe.py) and trains through the torch
binding.  With pyspark installed the DataFrame comes from a real
SparkSession; without it (this image) a minimal in-file stand-in with
the same ``.columns``/``.collect()`` surface carries the same rows —
the estimator code path is identical either way.

Run:  python examples/pytorch_spark_mnist.py [--epochs 4]
"""

from __future__ import annotations

import argparse
import sys
import tempfile

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def make_dataframe(n: int = 512, seed: int = 0):
    """An MNIST-like synthetic DataFrame: 64-dim feature vectors with a
    10-class label column.  Class centers are seed-independent so a
    different ``seed`` yields FRESH samples of the same distribution."""
    rng = np.random.default_rng(seed)
    centers = np.random.default_rng(1234).normal(size=(10, 64)) * 2.0
    rows = []
    for _ in range(n):
        label = int(rng.integers(0, 10))
        feat = centers[label] + rng.normal(size=64) * 0.5
        rows.append({"features": feat.tolist(), "label": label})

    try:
        from pyspark.sql import SparkSession

        spark = (SparkSession.builder.appName("hvd_tpu_mnist")
                 .master("local[2]").getOrCreate())
        return spark.createDataFrame(rows)
    except ImportError:
        class _LocalRow(dict):
            def asDict(self):
                return dict(self)

        class _LocalDataFrame:
            """pyspark-shaped holder (columns + collect) so the
            estimator's duck-typed fit(df) path runs without Spark."""

            def __init__(self, rows):
                self._rows = [_LocalRow(r) for r in rows]
                self.columns = list(rows[0])

            def collect(self):
                return list(self._rows)

        return _LocalDataFrame(rows)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--work-dir", default=None,
                        help="Store prefix (default: a temp dir)")
    args = parser.parse_args()

    import torch

    import horovod_tpu as hvd
    from horovod_tpu.estimator import Store, TorchEstimator

    hvd.init()
    work_dir = args.work_dir or tempfile.mkdtemp(prefix="hvd_spark_mnist_")
    store = Store.create(work_dir)

    torch.manual_seed(0)
    model = torch.nn.Sequential(
        torch.nn.Linear(64, 128), torch.nn.ReLU(),
        torch.nn.Linear(128, 10),
    )
    est = TorchEstimator(
        model=model,
        optimizer_factory=lambda ps: torch.optim.Adam(ps, lr=1e-3),
        loss=lambda out, y: torch.nn.functional.cross_entropy(
            out, y.reshape(-1).long()),
        store=store,
        batch_size=args.batch_size,
        epochs=args.epochs,
        feature_cols=["features"],
        label_cols=["label"],
        validation=0.15,
        run_id="spark_mnist",
        verbose=1,
    )
    df = make_dataframe()
    fitted = est.fit(df)

    if hvd.process_rank() == 0:
        hist = fitted.history
        print(f"train loss {hist[0]['loss']:.3f} -> {hist[-1]['loss']:.3f}"
              f"  val loss {hist[-1]['val_loss']:.3f}")
        # accuracy on fresh samples from the same distribution
        probe = make_dataframe(n=128, seed=7)
        rows = [r.asDict() if hasattr(r, "asDict") else dict(r)
                for r in probe.collect()]
        x = np.asarray([r["features"] for r in rows], np.float32)
        y = np.asarray([r["label"] for r in rows])
        pred = fitted.predict(x).argmax(axis=1)
        print(f"holdout accuracy: {(pred == y).mean():.1%}")
        print(f"checkpoint + materialized data under {work_dir}")


if __name__ == "__main__":
    main()
