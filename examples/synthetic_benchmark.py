"""Synthetic benchmark — the framework's headline benchmark harness.

Feature-for-feature port of the reference harness CLI (reference
examples/tensorflow2_synthetic_benchmark.py: --model/--batch-size/
--fp16-allreduce/--num-warmup-batches/--num-batches-per-iter/--num-iters),
re-done TPU-native: the model is flax ResNet, the step is a compiled SPMD
program over the mesh, gradients ride fused psum over ICI.

Run:  python examples/synthetic_benchmark.py --batch-size 32
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MODELS
from horovod_tpu.training import (
    TrainState, init_train_state, make_train_step, shard_batch,
)


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="horovod_tpu Synthetic Benchmark",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("--fp16-allreduce", action="store_true", default=False,
                        help="use bf16 compression during allreduce")
    parser.add_argument("--compression", type=str, default=None,
                        choices=["none", "bf16", "fp16", "int8", "fp8",
                                 "fp8_e5m2"],
                        help="gradient wire format (quantized formats "
                             "carry the error-feedback residual; "
                             "default: the HVD_COMPRESSION env knob)")
    parser.add_argument("--model", type=str, default="ResNet50",
                        help="model to benchmark")
    parser.add_argument("--batch-size", type=int, default=32,
                        help="input batch size per rank")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-warmup-batches", type=int, default=10,
                        help="number of warm-up batches not benchmarked")
    parser.add_argument("--num-batches-per-iter", type=int, default=10,
                        help="number of batches per benchmark iteration")
    parser.add_argument("--num-in-graph-steps", type=int, default=1,
                        help="optimizer steps compiled into one program "
                             "(lax.scan); amortizes host dispatch")
    parser.add_argument("--num-iters", type=int, default=10,
                        help="number of benchmark iterations")
    parser.add_argument("--adasum", action="store_true", default=False,
                        help="use Adasum reduction")
    parser.add_argument("--hierarchical", action="store_true", default=False,
                        help="use two-level (ICI/DCN-style) allreduce")
    parser.add_argument("--platform", type=str, default=None,
                        help="jax platform override (tpu/cpu)")
    parser.add_argument("--autotune", action="store_true", default=False,
                        help="live-tune fusion threshold / hierarchical "
                             "allreduce while benchmarking (reference "
                             "horovodrun --autotune)")
    parser.add_argument("--autotune-log-file", type=str, default=None,
                        help="CSV trace of autotune samples")
    parser.add_argument("--dtype", type=str, default="bfloat16",
                        choices=["bfloat16", "float32"],
                        help="model compute dtype (params stay float32)")
    parser.add_argument("--fused-optimizer", action="store_true",
                        default=False,
                        help="flat fused update kernel instead of the "
                             "per-leaf optax traversal "
                             "(optim/fused_update.py; bit-equal math)")
    parser.add_argument("--loss-fetch-steps", type=int, default=None,
                        help="trailing async loss-fetch cadence "
                             "(default: the HVD_LOSS_FETCH_STEPS knob)")
    return parser.parse_args(argv)


def log(s, nl=True):
    if hvd.rank() != 0:
        return
    print(s, end="\n" if nl else "", flush=True)


def run(args) -> dict:
    hvd.init(platform=args.platform)

    model = MODELS[args.model](
        num_classes=args.num_classes, dtype=jnp.dtype(args.dtype)
    )
    if args.fused_optimizer:
        from horovod_tpu.optim.fused_update import fused_sgd

        opt = fused_sgd(0.01, momentum=0.9)
    else:
        opt = optax.sgd(0.01, momentum=0.9)

    global_batch = args.batch_size * hvd.size()
    rng = np.random.default_rng(42)
    data = rng.uniform(
        size=(global_batch, args.image_size, args.image_size, 3)
    ).astype(np.float32)
    target = rng.integers(0, args.num_classes, size=(global_batch,)).astype(
        np.int32
    )

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    if args.compression:
        from horovod_tpu.ops.compression import Compression as _C
        from horovod_tpu.utils import env as _env

        compression = _C.lookup(
            args.compression,
            error_feedback=_env.get_bool(
                _env.HVD_COMPRESSION_ERROR_FEEDBACK, True))
    elif args.fp16_allreduce:
        compression = hvd.Compression.fp16
    else:
        compression = None   # make_train_step resolves HVD_COMPRESSION

    step = make_train_step(
        apply_fn=model.apply,
        loss_fn=loss_fn,
        optimizer=opt,
        op=hvd.Adasum if args.adasum else hvd.Average,
        compression=compression,
        has_batch_stats=True,
        hierarchical=args.hierarchical,
        autotune=args.autotune or None,
        autotune_log_file=args.autotune_log_file,
        in_graph_steps=args.num_in_graph_steps,
        fused_optimizer=args.fused_optimizer or None,
        loss_fetch_steps=args.loss_fetch_steps,
    )

    from horovod_tpu.ops.compression import ErrorFeedback as _EF
    from horovod_tpu.ops.compression import from_env as _comp_from_env

    eff = compression if compression is not None else _comp_from_env()
    state = init_train_state(
        model, opt, jnp.zeros((2, args.image_size, args.image_size, 3)),
        has_batch_stats=True,
        compression=eff if isinstance(eff, _EF) else None,
    )
    x = shard_batch(data)
    y = shard_batch(target)

    log(f"Model: {args.model}")
    log(f"Batch size: {args.batch_size} (global {global_batch})")
    log(f"Number of chips: {hvd.size()}")

    # NOTE: sync via device_get of the chained loss, not block_until_ready —
    # on tunneled/remote platforms block_until_ready can return before remote
    # execution finishes, which silently inflates throughput. Fetching the
    # scalar forces the whole sequential step chain to complete.
    log("Running warmup...")
    for _ in range(max(args.num_warmup_batches, 1)):
        state, loss = step(state, x, y)
    float(np.asarray(jax.device_get(loss)))

    log("Running benchmark...")
    imgs_per_call = (args.batch_size * hvd.size()
                     * max(args.num_in_graph_steps, 1))
    img_secs = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            state, loss = step(state, x, y)
        float(np.asarray(jax.device_get(loss)))
        dt = time.perf_counter() - t0
        img_sec = imgs_per_call * args.num_batches_per_iter / dt
        log(f"Iter: Img/sec total: {img_sec:.1f}")
        img_secs.append(img_sec)

    pm = getattr(step, "parameter_manager", None)
    if pm is not None:
        log(f"Autotune: frozen={pm.frozen} "
            f"threshold={pm.current.fusion_threshold_bytes} "
            f"hierarchical={pm.current.hierarchical_allreduce}")

    img_sec_mean = float(np.mean(img_secs))
    img_sec_conf = float(1.96 * np.std(img_secs))
    log(f"Img/sec per chip: {img_sec_mean / hvd.size():.1f}")
    log(f"Total img/sec on {hvd.size()} chip(s): "
        f"{img_sec_mean:.1f} +-{img_sec_conf:.1f}")
    return {
        "img_sec_total": img_sec_mean,
        "img_sec_per_chip": img_sec_mean / hvd.size(),
        "conf": img_sec_conf,
        "size": hvd.size(),
        "final_loss": float(np.asarray(jax.device_get(loss))),
    }


if __name__ == "__main__":
    run(parse_args())
