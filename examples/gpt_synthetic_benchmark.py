"""GPT (decoder LM) synthetic benchmark — the long-context causal path.

Same harness shape as bert_synthetic_benchmark (reference
examples/tensorflow2_synthetic_benchmark.py CLI), on the decoder family:
causal flash attention by default, ring/Ulysses sequence parallelism via
``--seq-parallel``.

Run:  python examples/gpt_synthetic_benchmark.py --seq-len 2048
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models.gpt import GPT, gpt2_small, gpt_tiny, next_token_loss
from horovod_tpu.parallel.ring_attention import (
    ring_attention, ulysses_attention,
)
from horovod_tpu.training import init_train_state, make_train_step, shard_batch


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="horovod_tpu GPT synthetic benchmark",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("--model", choices=["tiny", "gpt2"], default="gpt2")
    parser.add_argument("--batch-size", type=int, default=4,
                        help="per-rank sequences")
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--attn", choices=["xla", "pallas"],
                        default="pallas")
    parser.add_argument("--seq-parallel", choices=["none", "ring", "ulysses"],
                        default="none")
    parser.add_argument("--num-warmup-batches", type=int, default=2)
    parser.add_argument("--num-batches-per-iter", type=int, default=5)
    parser.add_argument("--num-iters", type=int, default=3)
    parser.add_argument("--dtype", choices=["bfloat16", "float32"],
                        default="bfloat16")
    parser.add_argument("--num-in-graph-steps", type=int, default=1,
                        help="optimizer steps compiled into one program "
                             "(lax.scan); amortizes host dispatch")
    return parser.parse_args(argv)


def _attention_fn(args):
    if args.seq_parallel == "ring":
        return lambda q, k, v, m: ring_attention(
            q, k, v, causal=True, impl=args.attn)
    if args.seq_parallel == "ulysses":
        return lambda q, k, v, m: ulysses_attention(
            q, k, v, causal=True, impl=args.attn)
    if args.attn == "pallas":
        return None  # model default = causal flash
    from horovod_tpu.ops.flash_attention import softmax_attention

    return lambda q, k, v, m: softmax_attention(q, k, v, causal=True)


def run(args) -> dict:
    hvd.init()
    dtype = jnp.dtype(args.dtype)
    factory = gpt2_small if args.model == "gpt2" else gpt_tiny
    model = factory(dtype=dtype, attention_fn=_attention_fn(args),
                    max_len=max(args.seq_len, 1024))
    opt = optax.adam(1e-4)

    rng = np.random.default_rng(0)
    if args.seq_parallel == "none":
        step = make_train_step(
            apply_fn=lambda v, x, train=True: model.apply(v, x),
            loss_fn=next_token_loss, optimizer=opt,
            in_graph_steps=args.num_in_graph_steps,
        )
        # init with the hook-free twin (the attention_fn may need the mesh)
        init_twin = factory(dtype=dtype, max_len=max(args.seq_len, 1024))
        state = init_train_state(
            init_twin, opt, jnp.zeros((2, args.seq_len), jnp.int32),
        )
        ids = shard_batch(rng.integers(
            0, 1000, size=(args.batch_size * hvd.size(), args.seq_len)
        ).astype(np.int32))
        n_batches = args.batch_size * hvd.size()
    else:
        # sequence parallelism: the SEQUENCE dim is sharded across ranks
        # (batch replicated per step); positions are globalized via
        # seq_offset; the shifted LM loss is computed within each shard
        # (the n-1 shard-boundary predictions are dropped — negligible
        # at benchmark lengths) and averaged over ranks
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.ops import collectives
        from horovod_tpu.ops.fusion import allreduce_pytree
        from horovod_tpu.training import TrainState

        init_twin = factory(dtype=dtype, max_len=max(args.seq_len, 1024))
        state = init_train_state(
            init_twin, opt, jnp.zeros((2, args.seq_len), jnp.int32),
        )
        local_seq = args.seq_len // hvd.size()

        def per_rank(state, ids_shard):
            off = hvd.rank() * local_seq

            def loss_of(params):
                logits = model.apply(
                    {"params": params, **state.model_state},
                    ids_shard, seq_offset=off,
                )
                return next_token_loss(logits, ids_shard)

            loss, grads = jax.value_and_grad(loss_of)(state.params)
            grads = allreduce_pytree(grads, op=hvd.Average)
            loss = collectives.allreduce(loss, op=hvd.Average)
            updates, opt_state = opt.update(
                grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return TrainState(params, opt_state, state.model_state,
                              state.step + 1), loss

        state_spec = TrainState(params=P(), opt_state=P(),
                                model_state=P(), step=P())
        step = hvd.spmd(
            per_rank, in_specs=(state_spec, P(None, hvd.AXIS)),
            out_specs=(state_spec, P()), donate_argnums=(0,),
        )
        from horovod_tpu import core
        from jax.sharding import NamedSharding

        ids = jax.device_put(
            rng.integers(0, 1000, size=(args.batch_size, args.seq_len)
                         ).astype(np.int32),
            NamedSharding(core.mesh(), P(None, hvd.AXIS)),
        )
        n_batches = args.batch_size

    def log(s):
        if hvd.rank() == 0:
            print(s, flush=True)

    log(f"Model: gpt-{args.model}  seq {args.seq_len}  attn {args.attn}  "
        f"sp {args.seq_parallel}")
    call = ((lambda st: step(st, ids, ids)) if args.seq_parallel == "none"
            else (lambda st: step(st, ids)))
    for _ in range(max(args.num_warmup_batches, 1)):
        state, loss = call(state)
    float(np.asarray(jax.device_get(loss)))

    rates = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            state, loss = call(state)
        float(np.asarray(jax.device_get(loss)))
        dt = time.perf_counter() - t0
        # sp mode runs its own single-step program; in-graph scan applies
        # to the data-parallel make_train_step path only
        k = (max(args.num_in_graph_steps, 1)
             if args.seq_parallel == "none" else 1)
        rate = n_batches * k * args.num_batches_per_iter / dt
        log(f"Iter: sequences/sec total: {rate:.1f}")
        rates.append(rate)

    mean = float(np.mean(rates))
    # in both modes the whole mesh jointly produced the counted sequences
    per_chip = mean / hvd.size()
    from horovod_tpu.utils.flops import param_count, transformer_mfu

    mfu = transformer_mfu(
        per_chip, param_count(state.params), model.num_layers,
        model.hidden_dim, args.seq_len, causal=True,
    )
    log(f"sequences/sec per chip: {per_chip:.1f}  "
        f"(analytic MFU {mfu:.1%} of v5e bf16 peak)")
    return {"seq_sec_per_chip": per_chip,
            "mfu": mfu,
            "final_loss": float(np.asarray(jax.device_get(loss)))}


if __name__ == "__main__":
    run(parse_args())
