"""Dense-MLP synthetic benchmark — the "dense" profiling target.

Analog of the reference's dense sweep target (reference
examples/mxnet_dense.py + test_dense.sh: a stack of fully-connected
layers used to stress pure-allreduce communication patterns under the
byteprofile tracer).  Gradient size dominates compute here, so this is
the benchmark that exercises the fusion planner and (optionally) the
timeline — set ``HVD_TIMELINE=<dir>`` to capture per-rank traces while
it runs.

Run:  python examples/mlp_dense_benchmark.py --hidden 4096 --layers 8
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models.mlp import MLP
from horovod_tpu.training import (
    init_train_state, make_train_step, shard_batch,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="horovod_tpu dense benchmark")
    p.add_argument("--hidden", type=int, default=4096)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--input-dim", type=int, default=1024)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--num-warmup-batches", type=int, default=5)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=5)
    return p.parse_args(argv)


def run(args) -> dict:
    hvd.init()

    model = MLP(features=[args.hidden] * args.layers + [args.num_classes])
    opt = optax.sgd(0.01)

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    step = make_train_step(
        apply_fn=lambda vars_, bx, **kw: model.apply(vars_, bx),
        loss_fn=loss_fn, optimizer=opt,
        compression=hvd.Compression.fp16 if args.fp16_allreduce
        else hvd.Compression.none,
    )
    state = init_train_state(model, opt, jnp.zeros((2, args.input_dim)))

    rng = np.random.default_rng(0)
    n = args.batch_size * hvd.size()
    bx = shard_batch(rng.normal(size=(n, args.input_dim)).astype(np.float32))
    by = shard_batch(rng.integers(0, args.num_classes, size=(n,))
                     .astype(np.int32))

    param_bytes = sum(p.size * p.dtype.itemsize
                      for p in jax.tree_util.tree_leaves(state.params))
    if hvd.rank() == 0:
        print(f"Dense model: {args.layers}x{args.hidden}, "
              f"{param_bytes / 1e6:.1f} MB of gradients per step")

    for _ in range(args.num_warmup_batches):
        state, loss = step(state, bx, by)
    float(np.asarray(jax.device_get(loss)))

    rates = []
    for _ in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            state, loss = step(state, bx, by)
        float(np.asarray(jax.device_get(loss)))
        dt = time.perf_counter() - t0
        steps_sec = args.num_batches_per_iter / dt
        # the interesting number for a dense stack: allreduced bytes/sec
        gbps = param_bytes * steps_sec / 1e9
        rates.append(gbps)
        if hvd.rank() == 0:
            print(f"Iter: {steps_sec:.2f} steps/sec, "
                  f"{gbps:.2f} GB/s gradient traffic")

    return {"grad_gbytes_sec": float(np.mean(rates)),
            "final_loss": float(np.asarray(jax.device_get(loss)))}


if __name__ == "__main__":
    run(parse_args())
