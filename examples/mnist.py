"""MNIST with the core JAX API — the framework's canonical example.

TPU-native re-design of the reference's flagship example (reference
examples/tensorflow2_mnist.py): same training recipe — per-rank sharded
dataset, ``DistributedGradientTape``-style averaged gradients, scaled
learning rate, root-rank state broadcast at step 0, rank-0 checkpointing —
expressed as one compiled SPMD step over the mesh instead of per-process
graph ops.

Run:  python examples/mnist.py --epochs 2
      bin/tpurun -np 8 python examples/mnist.py   (multi-host)
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from examples.datasets import synthetic_mnist
from horovod_tpu.data.loader import ShardedLoader


class ConvNet(nn.Module):
    """The reference example's small conv net (reference
    examples/tensorflow2_mnist.py:40-50), flax edition."""

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(10)(x)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="horovod_tpu MNIST")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-rank batch size")
    p.add_argument("--lr", type=float, default=0.0001,
                   help="base lr; effective lr is this x world size")
    p.add_argument("--num-samples", type=int, default=4096)
    return p.parse_args(argv)


def run(args) -> dict:
    hvd.init()

    x, y = synthetic_mnist(args.num_samples)
    model = ConvNet()
    # scale lr by world size, as the reference does (tensorflow2_mnist.py:57)
    opt = optax.adam(args.lr * hvd.size())

    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    opt_state = opt.init(params)
    # root-rank broadcast before training (reference
    # tensorflow2_mnist.py:73-79 BroadcastGlobalVariablesHook semantics)
    params = hvd.broadcast_parameters(params)
    opt_state = hvd.broadcast_optimizer_state(opt_state)

    def loss_fn(params, bx, by):
        logits = model.apply(params, bx)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, by
        ).mean()

    @hvd.spmd(in_specs=(P(), P(), P(hvd.AXIS), P(hvd.AXIS)),
              out_specs=(P(), P(), P()))
    def train_step(params, opt_state, bx, by):
        tape = hvd.DistributedGradientTape(
            jax.value_and_grad(loss_fn), op=hvd.Average
        )
        loss, grads = tape.gradient(params, bx, by)
        from horovod_tpu.ops import collectives
        loss = collectives.allreduce(loss, op=hvd.Average)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loader = ShardedLoader(x, y, batch_size=args.batch_size,
                           shuffle=True, seed=7, drop_remainder=True)
    losses = []
    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        for step, (bx, by, _active) in enumerate(loader):
            params, opt_state, loss = train_step(params, opt_state, bx, by)
            if step % 10 == 0 and hvd.rank() == 0:
                print(f"epoch {epoch} step {step} "
                      f"loss {float(np.asarray(jax.device_get(loss))):.4f}")
        losses.append(float(np.asarray(jax.device_get(loss))))
        if hvd.rank() == 0:
            print(f"epoch {epoch} done in {time.perf_counter() - t0:.1f}s")
    return {"final_loss": losses[-1], "losses": losses}


if __name__ == "__main__":
    run(parse_args())
