#!/bin/bash
# Dense-stack trace-collection sweep — analog of the reference's
# examples/test_dense.sh (mxnet_dense.py under the byteprofile tracer):
# an allreduce-dominated workload for profiling the communication plane,
# swept over gradient compression.
set -e
cd "$(dirname "$0")/.."

export HVD_TIMELINE="${TRACE_DIR:-/tmp/hvd_traces/dense}"
export HVD_TRACE_START_STEP="${HVD_TRACE_START_STEP:-5}"
export HVD_TRACE_END_STEP="${HVD_TRACE_END_STEP:-25}"

HIDDEN="${HIDDEN:-4096}"
LAYERS="${LAYERS:-8}"

for COMPRESS in "" "--fp16-allreduce"; do
    echo "=== dense ${HIDDEN}x${LAYERS} ${COMPRESS:-fp32} ==="
    python examples/mlp_dense_benchmark.py \
        --hidden "$HIDDEN" --layers "$LAYERS" $COMPRESS "$@"
done

echo "traces in $HVD_TIMELINE"
