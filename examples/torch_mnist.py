"""MNIST through the PyTorch API surface.

Mirror of the reference's pytorch_mnist.py recipe on the
``horovod_tpu.torch`` shim: ``DistributedOptimizer`` wrapping a torch
optimizer (async allreduce semantics + ``synchronize``), parameter and
optimizer-state broadcast from root, metric averaging via the eager
allreduce (reference examples/pytorch_mnist.py:65-120).

Note: torch in this image is CPU-only; the point of this example is API
parity for users migrating torch scripts — the compute path for TPU
training is the JAX API (examples/mnist.py).

Run:  python examples/torch_mnist.py --epochs 1
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu as hvd
import horovod_tpu.torch as hvd_torch
from examples.datasets import synthetic_mnist


class Net(nn.Module):
    """The reference example's network (pytorch_mnist.py:28-47), minus
    dropout for determinism."""

    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = x.view(-1, 784)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="horovod_tpu torch MNIST")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--num-samples", type=int, default=1024)
    return p.parse_args(argv)


def run(args) -> dict:
    hvd.init()
    torch.manual_seed(42)

    x, y = synthetic_mnist(args.num_samples)
    # per-rank shard, as the reference uses DistributedSampler
    # (pytorch_mnist.py:100-104)
    shard = slice(hvd_torch.rank(), None, hvd_torch.size())
    xs = torch.from_numpy(x[shard]).float()
    ys = torch.from_numpy(y[shard]).long()

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.lr * hvd_torch.size(),
                                momentum=0.5)
    # root-rank sync of weights and optimizer state
    # (pytorch_mnist.py:117-120)
    hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd_torch.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd_torch.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    losses = []
    for epoch in range(args.epochs):
        model.train()
        for i in range(0, len(xs) - args.batch_size + 1, args.batch_size):
            bx, by = xs[i:i + args.batch_size], ys[i:i + args.batch_size]
            optimizer.zero_grad()
            loss = F.nll_loss(model(bx), by)
            loss.backward()
            optimizer.step()
        # averaged epoch metric, as in the reference's metric_average
        # (pytorch_mnist.py:122-127)
        avg = hvd_torch.allreduce(loss.detach(), name="epoch_loss")
        losses.append(float(avg))
        if hvd_torch.rank() == 0:
            print(f"epoch {epoch} loss {losses[-1]:.4f}")
    return {"final_loss": losses[-1]}


if __name__ == "__main__":
    run(parse_args())
