"""MNIST through the Estimator/Store layer.

Analog of the reference's Spark estimator examples (reference
examples/keras_spark_mnist.py, pytorch_spark_mnist.py): a high-level
``Estimator.fit(x, y)`` with a ``Store`` for checkpoints, then
``EstimatorModel.predict`` — no Spark cluster, the mesh is the worker
pool (SURVEY §2.5 → the estimator layer keeps the Store abstraction).

Run:  python examples/estimator_mnist.py
"""

from __future__ import annotations

import argparse
import sys
import tempfile

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import optax
from flax import linen as nn

import horovod_tpu as hvd
from examples.datasets import synthetic_mnist
from horovod_tpu.callbacks import BroadcastGlobalVariablesCallback
from horovod_tpu.estimator import Estimator, LocalStore


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(10)(x)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="horovod_tpu Estimator MNIST")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-samples", type=int, default=1024)
    p.add_argument("--work-dir", type=str, default=None)
    return p.parse_args(argv)


def run(args) -> dict:
    hvd.init()
    x, y = synthetic_mnist(args.num_samples)

    store = LocalStore(args.work_dir or tempfile.mkdtemp(prefix="hvd_est_"))

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()

    est = Estimator(
        model=MLP(),
        optimizer=optax.adam(1e-3),
        loss=loss_fn,
        store=store,
        batch_size=args.batch_size,
        epochs=args.epochs,
        callbacks=[BroadcastGlobalVariablesCallback()],
        run_id="estimator_mnist",
    )
    trained = est.fit(x, y)

    preds = trained.predict(x[:256])
    acc = float((np.argmax(preds, axis=1) == y[:256]).mean())
    if hvd.rank() == 0:
        print(f"train accuracy (first 256): {acc:.3f}")
    return {"accuracy": acc, "store": store}


if __name__ == "__main__":
    run(parse_args())
