"""ResNet-50 ImageNet training through the PyTorch API surface — the
reference's full-recipe torch example (reference
examples/pytorch_imagenet_resnet50.py), on the ``horovod_tpu.torch``
binding.

Reference concepts demonstrated, each on its horovod_tpu form:

* resume: rank 0 scans checkpoints, ``broadcast_object`` agrees on the
  epoch (reference :88-99)
* ``DistributedOptimizer(named_parameters, compression,
  backward_passes_per_step)`` with optional bf16 wire compression
  (reference :181-188 ``--fp16-allreduce``, ``--batches-per-allreduce``)
* root-rank parameter + optimizer-state broadcast (reference :190-192)
* LR warmup + staircase schedule by epoch (reference :135-152 adjust_lr)
* validation accuracy averaged across ranks with the eager allreduce
  (reference :219-231 metric_average)
* rank-0-only checkpointing (reference :234-241)

Note: torch in this image is CPU-only; this example is the migration
surface for torch scripts — TPU-resident training is the JAX path
(examples/keras_imagenet_resnet50.py).  torchvision is not installed, so
the model is a torchvision-shaped ResNet-50 built from torch.nn
primitives; with no --train-dir the data is synthetic.

Run:  tpurun -np 2 python examples/pytorch_imagenet_resnet50.py \
          --epochs 1 --steps-per-epoch 4 --image-size 64
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


def conv_bn(cin, cout, k=3, stride=1, groups=1):
    pad = (k - 1) // 2
    return [nn.Conv2d(cin, cout, k, stride, pad, groups=groups,
                      bias=False), nn.BatchNorm2d(cout)]


class Bottleneck(nn.Module):
    """torchvision-layout bottleneck (1x1 / 3x3-strided / 1x1 x4)."""

    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * 4
        self.body = nn.Sequential(
            *conv_bn(cin, width, 1), nn.ReLU(inplace=True),
            *conv_bn(width, width, 3, stride), nn.ReLU(inplace=True),
            *conv_bn(width, cout, 1))
        self.down = (nn.Sequential(*conv_bn(cin, cout, 1, stride))
                     if stride != 1 or cin != cout else None)

    def forward(self, x):
        idn = x if self.down is None else self.down(x)
        return F.relu(self.body(x) + idn)


class ResNet50(nn.Module):
    """3-4-6-3 bottleneck stack, torchvision parameter layout."""

    def __init__(self, num_classes=1000):
        super().__init__()
        stages = []
        cin = 64
        for i, (blocks, width) in enumerate(
                zip((3, 4, 6, 3), (64, 128, 256, 512))):
            for b in range(blocks):
                stages.append(Bottleneck(
                    cin, width, stride=2 if b == 0 and i > 0 else 1))
                cin = width * 4
        self.stem = nn.Sequential(
            *conv_bn(3, 64, 7, 2), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, 2, 1))
        self.stages = nn.Sequential(*stages)
        self.head = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.stages(self.stem(x))
        x = F.adaptive_avg_pool2d(x, 1).flatten(1)
        return self.head(x)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="horovod_tpu torch ImageNet recipe",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--train-dir", default=None,
                   help=".npz shards with 'x' (NCHW float) and 'y'; "
                        "synthetic when unset")
    p.add_argument("--checkpoint-format",
                   default="./checkpoint-{epoch}.pt",
                   help="rank-0 checkpoint path pattern")
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="bf16 wire compression for gradient allreduce")
    p.add_argument("--batches-per-allreduce", type=int, default=1,
                   help="accumulate N backwards before communicating "
                        "(backward_passes_per_step)")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--steps-per-epoch", type=int, default=8,
                   help="steps per epoch (synthetic mode)")
    p.add_argument("--base-lr", type=float, default=0.0125)
    p.add_argument("--warmup-epochs", type=float, default=5)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=0.00005)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    return p.parse_args(argv)


def adjust_lr(optimizer, args, epoch: int, step: int, spe: int) -> float:
    """Reference adjust_learning_rate: linear warmup over the first
    warmup_epochs to base_lr*size, then /10 at epochs 30/60/80."""
    if epoch < args.warmup_epochs:
        frac = (epoch * spe + step + 1) / (args.warmup_epochs * spe)
        mult = frac * (hvd.size() - 1) + 1      # 1 -> size, the ref ramp
        lr = args.base_lr * mult
    else:
        decay = 10 ** -sum(epoch >= e for e in (30, 60, 80))
        lr = args.base_lr * hvd.size() * decay
    for group in optimizer.param_groups:
        group["lr"] = lr
    return lr


def metric_average(val: float, name: str) -> float:
    return float(hvd.allreduce(torch.tensor([val]), name=name)[0])


def run(args) -> dict:
    hvd.init()
    torch.manual_seed(42 + hvd.rank())
    verbose = hvd.rank() == 0

    model = ResNet50(num_classes=args.num_classes)
    optimizer = torch.optim.SGD(model.parameters(), lr=args.base_lr,
                                momentum=args.momentum,
                                weight_decay=args.wd)

    # resume: rank 0 scans for the newest checkpoint, everyone agrees
    resume = 0
    if verbose:
        for e in range(args.epochs, 0, -1):
            if os.path.exists(args.checkpoint_format.format(epoch=e)):
                resume = e
                break
    resume = hvd.broadcast_object(resume, root_rank=0,
                                  name="resume_from_epoch")
    if resume > 0 and verbose:
        # rank 0 only (reference :88-99): the broadcasts below ship the
        # restored state to ranks that can't see the checkpoint file
        ckpt = torch.load(args.checkpoint_format.format(epoch=resume),
                          weights_only=True)
        model.load_state_dict(ckpt["model"])
        optimizer.load_state_dict(ckpt["optimizer"])

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=(hvd.Compression.fp16 if args.fp16_allreduce
                     else hvd.Compression.none),
        backward_passes_per_step=args.batches_per_allreduce)

    if args.train_dir:
        import glob

        files = sorted(glob.glob(os.path.join(args.train_dir, "*.npz")))
        assert files, f"no .npz shards under {args.train_dir}"
        xs, ys = zip(*((d["x"], d["y"]) for d in map(np.load, files)))
        # per-rank shard (the reference's DistributedSampler)
        x_all = np.concatenate(xs)[hvd.rank()::hvd.size()]
        y_all = np.concatenate(ys)[hvd.rank()::hvd.size()]
        spe = max(1, len(x_all) // args.batch_size)
    else:
        spe = args.steps_per_epoch
        rng = np.random.default_rng(7 + hvd.rank())

    last = {"loss": float("nan"), "acc": 0.0}
    for epoch in range(resume, args.epochs):
        model.train()
        for step in range(spe):
            lr = adjust_lr(optimizer, args, epoch, step, spe)
            if args.train_dir:
                lo = (step * args.batch_size) % max(1, len(x_all))
                bx = torch.from_numpy(
                    x_all[lo:lo + args.batch_size]).float()
                by = torch.from_numpy(
                    y_all[lo:lo + args.batch_size]).long()
            else:
                bx = torch.from_numpy(rng.standard_normal(
                    (args.batch_size, 3, args.image_size,
                     args.image_size), dtype=np.float32))
                by = torch.from_numpy(rng.integers(
                    0, args.num_classes,
                    size=(args.batch_size,)).astype(np.int64))
            # this binding's contract (docs/pytorch.md): step() after
            # EVERY backward; it synchronizes and applies on the Nth.
            # Micro losses are divided by N so the accumulated gradient
            # is the mean (the reference divides the same way)
            optimizer.zero_grad()
            micro = max(1, args.batch_size // args.batches_per_allreduce)
            for lo2 in range(0, args.batch_size, micro):
                loss = F.cross_entropy(
                    model(bx[lo2:lo2 + micro]), by[lo2:lo2 + micro]
                ) / args.batches_per_allreduce
                loss.backward()
                optimizer.step()

        # cross-rank averaged epoch metrics (reference metric_average)
        model.eval()
        with torch.no_grad():
            logits = model(bx)
            acc = float((logits.argmax(1) == by).float().mean())
        last = {"loss": metric_average(float(loss), "avg_loss"),
                "acc": metric_average(acc, "avg_accuracy"), "lr": lr}
        if verbose:
            print(f"epoch {epoch}: loss {last['loss']:.4f} "
                  f"acc {last['acc']:.3f} lr {lr:.5f}", flush=True)
            torch.save({"model": model.state_dict(),
                        "optimizer": optimizer.state_dict()},
                       args.checkpoint_format.format(epoch=epoch + 1))
    return {"last_loss": last["loss"], "accuracy": last["acc"],
            "epochs_run": args.epochs - resume}


if __name__ == "__main__":
    run(parse_args())
