"""Keras estimator trained from a Spark DataFrame (reference
examples/keras_spark_mnist.py: DataFrame of feature-vector/label rows ->
KerasEstimator with a Store -> fit(df) -> model).

Same harness as pytorch_spark_mnist.py on the Keras path:
``horovod_tpu.spark.keras.KerasEstimator`` ingests the DataFrame through
the Store and trains through the TF binding with the broadcast callback.

Run:  python examples/keras_spark_mnist.py [--epochs 6]
"""

from __future__ import annotations

import argparse
import sys
import tempfile

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from pytorch_spark_mnist import make_dataframe  # noqa: E402


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=6)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--work-dir", default=None)
    args = parser.parse_args()

    import tensorflow as tf

    import horovod_tpu as hvd
    from horovod_tpu.estimator import Store

    try:  # reference-shaped path (gated on pyspark, like horovod.spark)
        from horovod_tpu.spark.keras import KerasEstimator
    except ImportError:  # no pyspark: the estimator package is ungated
        from horovod_tpu.estimator import KerasEstimator

    hvd.init()
    work_dir = args.work_dir or tempfile.mkdtemp(prefix="hvd_keras_mnist_")

    model = tf.keras.Sequential([
        tf.keras.layers.Input((64,)),
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    est = KerasEstimator(
        model=model,
        optimizer=tf.keras.optimizers.Adam(1e-3),
        loss=tf.keras.losses.SparseCategoricalCrossentropy(
            from_logits=True),
        store=Store.create(work_dir),
        batch_size=args.batch_size,
        epochs=args.epochs,
        feature_cols=["features"],
        label_cols=["label"],
        validation=0.15,
        run_id="keras_spark_mnist",
        verbose=0,
    )
    fitted = est.fit(make_dataframe())

    if hvd.process_rank() == 0:
        hist = fitted.history_
        print(f"train loss {hist['loss'][0]:.3f} -> "
              f"{hist['loss'][-1]:.3f}  val loss {hist['val_loss'][-1]:.3f}")
        probe = make_dataframe(n=128, seed=7)
        rows = [r.asDict() if hasattr(r, "asDict") else dict(r)
                for r in probe.collect()]
        x = np.asarray([r["features"] for r in rows], np.float32)
        y = np.asarray([r["label"] for r in rows])
        pred = np.asarray(fitted.predict(x, verbose=0)).argmax(axis=1)
        print(f"holdout accuracy: {(pred == y).mean():.1%}")


if __name__ == "__main__":
    main()
