"""MNIST through the TensorFlow binding.

Mirror of the reference's TF2 Keras example (reference
examples/tensorflow2_keras_mnist.py): hvd.init → shard the dataset by
rank → wrap the optimizer in hvd.DistributedOptimizer → callbacks
broadcast initial state and average metrics; checkpointing gated on
rank 0.  The TF math runs on host; the gradients cross processes on the
framework's data plane (launch with ``tpurun -np 2`` for the real
multi-process path).

Run:  python examples/tf2_keras_mnist.py --epochs 2
"""

from __future__ import annotations

import argparse
import sys
import tempfile

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None) -> float:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    args = parser.parse_args(argv)

    import tensorflow as tf

    import horovod_tpu.tensorflow.keras as hvd

    hvd.init()

    from examples.datasets import synthetic_mnist

    x, y = synthetic_mnist(n=2048)
    x = x.reshape((-1, 28 * 28)).astype(np.float32)
    # shard by process rank (reference shards via tf.data .shard)
    from horovod_tpu import core

    n_proc = max(core.process_size(), 1)
    r = core.process_rank()
    x, y = x[r::n_proc], y[r::n_proc]

    model = tf.keras.Sequential([
        tf.keras.layers.Input((28 * 28,)),
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    # scale LR by world size (reference: lr * hvd.size())
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=args.lr * n_proc)
    )
    model.compile(
        optimizer=opt,
        loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(warmup_epochs=1),
    ]
    if hvd.rank() == 0 and core.process_rank() == 0:
        ckpt = tempfile.mkdtemp(prefix="tf2_mnist_ckpt") + "/model.weights.h5"
        callbacks.append(tf.keras.callbacks.ModelCheckpoint(
            ckpt, save_weights_only=True
        ))

    hist = model.fit(
        x, y, batch_size=args.batch_size, epochs=args.epochs,
        verbose=2 if core.process_rank() == 0 else 0,
        callbacks=callbacks,
    )
    return float(hist.history["loss"][-1])


if __name__ == "__main__":
    print(f"final loss: {main():.4f}")
