"""Per-rank metrics plane.

The live counterpart of the timeline/ post-mortem traces: a process-wide
registry of counters/gauges/histograms (registry.py), the instrument
inventory every layer reports into (this module), and the pusher that
ships JSON snapshots to the launcher's rendezvous server for cross-rank
aggregation (push.py → run/http_server.py ``GET /metrics``).

Metric families (all prefixed ``hvd_``; the launcher injects a ``rank``
label when it aggregates):

==============================  =========  ==================================
name                            kind       meaning
==============================  =========  ==================================
hvd_eager_collective_calls_total counter   eager dispatches, by ``op``
hvd_eager_collective_bytes_total counter   per-rank payload bytes, by ``op``
hvd_eager_collective_seconds    histogram  dispatch wall time, by ``op``
hvd_negotiation_seconds         histogram  controller negotiate(), by ``op``
hvd_host_collective_calls_total counter    host-plane ops, by ``op``/``transport``
hvd_host_collective_bytes_total counter    host-plane bytes, by ``op``/``transport``
hvd_host_collective_seconds     histogram  host-plane wall time, by ``transport``
hvd_collectives_traced_total    counter    collectives emitted at trace time
hvd_collectives_traced_bytes_total counter traced payload bytes, by ``op``
hvd_step_seconds                histogram  train-step cadence (dispatch-to-
                                           dispatch interval — honest under
                                           async dispatch, see training.py)
hvd_steps_total                 counter    train steps dispatched
hvd_samples_total               counter    global samples dispatched
hvd_train_loss                  gauge      trailing async loss fetch (N
                                           steps old by construction —
                                           never a pipeline stall)
hvd_ring_ops_total              counter    ring-plane transfers, by ``op``
hvd_ring_bytes_total            counter    ring-plane payload bytes
hvd_ring_active                 gauge      1 when the peer ring is up
hvd_inflight_ops                gauge      stall-inspector watchdog entries
hvd_stalled_ops                 gauge      entries past the warning threshold
hvd_stall_warnings_total        counter    cumulative stall warnings
hvd_controller_cycles           gauge      coordinator negotiation cycles
hvd_controller_cache_hits       gauge      coordinator response-cache hits
hvd_controller_stall_warnings   gauge      coordinator-side stall warnings
hvd_join_events_total           counter    elastic host-plane join() calls
hvd_sanitizer_checks_total      counter    sanitizer fingerprints verified
hvd_sanitizer_mismatches_total  counter    sanitizer divergences raised
hvd_heartbeats_total            counter    lease renewals pushed to /health
hvd_aborts_total                counter    coordinated aborts, by ``source``
hvd_http_retries_total          counter    rendezvous HTTP requests retried
hvd_faults_injected_total       counter    HVD_FAULT_SPEC faults, by ``kind``
hvd_restarts_total              counter    supervised job relaunches (launcher)
hvd_membership_epochs_total     counter    elastic membership epochs committed
hvd_ranks_removed_total         counter    workers removed from the world
hvd_ranks_admitted_total        counter    workers admitted into the world
hvd_autotune_predicted_speedup  gauge      replay-predicted speedup of the
                                           applied fusion plan (percent)
hvd_autotune_realized_speedup   gauge      realized speedup of the applied
                                           plan vs its baseline window (pct)
hvd_autotune_plans_applied_total counter   profile-guided plans applied live
hvd_autotune_rollbacks_total    counter    plans rolled back past guard band
hvd_mfu                         gauge      measured MFU over the compute-
                                           anatomy profiler's window
hvd_step_phase_fraction         gauge      share of profiled step wall time
                                           per phase (by ``phase`` label)
hvd_host_gap_us                 gauge      per-step device-idle-on-host time
                                           from inter-dispatch gaps
hvd_serve_requests_total        counter    inference requests, by ``outcome``
hvd_serve_latency_seconds       histogram  request submit→complete latency
hvd_serve_queue_wait_seconds    histogram  request submit→pull queue wait
hvd_serve_batch_fill            histogram  real (pre-padding) batch sizes
hvd_serve_queue_depth           gauge      pending requests in the broker
hvd_serve_replicas              gauge      live inference replicas
hvd_serve_p99_ms                gauge      windowed p99 request latency
hvd_serve_autoscale_events_total counter   autoscale actions, by ``direction``
hvd_serve_drains_total          counter    lossless drain handshakes done
hvd_serve_requeues_total        counter    in-flight requests requeued after
                                           a replica died uncleanly
hvd_projection_step_us          gauge      digital-twin projected step time,
                                           by target ``world``
hvd_projection_efficiency       gauge      projected scaling efficiency vs
                                           the source replay baseline
hvd_projection_err_pct          gauge      tracked projected-vs-measured
                                           step-time error of the twin
hvd_alerts_total                counter    watchdog alerts raised, by
                                           ``signal``/``severity``
                                           (horovod_tpu/observe/)
hvd_watch_arms_total            counter    trace+profile windows auto-armed
                                           by a confirmed alert
hvd_timeseries_flushes_total    counter    time-series history flushes, by
                                           ``mode`` (delta/full/resync)
hvd_events_total                counter    flight-recorder events emitted,
                                           by ``kind``/``severity``
                                           (observe/events.py)
hvd_events_dropped_total        counter    events dropped on per-process
                                           ring overflow (oldest evicted)
hvd_snapshots_total             counter    peer-tier snapshot generations
                                           committed (elastic/peerstate.py)
hvd_snapshot_bytes_total        counter    serialized snapshot bytes pushed
                                           to peers
hvd_snapshot_failures_total     counter    async snapshot attempts that died
                                           before their commit marker
hvd_snapshot_stall_us           gauge      step-path stall of the last
                                           snapshot enqueue, microseconds
hvd_snapshot_gen                gauge      newest own generation committed
                                           to the peer tier
hvd_snapshot_reprotected_total  counter    shards re-pushed to restore
                                           K-redundancy after a shrink
hvd_restores_total              counter    state restores completed, by
                                           ``source`` (peer/storage)
==============================  =========  ==================================
"""

from __future__ import annotations

import numpy as np

from ..utils import env as env_util
from .registry import (  # noqa: F401
    BYTES_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    MetricsRegistry,
    exponential_buckets,
    latency_buckets_from_env,
    registry,
    render_prometheus,
)

#: serving-request latency scheme: the default floor (100 µs) is tuned
#: for dispatch spans; request latencies live in the 0.25 ms..30 s range
#: (HVD_SERVE_LATENCY_BUCKET_FLOOR moves the floor; factor/count shared
#: with the job-wide HVD_METRICS_BUCKET_{FACTOR,COUNT})
SERVE_LATENCY_BUCKETS = latency_buckets_from_env(
    env_util.HVD_SERVE_LATENCY_BUCKET_FLOOR,
    env_util.DEFAULT_SERVE_LATENCY_BUCKET_FLOOR)

# -- instrument inventory ----------------------------------------------------
EAGER_CALLS = registry.counter(
    "hvd_eager_collective_calls_total",
    "Eager collective dispatches by op type.", ("op",))
EAGER_BYTES = registry.counter(
    "hvd_eager_collective_bytes_total",
    "Per-rank payload bytes moved by eager collectives.", ("op",))
EAGER_SECONDS = registry.histogram(
    "hvd_eager_collective_seconds",
    "Eager collective dispatch wall time.", ("op",))
NEGOTIATE_SECONDS = registry.histogram(
    "hvd_negotiation_seconds",
    "Controller negotiation (submit+wait) wall time.", ("op",))

HOST_CALLS = registry.counter(
    "hvd_host_collective_calls_total",
    "Host-plane collective ops by transport (ring/star/mesh).",
    ("op", "transport"))
HOST_BYTES = registry.counter(
    "hvd_host_collective_bytes_total",
    "Host-plane collective payload bytes by transport.",
    ("op", "transport"))
HOST_SECONDS = registry.histogram(
    "hvd_host_collective_seconds",
    "Host-plane collective wall time by transport.", ("transport",))

TRACED_CALLS = registry.counter(
    "hvd_collectives_traced_total",
    "Collective HLOs emitted during SPMD tracing (per compile, not per "
    "step).", ("op",))
TRACED_BYTES = registry.counter(
    "hvd_collectives_traced_bytes_total",
    "Per-rank payload bytes of traced collectives.", ("op",))
TRACED_GROUP_CALLS = registry.counter(
    "hvd_collectives_traced_group_total",
    "Traced collectives dispatched over a restricted communication "
    "group (two-level local/cross stages, process sets) — the group-"
    "labelled inventory the schedule checker and sanitizer reason "
    "about.", ("op", "group"))

STEP_SECONDS = registry.histogram(
    "hvd_step_seconds",
    "Train-step cadence: interval between successive step dispatches "
    "(equals real step time in steady state under async dispatch).")
STEPS_TOTAL = registry.counter(
    "hvd_steps_total", "Train steps dispatched.")
SAMPLES_TOTAL = registry.counter(
    "hvd_samples_total", "Global samples dispatched into train steps.")
TRAIN_LOSS = registry.gauge(
    "hvd_train_loss",
    "Most recently fetched training loss — fetched on the trailing "
    "HVD_LOSS_FETCH_STEPS cadence (training.py), so the value is N "
    "steps old and the fetch never drains the dispatch pipeline.")

RING_OPS = registry.counter(
    "hvd_ring_ops_total", "Peer-ring transfers executed.", ("op",))
RING_BYTES = registry.counter(
    "hvd_ring_bytes_total", "Peer-ring payload bytes transferred.")
RING_ACTIVE = registry.gauge(
    "hvd_ring_active", "1 while the peer-ring data plane is established.")

INFLIGHT_OPS = registry.gauge(
    "hvd_inflight_ops", "Operations currently in the stall-inspector "
    "watchdog table (negotiation/dispatch queue depth).")
STALLED_OPS = registry.gauge(
    "hvd_stalled_ops", "Watchdog entries past the warning threshold.")
STALL_WARNINGS = registry.counter(
    "hvd_stall_warnings_total", "Cumulative stall warnings emitted.")

CONTROLLER_CYCLES = registry.gauge(
    "hvd_controller_cycles", "Coordinator negotiation cycles completed.")
CONTROLLER_CACHE_HITS = registry.gauge(
    "hvd_controller_cache_hits", "Coordinator response-cache hits.")
CONTROLLER_STALLS = registry.gauge(
    "hvd_controller_stall_warnings", "Coordinator-side stall warnings.")

JOIN_EVENTS = registry.counter(
    "hvd_join_events_total", "Elastic host-plane join() barriers entered.")

SANITIZER_CHECKS = registry.counter(
    "hvd_sanitizer_checks_total",
    "Collective-sanitizer fingerprint checks that verified clean.")
SANITIZER_MISMATCHES = registry.counter(
    "hvd_sanitizer_mismatches_total",
    "Collective-sanitizer divergences detected (signature mismatch or "
    "silent peer).")

HEARTBEATS = registry.counter(
    "hvd_heartbeats_total",
    "Heartbeat lease renewals pushed to the rendezvous /health scope.")
ABORTS = registry.counter(
    "hvd_aborts_total",
    "Coordinated aborts by source plane (launcher/stall_inspector/api) "
    "plus 'observed' on ranks whose heartbeat saw the flag.", ("source",))
HTTP_RETRIES = registry.counter(
    "hvd_http_retries_total",
    "Rendezvous HTTP requests retried after a transient failure "
    "(URLError or 5xx).")
HTTP_REUSE = registry.counter(
    "hvd_http_reuse_total",
    "Rendezvous HTTP requests served over a pooled keep-alive "
    "connection instead of a fresh TCP connect (run/http_client.py).")
CP_FAILOVERS = registry.counter(
    "hvd_cp_failovers_total",
    "Requests that abandoned a dead rendezvous address for the next "
    "entry of the HVD_RENDEZVOUS_ADDRS failover list.")
RELAY_FLUSHES = registry.counter(
    "hvd_relay_flushes_total",
    "Per-host relay upstream batch flushes (run/relay.py; one PUT "
    "/batch each, replacing one request per buffered key).")
RELAY_ENTRIES = registry.counter(
    "hvd_relay_entries_total",
    "KV entries the per-host relay aggregated into upstream batches.")
RELAY_FALLBACKS = registry.counter(
    "hvd_relay_fallbacks_total",
    "Control-plane clients that fell back from an unreachable per-host "
    "relay to the primary rendezvous (pass-through mode).")
METRICS_DELTA_PUSHES = registry.counter(
    "hvd_metrics_delta_pushes_total",
    "Metric snapshot pushes sent as family deltas instead of full "
    "snapshots (metrics/push.py), by outcome.", ("outcome",))
FAULTS_INJECTED = registry.counter(
    "hvd_faults_injected_total",
    "Faults injected by the HVD_FAULT_SPEC harness, by kind.", ("kind",))
RESTARTS = registry.counter(
    "hvd_restarts_total",
    "Supervised job relaunches performed by the tpurun restart policy "
    "(launcher-side).")
MEMBERSHIP_EPOCHS = registry.counter(
    "hvd_membership_epochs_total",
    "Elastic membership epochs committed by the driver (launcher-side; "
    "includes the initial world).")
RANKS_REMOVED = registry.counter(
    "hvd_ranks_removed_total",
    "Workers removed from the elastic world (crashes, lease expiries, "
    "partitions).")
RANKS_ADMITTED = registry.counter(
    "hvd_ranks_admitted_total",
    "Workers admitted into the elastic world at epoch boundaries "
    "(rejoins and spare hosts).")

SNAPSHOTS_TOTAL = registry.counter(
    "hvd_snapshots_total",
    "Peer-tier snapshot generations committed by this rank "
    "(elastic/peerstate.py).")
SNAPSHOT_BYTES = registry.counter(
    "hvd_snapshot_bytes_total",
    "Serialized snapshot bytes this rank pushed to its replica peers.")
SNAPSHOT_FAILURES = registry.counter(
    "hvd_snapshot_failures_total",
    "Async snapshot attempts that failed before writing their commit "
    "marker (the generation stays unrestorable; storage tier covers).")
SNAPSHOT_STALL_US = registry.gauge(
    "hvd_snapshot_stall_us",
    "Step-path stall of the last snapshot enqueue in microseconds — "
    "the ONLY checkpoint cost the training step pays on the peer tier.")
SNAPSHOT_GEN = registry.gauge(
    "hvd_snapshot_gen",
    "Newest generation (= step) this rank committed to the peer tier.")
SNAPSHOT_REPROTECTED = registry.counter(
    "hvd_snapshot_reprotected_total",
    "Shards re-pushed to new peers to restore K-redundancy after a "
    "world shrink orphaned their replicas.")
RESTORES = registry.counter(
    "hvd_restores_total",
    "State restores completed, by source tier (peer/storage).",
    ("source",))

AUTOTUNE_PREDICTED_SPEEDUP = registry.gauge(
    "hvd_autotune_predicted_speedup",
    "Replay-predicted speedup (percent) of the currently applied "
    "profile-guided fusion plan (optim/profile_guided.py).")
AUTOTUNE_REALIZED_SPEEDUP = registry.gauge(
    "hvd_autotune_realized_speedup",
    "Realized speedup (percent) of the applied plan's verify window "
    "against its baseline window.")
AUTOTUNE_PLANS_APPLIED = registry.counter(
    "hvd_autotune_plans_applied_total",
    "Profile-guided fusion plans applied live through the re-jit seam.")
AUTOTUNE_ROLLBACKS = registry.counter(
    "hvd_autotune_rollbacks_total",
    "Applied plans rolled back because realized speedup lagged the "
    "prediction past the guard band.")

MFU = registry.gauge(
    "hvd_mfu",
    "Model-FLOPs utilization measured by the compute-anatomy profiler "
    "over its capture window (timeline/profiler.py: cost_analysis flops "
    "over measured step wall time, divided by utils/flops.peak_flops — "
    "the same single-sourced peak the bench JSON divides by).")
STEP_PHASE_FRACTION = registry.gauge(
    "hvd_step_phase_fraction",
    "Fraction of the profiled step's wall time spent in each phase "
    "(forward/backward/grad_allreduce/optimizer_update/host_gap).",
    ("phase",))
HOST_GAP_US = registry.gauge(
    "hvd_host_gap_us",
    "Per-step device-idle-waiting-on-host time detected from "
    "inter-dispatch gaps inside the profiled window.")

SERVE_REQUESTS = registry.counter(
    "hvd_serve_requests_total",
    "Inference requests by outcome (ok/error/timeout/rejected) — "
    "serving plane, horovod_tpu/serving/.", ("outcome",))
SERVE_LATENCY = registry.histogram(
    "hvd_serve_latency_seconds",
    "Inference request latency, submit to complete (the number the SLO "
    "is written against).", buckets=SERVE_LATENCY_BUCKETS)
SERVE_QUEUE_WAIT = registry.histogram(
    "hvd_serve_queue_wait_seconds",
    "Time a request waited in the broker queue before a replica pulled "
    "it (queueing delay component of hvd_serve_latency_seconds).",
    buckets=SERVE_LATENCY_BUCKETS)
SERVE_BATCH_FILL = registry.histogram(
    "hvd_serve_batch_fill",
    "Real (pre-padding) batch sizes formed by the continuous batcher.",
    buckets=exponential_buckets(1.0, 2.0, 9))
SERVE_QUEUE_DEPTH = registry.gauge(
    "hvd_serve_queue_depth",
    "Requests pending in the serving broker queue (the autoscaler's "
    "primary load signal).")
SERVE_REPLICAS = registry.gauge(
    "hvd_serve_replicas",
    "Live inference replicas pulling from the broker.")
SERVE_P99_MS = registry.gauge(
    "hvd_serve_p99_ms",
    "Windowed p99 request latency in milliseconds (compared against "
    "HVD_SERVE_SLO_MS by the autoscaler).")
SERVE_AUTOSCALE_EVENTS = registry.counter(
    "hvd_serve_autoscale_events_total",
    "Membership epochs committed by the serving autoscaler, by "
    "direction (grow/shrink).", ("direction",))
SERVE_DRAINS = registry.counter(
    "hvd_serve_drains_total",
    "Lossless drain handshakes completed before a scale-down removal "
    "(elastic/driver.py).")
SERVE_REQUEUES = registry.counter(
    "hvd_serve_requeues_total",
    "In-flight requests returned to the queue after a replica died "
    "without completing them.")

PROJECTION_STEP_US = registry.gauge(
    "hvd_projection_step_us",
    "Digital-twin projected step time in µs for one target topology "
    "(timeline/replay/projection.py; labeled by target world size).",
    ("world",))
PROJECTION_EFFICIENCY = registry.gauge(
    "hvd_projection_efficiency",
    "Projected scaling efficiency (source replay baseline over projected "
    "step) for one target topology, by target world size.", ("world",))
PROJECTION_ERR_PCT = registry.gauge(
    "hvd_projection_err_pct",
    "Projected-vs-measured step-time error of the digital twin on a "
    "world that was actually run (the twin's tracked accuracy — "
    "docs/projection.md validation contract).")

ALERTS_TOTAL = registry.counter(
    "hvd_alerts_total",
    "Online-watchdog alerts raised by the observe/ detectors, by signal "
    "(step_time_regression/straggler/mfu_drop/comm_beta_drift/slo_burn) "
    "and severity (warning/critical) — docs/observe.md.",
    ("signal", "severity"))
WATCH_ARMS = registry.counter(
    "hvd_watch_arms_total",
    "Trace+profile windows auto-armed by a confirmed step-time or "
    "straggler alert (observe/watchdog.py KV broadcast).")
TIMESERIES_FLUSHES = registry.counter(
    "hvd_timeseries_flushes_total",
    "Time-series history flushes shipped to the launcher, by mode "
    "(delta/full/resync) — metrics/timeseries.py.", ("mode",))
EVENTS_TOTAL = registry.counter(
    "hvd_events_total",
    "Control-plane flight-recorder events emitted, by kind "
    "(epoch.commit/abort.publish/restart.attempt/...) and severity "
    "(observe/events.py, docs/observe.md).", ("kind", "severity"))
EVENTS_DROPPED = registry.counter(
    "hvd_events_dropped_total",
    "Flight-recorder events dropped on per-process ring overflow "
    "(oldest evicted; raise HVD_EVENTS_RING_CAP if nonzero).")

COMPRESSION_RESIDUAL_NORM = registry.gauge(
    "hvd_compression_residual_norm",
    "Global L2 norm of the error-feedback residual pytree, sampled every "
    "HVD_COMPRESSION_GUARD_STEPS steps (ops/compression.py; a healthy EF "
    "loop keeps this bounded by the per-step quantization error).")
COMPRESSION_FALLBACKS = registry.counter(
    "hvd_compression_fallbacks_total",
    "Automatic fall-backs to uncompressed allreduce after the error-"
    "feedback residual diverged (training.py convergence guard).")
TWO_LEVEL_FALLBACKS = registry.counter(
    "hvd_two_level_fallbacks_total",
    "two_level_allreduce degradations to flat allreduce (non-power-of-two "
    "cross-host group or trivial topology); counted per compiled program, "
    "not per step.")


def on() -> bool:
    """The hot-path gate: one attribute read."""
    return registry.enabled


def payload_bytes(shape, dtype) -> int:
    """Best-effort byte count of one rank's payload; never raises (the
    metrics plane must not take down a dispatch over an exotic dtype)."""
    try:
        n = 1
        for d in shape:
            n *= int(d)
        return n * np.dtype(dtype).itemsize
    except Exception:  # noqa: BLE001
        try:
            import ml_dtypes  # bfloat16/fp8 names numpy doesn't know

            n = 1
            for d in shape:
                n *= int(d)
            return n * np.dtype(getattr(ml_dtypes, str(dtype))).itemsize
        except Exception:  # noqa: BLE001
            return 0


def record_eager(op: str, nbytes: int, negotiate_s: float,
                 total_s: float) -> None:
    """One eager collective dispatch (eager._dispatch_guard)."""
    EAGER_CALLS.labels(op).inc()
    if nbytes:
        EAGER_BYTES.labels(op).inc(nbytes)
        # dispatch cost density (µs per MiB moved): the series the
        # observe/ comm-β drift detector compares against the α–β model
        if timeseries.on():
            timeseries.record(timeseries.DISPATCH_US_PER_MIB,
                              total_s * 1e6 / (nbytes / 2**20))
    EAGER_SECONDS.labels(op).observe(total_s)
    NEGOTIATE_SECONDS.labels(op).observe(negotiate_s)


def record_host(op: str, transport: str, nbytes: int, seconds: float) -> None:
    """One host-plane collective (eager.process_* transports)."""
    HOST_CALLS.labels(op, transport).inc()
    if nbytes:
        HOST_BYTES.labels(op, transport).inc(nbytes)
    HOST_SECONDS.labels(transport).observe(seconds)


def record_traced(op: str, tensor) -> None:
    """A collective primitive emitted during SPMD tracing
    (ops/collectives.py) — compile-time cost only, never per-step."""
    if not registry.enabled:
        return
    try:
        TRACED_CALLS.labels(op).inc()
        nb = payload_bytes(getattr(tensor, "shape", ()),
                           getattr(tensor, "dtype", "float32"))
        if nb:
            TRACED_BYTES.labels(op).inc(nb)
    except Exception:  # noqa: BLE001 — tracing must never fail on metrics
        pass


def record_traced_group(op: str, group: str) -> None:
    """Group-labelled traced-collective inventory (two-level local/cross
    stages, process sets) — rides its own counter so the user-visible
    per-op dispatch (already counted by :func:`record_traced` at the
    call seam) is not double-counted.  ``group`` here is the group
    *family* (``local`` / ``cross`` / ``process_set:…``): tracing emits
    one program for every device, so there is no single concrete group
    instance to name — the sanitizer's runtime fingerprints key the
    concrete instances (``local:<node>``, ``cross:<chunk>``)."""
    if not registry.enabled:
        return
    try:
        TRACED_GROUP_CALLS.labels(op, group).inc()
    except Exception:  # noqa: BLE001 — tracing must never fail on metrics
        pass


def dump_metrics_json(path: str) -> None:
    """Write the per-rank snapshot (called by timeline shutdown so
    ``metrics.json`` lands next to ``comm.json``)."""
    registry.dump(path)


from . import timeseries  # noqa: E402  (ring-buffer history plane)
from .push import (  # noqa: E402,F401  (import after instruments exist)
    start_pusher,
    start_pusher_from_env,
    stop_pusher,
)
