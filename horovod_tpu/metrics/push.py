"""Metrics pusher: ship per-rank JSON snapshots to the launcher.

The launcher (tpurun / function-mode ``run()``) owns the rendezvous
server (run/http_server.py); each worker pushes its registry snapshot to
the ``metrics`` scope under its process id, and the server's signed
``GET /metrics`` renders every rank's snapshot as one Prometheus page.
Pull would need a per-rank listener and a port per worker; push rides the
HTTP KV store that already exists for bootstrap — the same transport
choice the reference made for rendezvous (run/http/http_server.py).

Wired up in two places:

* ``core.init()`` calls :func:`start_pusher_from_env` — active when the
  launcher set ``HVD_METRICS_KV_ADDR``/``PORT``/``HVD_METRICS_SECRET``;
  the interval comes from ``HVD_METRICS_PUSH_SECONDS`` (default 5).
* ``run/task_fn.py`` pushes a final snapshot after the worker function
  returns, so short function-mode jobs are captured even if no interval
  ever elapsed.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

_pusher: Optional["MetricsPusher"] = None
_lock = threading.Lock()


def push_snapshot(addr: str, port: int, rank: int,
                  secret: Optional[bytes] = None) -> bool:
    """One snapshot PUT to the launcher KV store; returns success.
    Never raises — losing a metrics sample must not fail the job."""
    from .registry import registry

    try:
        from ..run.http_client import put_kv

        payload = json.dumps(registry.snapshot()).encode()
        put_kv(addr, port, "metrics", str(rank), payload, secret=secret)
        return True
    except Exception as e:  # noqa: BLE001
        log.debug("metrics push failed: %s", e)
        return False


class MetricsPusher(threading.Thread):
    def __init__(self, addr: str, port: int, rank: int,
                 secret: Optional[bytes], interval: float) -> None:
        super().__init__(daemon=True, name="hvd-metrics-pusher")
        self.addr = addr
        self.port = port
        self.rank = rank
        self.secret = secret
        self.interval = max(float(interval), 0.5)
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            push_snapshot(self.addr, self.port, self.rank, self.secret)

    def stop(self, final_push: bool = True) -> None:
        self._stop.set()
        if final_push:
            push_snapshot(self.addr, self.port, self.rank, self.secret)


_atexit_registered = False


def start_pusher(addr: str, port: int, rank: int,
                 secret: Optional[bytes] = None,
                 interval: float = 5.0) -> MetricsPusher:
    """Start (or replace) the process-wide pusher thread.  Registers an
    atexit flush: a worker that exits without hvd.shutdown() — or before
    the first interval elapses — must still land its final snapshot on
    the launcher."""
    global _pusher, _atexit_registered
    with _lock:
        if _pusher is not None:
            _pusher.stop(final_push=False)
        _pusher = MetricsPusher(addr, port, rank, secret, interval)
        _pusher.start()
        if not _atexit_registered:
            import atexit

            atexit.register(stop_pusher)
            _atexit_registered = True
        return _pusher


def start_pusher_from_env(rank: int) -> Optional[MetricsPusher]:
    """Launcher-driven activation (no-op unless tpurun/run() set the
    ``HVD_METRICS_KV_*`` vars and the registry is enabled)."""
    from .registry import registry

    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port or not registry.enabled:
        return None
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    secret = bytes.fromhex(secret_hex) if secret_hex else None
    interval = env_util.get_float(env_util.HVD_METRICS_PUSH_SECONDS, 5.0)
    return start_pusher(addr, port, rank, secret, interval)


def stop_pusher() -> None:
    """Stop the pusher, flushing one final snapshot (core.shutdown)."""
    global _pusher
    with _lock:
        if _pusher is not None:
            _pusher.stop(final_push=True)
            _pusher = None
