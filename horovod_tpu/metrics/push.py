"""Metrics pusher: ship per-rank JSON snapshots to the launcher.

The launcher (tpurun / function-mode ``run()``) owns the rendezvous
server (run/http_server.py); each worker pushes its registry snapshot to
the ``metrics`` scope under its process id, and the server's signed
``GET /metrics`` renders every rank's snapshot as one Prometheus page.
Pull would need a per-rank listener and a port per worker; push rides the
HTTP KV store that already exists for bootstrap — the same transport
choice the reference made for rendezvous (run/http/http_server.py).

Wired up in two places:

* ``core.init()`` calls :func:`start_pusher_from_env` — active when the
  launcher set ``HVD_METRICS_KV_ADDR``/``PORT``/``HVD_METRICS_SECRET``;
  the interval comes from ``HVD_METRICS_PUSH_SECONDS`` (default 5).
* ``run/task_fn.py`` pushes a final snapshot after the worker function
  returns, so short function-mode jobs are captured even if no interval
  ever elapsed.

**Delta pushes (docs/control_plane.md).**  A full snapshot grows with
the instrument count (100+ families after PR 12) while most families
are idle between pushes, so the interval pusher ships only the families
that changed since the last acknowledged push: ``{"__delta__": true,
"base_id": <server incarnation>, "metrics": {changed}, "removed":
[...]}``, merged server-side into the stored full snapshot.  The
server's reply carries its ``server_id``; a restart or warm-standby
failover changes it, the next delta is rejected with 409, and the
pusher resyncs with one full snapshot — so an aggregated scrape is
never silently stale.  ``HVD_METRICS_DELTA=0`` forces full snapshots.
When the push rides a per-host relay (run/relay.py) deltas are off:
the relay coalesces to the latest full snapshot per rank and batches
upstream, which replaces the delta saving with a bigger one.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

_pusher: Optional["MetricsPusher"] = None
_lock = threading.Lock()


def push_snapshot(addr: str, port: int, rank: int,
                  secret: Optional[bytes] = None) -> bool:
    """One snapshot PUT to the launcher KV store; returns success.
    Never raises — losing a metrics sample must not fail the job."""
    from .registry import registry

    try:
        from ..run.http_client import put_kv

        payload = json.dumps(registry.snapshot()).encode()
        put_kv(addr, port, "metrics", str(rank), payload, secret=secret)
        return True
    except Exception as e:  # noqa: BLE001
        log.debug("metrics push failed: %s", e)
        return False


class MetricsPusher(threading.Thread):
    def __init__(self, addr: str, port: int, rank: int,
                 secret: Optional[bytes], interval: float) -> None:
        super().__init__(daemon=True, name="hvd-metrics-pusher")
        self.addr = addr
        self.port = port
        self.rank = rank
        self.secret = secret
        self.interval = max(float(interval), 0.5)
        self.delta_enabled = env_util.get_bool(env_util.HVD_METRICS_DELTA,
                                               True)
        # the delta base: the canonical form of every family the server
        # acknowledged, and the server incarnation that holds it
        self._last_families: Optional[dict] = None
        self._server_id: Optional[str] = None
        self.delta_pushes = 0
        self.full_pushes = 0
        self.resyncs = 0
        self.last_push_bytes = 0
        self.bytes_sent = 0
        self._stop = threading.Event()

    def push(self) -> bool:
        """One interval push: a family delta against the last
        acknowledged snapshot when possible, a full snapshot otherwise
        (first push, deltas disabled, relay-routed, or the server
        answered 409 because its incarnation changed).  Returns
        success; never raises."""
        import urllib.error

        from ..run import relay
        from ..run.http_client import put_kv_reply
        from .registry import registry

        try:
            snap = registry.snapshot()
            families = snap.get("metrics", {})
            canon = {n: json.dumps(f, sort_keys=True)
                     for n, f in families.items()}
            ep = relay.control_endpoint()
            via_relay = ep is not None and ep[2]
            # deltas need the primary's merge acknowledgement, so they
            # only run on the direct path; behind a relay, full
            # snapshots coalesce there instead
            use_delta = (self.delta_enabled and not via_relay
                         and self._server_id is not None
                         and self._last_families is not None)
            reply = None
            body = b""
            if use_delta:
                last = self._last_families
                changed = {n: families[n] for n, c in canon.items()
                           if last.get(n) != c}
                removed = [n for n in last if n not in canon]
                body = json.dumps({
                    "__delta__": True,
                    "base_id": self._server_id,
                    "metrics": changed,
                    "removed": removed,
                    "ts": snap.get("ts"),
                }).encode()
                try:
                    reply = put_kv_reply(self.addr, self.port, "metrics",
                                         str(self.rank), body,
                                         secret=self.secret)
                    self.delta_pushes += 1
                    _record_delta("delta")
                except urllib.error.HTTPError as e:
                    if e.code != 409:
                        raise
                    # server restart / standby takeover: the base is
                    # gone — resync with one full snapshot
                    self.resyncs += 1
                    _record_delta("resync")
                    use_delta = False
            if not use_delta:
                body = json.dumps(snap).encode()
                # through the relay (coalesced + batched upstream) when
                # one answers, with the shared permanent fallback to
                # the direct path — a dead relay must degrade to
                # per-rank pushes, never to silence
                reply = relay.control_put(self.addr, self.port, "metrics",
                                          str(self.rank), body,
                                          secret=self.secret,
                                          want_reply=True)
                self.full_pushes += 1
            self.last_push_bytes = len(body)
            self.bytes_sent += len(body)
            answered_by_relay = isinstance(reply, dict) \
                and bool(reply.get("relay"))
            sid = reply.get("server_id") if isinstance(reply, dict) else None
            if answered_by_relay or sid is None:
                # no merge acknowledgement to base a delta on (relay
                # replies buffer locally; a bare 200 is a pre-control-
                # plane server): keep pushing full snapshots
                self._server_id = None
                self._last_families = None
            else:
                self._server_id = sid
                self._last_families = canon
            return True
        except Exception as e:  # noqa: BLE001 — losing a sample must
            log.debug("metrics push failed: %s", e)  # not fail the job
            return False

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            self.push()

    def stop(self, final_push: bool = True) -> None:
        self._stop.set()
        if final_push:
            push_snapshot(self.addr, self.port, self.rank, self.secret)


def _record_delta(outcome: str) -> None:
    try:
        from .. import metrics

        if metrics.on():
            metrics.METRICS_DELTA_PUSHES.labels(outcome).inc()
    except Exception:  # noqa: BLE001
        pass


_atexit_registered = False


def start_pusher(addr: str, port: int, rank: int,
                 secret: Optional[bytes] = None,
                 interval: float = 5.0) -> MetricsPusher:
    """Start (or replace) the process-wide pusher thread.  Registers an
    atexit flush: a worker that exits without hvd.shutdown() — or before
    the first interval elapses — must still land its final snapshot on
    the launcher."""
    global _pusher, _atexit_registered
    with _lock:
        if _pusher is not None:
            _pusher.stop(final_push=False)
        _pusher = MetricsPusher(addr, port, rank, secret, interval)
        _pusher.start()
        if not _atexit_registered:
            import atexit

            atexit.register(stop_pusher)
            _atexit_registered = True
        return _pusher


def start_pusher_from_env(rank: int) -> Optional[MetricsPusher]:
    """Launcher-driven activation (no-op unless tpurun/run() set the
    ``HVD_METRICS_KV_*`` vars and the registry is enabled)."""
    from .registry import registry

    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port or not registry.enabled:
        return None
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    secret = bytes.fromhex(secret_hex) if secret_hex else None
    interval = env_util.get_float(env_util.HVD_METRICS_PUSH_SECONDS, 5.0)
    return start_pusher(addr, port, rank, secret, interval)


def stop_pusher() -> None:
    """Stop the pusher, flushing one final snapshot (core.shutdown)."""
    global _pusher
    with _lock:
        if _pusher is not None:
            _pusher.stop(final_push=True)
            _pusher = None
