"""Always-on telemetry time-series: bounded ring-buffer history.

The registry (registry.py) answers "what is the value NOW"; this module
answers "what was it over the last N steps" — the history the online
anomaly watchdog (horovod_tpu/observe/) runs its detectors on, without
anyone having picked a trace window in advance.  Every diagnostic
surface before this one (BYTEPS_TRACE step windows, the compute-anatomy
profiler, the replay twin) is operator-initiated; the time-series plane
is the cheap always-on substrate that tells the operator *when* to
spend those.

Design constraints, in order:

1. **hot-path cost**: appends sit on the training-step cadence and the
   eager dispatch path.  One append = one deque append plus an integer
   compare under a per-series lock; the downsampling fold touches
   ``factor`` floats once every ``factor`` appends (amortized O(1)).
   Call sites gate on :func:`on` — one attribute read when disabled.
2. **bounded memory**: each series holds ``HVD_TIMESERIES_TIERS`` rings
   of ``HVD_TIMESERIES_CAP`` samples.  Tier 0 is raw; tier *i+1* keeps
   one mean-folded sample per ``HVD_TIMESERIES_FACTOR`` tier-*i*
   samples — recent history at full resolution, older history
   progressively coarser, total memory fixed at cap × tiers.
3. **no deps, never raises into callers**: same rules as the registry.

**Flush protocol (docs/observe.md).**  A pusher thread (started from
``core.init`` next to the metrics pusher) ships each rank's history to
the launcher's ``timeseries`` KV scope.  On the direct path it sends
*deltas* — only the raw samples appended since the last acknowledged
push, tagged with the server incarnation (``base_id``) and the series
append counter (``seq``) — and the server appends them into its stored
per-rank document; a server restart/failover 409s the next delta and
the pusher resyncs with one full snapshot (the same contract as
metrics/push.py).  Through a per-host relay (run/relay.py) deltas are
off: the relay coalesces to the latest full snapshot per rank and
batches upstream, which cannot lose intermediate samples the way a
coalesced delta would.  ``GET /timeseries`` serves the aggregate.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

#: the signal catalogue (docs/observe.md): every series name appended by
#: the runtime.  Kept here so the watchdog, hvd_watch, and the docs
#: enumerate one list.
STEP_SECONDS = "step_seconds"              # train-step cadence (training.py)
MFU_SERIES = "mfu"                         # profiler window MFU
HOST_GAP_US_SERIES = "host_gap_us"         # profiler host-gap per step
DISPATCH_US_PER_MIB = "dispatch_us_per_mib"  # eager collective cost density
SERVE_P99_MS_SERIES = "serve_p99_ms"       # serving windowed p99
RESIDUAL_NORM_SERIES = "residual_norm"     # compression error-feedback norm

KNOWN_SERIES = (
    STEP_SECONDS, MFU_SERIES, HOST_GAP_US_SERIES, DISPATCH_US_PER_MIB,
    SERVE_P99_MS_SERIES, RESIDUAL_NORM_SERIES,
)


class Series:
    """One named signal: tiered rings of ``(step, value)`` samples.

    ``step`` is the caller's logical clock (train step when one exists,
    else the append ordinal) — detectors report windows in it, and the
    auto-arm protocol broadcasts trace windows against it."""

    def __init__(self, cap: int, tiers: int, factor: int) -> None:
        self._lock = threading.Lock()
        self.cap = max(int(cap), 4)
        self.factor = max(int(factor), 2)
        self._tiers: List[deque] = [
            deque(maxlen=self.cap) for _ in range(max(int(tiers), 1))
        ]
        # per-tier fold accumulators: samples waiting to be mean-folded
        # one tier up (each holds < factor entries)
        self._pending: List[List[Tuple[float, float]]] = [
            [] for _ in self._tiers
        ]
        self.seq = 0          # total appends ever (the delta cursor)
        self.last_step = 0

    def append(self, step: Optional[int], value: float) -> None:
        with self._lock:
            self.seq += 1
            s = int(step) if step is not None else self.seq
            self.last_step = s
            v = float(value)
            self._tiers[0].append((s, v))
            # fold up: tier i's pending batch becomes one tier i+1
            # sample (mean value, last step) every `factor` samples
            carry: Optional[Tuple[float, float]] = (s, v)
            for i in range(len(self._tiers) - 1):
                if carry is None:
                    break
                pend = self._pending[i]
                pend.append(carry)
                carry = None
                if len(pend) >= self.factor:
                    mean = sum(p[1] for p in pend) / len(pend)
                    folded = (pend[-1][0], mean)
                    self._tiers[i + 1].append(folded)
                    pend.clear()
                    carry = folded

    def raw_since(self, seq: int) -> Tuple[List[Tuple[float, float]], int]:
        """``(samples, dropped)``: tier-0 samples appended after append
        ordinal ``seq``, plus how many of them aged out of the ring
        before this read (the delta pusher reports the gap instead of
        silently papering over it)."""
        with self._lock:
            gap = self.seq - seq
            if gap <= 0:
                return [], 0
            tier0 = list(self._tiers[0])
            take = min(gap, len(tier0))
            return tier0[len(tier0) - take:], gap - take

    def merged(self) -> List[Tuple[float, float]]:
        """All tiers flattened oldest→newest: coarse history first, the
        raw tail last, deduped where a coarser tier overlaps the finer
        one's span (wire/report form)."""
        with self._lock:
            tiers = [list(t) for t in self._tiers]
        out: List[Tuple[float, float]] = []
        cutoff = tiers[0][0][0] if tiers[0] else None
        for t in reversed(tiers[1:]):
            for s, v in t:
                if cutoff is None or s < cutoff:
                    out.append((s, v))
        out.extend(tiers[0])
        return out

    def snapshot(self) -> dict:
        return {
            "samples": [[s, v] for s, v in self.merged()],
            "seq": self.seq,
            "last_step": self.last_step,
        }


class TimeseriesStore:
    """Process-wide collection of named series (mirrors the metrics
    registry's enabled/singleton shape)."""

    def __init__(self, enabled: Optional[bool] = None,
                 cap: Optional[int] = None, tiers: Optional[int] = None,
                 factor: Optional[int] = None) -> None:
        self._series: Dict[str, Series] = {}
        self._lock = threading.Lock()
        self.enabled = (
            enabled if enabled is not None
            else env_util.get_bool(env_util.HVD_TIMESERIES, True)
        )
        self.cap = cap if cap is not None else env_util.get_int(
            env_util.HVD_TIMESERIES_CAP, env_util.DEFAULT_TIMESERIES_CAP)
        self.tiers = tiers if tiers is not None else env_util.get_int(
            env_util.HVD_TIMESERIES_TIERS,
            env_util.DEFAULT_TIMESERIES_TIERS)
        self.factor = factor if factor is not None else env_util.get_int(
            env_util.HVD_TIMESERIES_FACTOR,
            env_util.DEFAULT_TIMESERIES_FACTOR)

    def series(self, name: str) -> Series:
        s = self._series.get(name)
        if s is None:
            with self._lock:
                s = self._series.setdefault(
                    name, Series(self.cap, self.tiers, self.factor))
        return s

    def record(self, name: str, value: float,
               step: Optional[int] = None) -> None:
        """One sample; never raises (the history must not take down a
        dispatch or a step)."""
        if not self.enabled:
            return
        try:
            self.series(name).append(step, value)
        except Exception as e:  # noqa: BLE001
            log.debug("timeseries append failed: %s", e)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def snapshot(self) -> dict:
        """The full wire form one rank pushes (and the resync body)."""
        return {"series": {n: self.series(n).snapshot()
                           for n in self.names()}}

    def history(self, name: str) -> List[Tuple[float, float]]:
        return self.series(name).merged() if name in self._series else []

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


#: the process-wide store every instrumented layer appends into
store = TimeseriesStore()


def on() -> bool:
    """The hot-path gate: one attribute read."""
    return store.enabled


def record(name: str, value: float, step: Optional[int] = None) -> None:
    store.record(name, value, step=step)


# ---------------------------------------------------------------------------
# flush: per-rank pusher thread (delta protocol mirroring metrics/push.py)
# ---------------------------------------------------------------------------
class TimeseriesPusher(threading.Thread):
    """Ship this rank's history to the launcher's ``timeseries`` scope.

    Each flush also polls the ``observe/arm`` broadcast and applies any
    pending auto-armed trace+profile window (observe/autoarm.py) — the
    worker-side half of the alert→diagnosis loop, deliberately on this
    thread so the step path itself never gains a KV read."""

    def __init__(self, addr: str, port: int, rank: int,
                 secret: Optional[bytes], interval: float) -> None:
        super().__init__(daemon=True, name="hvd-timeseries-pusher")
        self.addr = addr
        self.port = port
        self.rank = rank
        self.secret = secret
        self.interval = max(float(interval), 0.5)
        self._server_id: Optional[str] = None
        self._acked: Dict[str, int] = {}   # series -> acked seq
        self.delta_pushes = 0
        self.full_pushes = 0
        self.resyncs = 0
        self._stop = threading.Event()

    def _delta_body(self) -> Optional[bytes]:
        series = {}
        for name in store.names():
            samples, dropped = store.series(name).raw_since(
                self._acked.get(name, 0))
            if samples or dropped:
                entry = {"samples": [[s, v] for s, v in samples],
                         "seq": store.series(name).seq}
                if dropped:
                    entry["dropped"] = dropped
                series[name] = entry
        if not series:
            return None
        return json.dumps({
            "__tsdelta__": True,
            "base_id": self._server_id,
            "series": series,
        }).encode()

    def push(self) -> bool:
        """One flush; returns success, never raises."""
        import urllib.error

        from ..run import relay
        from ..run.http_client import put_kv_reply

        try:
            ep = relay.control_endpoint()
            via_relay = ep is not None and ep[2]
            use_delta = not via_relay and self._server_id is not None
            reply = None
            if use_delta:
                body = self._delta_body()
                if body is None:
                    return True   # nothing new; skip the round trip
                try:
                    reply = put_kv_reply(
                        self.addr, self.port, "timeseries",
                        str(self.rank), body, secret=self.secret)
                    self.delta_pushes += 1
                    _record_flush("delta")
                except urllib.error.HTTPError as e:
                    if e.code != 409:
                        raise
                    self.resyncs += 1
                    _record_flush("resync")
                    use_delta = False
            if not use_delta:
                snap = store.snapshot()
                body = json.dumps(snap).encode()
                reply = relay.control_put(
                    self.addr, self.port, "timeseries", str(self.rank),
                    body, secret=self.secret, want_reply=True)
                self.full_pushes += 1
                _record_flush("full")
            answered_by_relay = isinstance(reply, dict) \
                and bool(reply.get("relay"))
            sid = reply.get("server_id") if isinstance(reply, dict) else None
            if answered_by_relay or sid is None:
                self._server_id = None
                self._acked = {}
            else:
                self._server_id = sid
                self._acked = {n: store.series(n).seq
                               for n in store.names()}
            return True
        except Exception as e:  # noqa: BLE001 — losing history must
            log.debug("timeseries push failed: %s", e)  # not fail the job
            return False

    def _poll_arm(self) -> None:
        try:
            from ..observe import autoarm

            autoarm.poll_and_apply(self.addr, self.port,
                                   secret=self.secret)
        except Exception as e:  # noqa: BLE001
            log.debug("auto-arm poll failed: %s", e)

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            self.push()
            self._poll_arm()

    def stop(self, final_push: bool = True) -> None:
        self._stop.set()
        if final_push:
            self.push()


def _record_flush(mode: str) -> None:
    try:
        from .. import metrics

        if metrics.on():
            metrics.TIMESERIES_FLUSHES.labels(mode).inc()
    except Exception:  # noqa: BLE001
        pass


_pusher: Optional[TimeseriesPusher] = None
_plock = threading.Lock()


def start_flusher(addr: str, port: int, rank: int,
                  secret: Optional[bytes] = None,
                  interval: float = 5.0) -> TimeseriesPusher:
    global _pusher
    with _plock:
        if _pusher is not None:
            _pusher.stop(final_push=False)
        _pusher = TimeseriesPusher(addr, port, rank, secret, interval)
        _pusher.start()
        return _pusher


def start_flusher_from_env(rank: int) -> Optional[TimeseriesPusher]:
    """Launcher-driven activation (core.init), mirroring
    metrics.push.start_pusher_from_env: no-op unless the launcher set
    the ``HVD_METRICS_KV_*`` wiring and the history is enabled."""
    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port or not store.enabled:
        return None
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    secret = bytes.fromhex(secret_hex) if secret_hex else None
    interval = env_util.get_float(
        env_util.HVD_TIMESERIES_FLUSH_SECONDS,
        env_util.get_float(env_util.HVD_METRICS_PUSH_SECONDS, 5.0))
    return start_flusher(addr, port, rank, secret, interval)


def stop_flusher() -> None:
    global _pusher
    with _plock:
        if _pusher is not None:
            _pusher.stop(final_push=True)
            _pusher = None
