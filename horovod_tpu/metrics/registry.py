"""Process-wide metrics registry: counters, gauges, histograms.

The *live* half of the fork's observability story (the byteprofile/dPRO
layer is the post-mortem half, timeline/): numeric metrics you can scrape
while a job runs.  Prometheus-shaped on purpose — counters are cumulative,
histograms use fixed upper-bound buckets with ``_bucket{le=...}`` /
``_sum`` / ``_count`` exposition — so the text output drops straight into
any Prometheus/Grafana stack; a JSON snapshot form rides the rendezvous
KV store so the launcher can aggregate every rank (run/http_server.py
``GET /metrics``).

Design constraints, in order:

1. **hot-path cost**: instrumented sites sit on the eager dispatch path
   and the training-step cadence.  Every update is one dict lookup on a
   pre-interned label tuple plus a small per-child lock (the GIL makes
   the lock nearly free when uncontended).  Call sites additionally gate
   on ``registry.enabled`` so a disabled registry costs one attribute
   read (the < 2% overhead budget, docs/PERF.md).
2. **thread safety**: the eager plane, the ring dispatcher thread, the
   stall-inspector daemon, and the metrics pusher all touch the registry
   concurrently.
3. **no deps**: text exposition and JSON snapshot are hand-rolled; the
   container must not need prometheus_client.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils import env as env_util

_INF = float("inf")


def exponential_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """``count`` upper bounds growing geometrically from ``start``
    (prometheus_client's ``exponential_buckets`` contract)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


def latency_buckets_from_env(
        floor_var: Optional[str] = None,
        floor_default: Optional[float] = None) -> Tuple[float, ...]:
    """The configurable latency bucket scheme: exponential from a floor.

    The defaults (100 µs floor, ×2, 18 buckets) are tuned for µs-scale
    dispatch spans; workloads on a different latency scale — the serving
    plane's sub-ms..seconds request latencies — pass their own
    ``floor_var`` (e.g. ``HVD_SERVE_LATENCY_BUCKET_FLOOR``) and
    ``floor_default`` so their histograms don't collapse into one
    bucket.  ``HVD_METRICS_BUCKET_{FLOOR,FACTOR,COUNT}`` reshape the
    default scheme job-wide (factor/count are shared by every scheme)."""
    floor = env_util.get_float(
        floor_var or env_util.HVD_METRICS_BUCKET_FLOOR,
        floor_default if floor_default is not None
        else env_util.DEFAULT_METRICS_BUCKET_FLOOR)
    factor = env_util.get_float(env_util.HVD_METRICS_BUCKET_FACTOR,
                                env_util.DEFAULT_METRICS_BUCKET_FACTOR)
    count = env_util.get_int(env_util.HVD_METRICS_BUCKET_COUNT,
                             env_util.DEFAULT_METRICS_BUCKET_COUNT)
    return exponential_buckets(floor, factor, count)


#: default latency buckets: 100 µs .. ~26 s in x2 steps — wide enough to
#: cover eager dispatch (sub-ms) through big-model step times in one
#: scheme; reshaped by HVD_METRICS_BUCKET_{FLOOR,FACTOR,COUNT} (read at
#: import — set them before the first ``import horovod_tpu``)
LATENCY_BUCKETS = latency_buckets_from_env()

#: payload-size buckets: 64 B .. 4 GB in x8 steps
BYTES_BUCKETS = exponential_buckets(64.0, 8.0, 10)


def _fmt(v: float) -> str:
    """Prometheus sample value formatting: integers render bare."""
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    if isinstance(v, float) and v != v:  # NaN
        return "NaN"
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Child:
    """One labeled time series.

    ``ts`` is the child's last-mutation wall time — snapshot() folds it
    into the family-level ``updated`` stamp so consumers (the observe/
    watchdog, GET /metrics.json) can tell a stale *family* apart from a
    stale snapshot.  Stamped inside the existing per-update lock: one
    extra ``time.time()`` per update, well inside the hot-path budget.
    """

    __slots__ = ("_lock", "value", "ts")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0
        self.ts = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount
            self.ts = time.time()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)
            self.ts = time.time()

    def get(self) -> float:
        return self.value


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count", "ts")

    def __init__(self, buckets: Tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * len(buckets)  # per-bucket, NON-cumulative
        self.sum = 0.0
        self.count = 0
        self.ts = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.sum += v
            self.count += 1
            self.ts = time.time()
            # linear scan: bucket lists are short (<= ~20) and the scan
            # usually exits in the first few entries for latency data
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    self.counts[i] += 1
                    break


class Metric:
    """A named family of children keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def _new_child(self):
        return _Child()

    def labels(self, *values, **kv):
        """The child for one label-value combination (created on first
        use, then cached — call sites may hold the returned child)."""
        if kv:
            values = tuple(str(kv[k]) for k in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}"
            )
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._new_child())
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; use .labels()"
            )
        return self.labels()

    def samples(self) -> List[Tuple[Dict[str, str], object]]:
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, vals)), child)
                for vals, child in items]


class Counter(Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def get(self, *values, **kv) -> float:
        if values or kv or not self.labelnames:
            return self.labels(*values, **kv).get()
        raise ValueError(f"{self.name}: label values required")


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().inc(-amount)

    def get(self, *values, **kv) -> float:
        return self.labels(*values, **kv).get() if (values or kv) \
            else self._default().get()


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None) -> None:
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(float(b) for b in (buckets or LATENCY_BUCKETS)))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)


class MetricsRegistry:
    """Thread-safe registry of metric families.

    ``enabled`` gates the instrumented call sites (they check it before
    touching any child); the registry itself always works so tests and
    the exposition path never need special cases.  Collector callbacks
    run at snapshot time — the hook for pull-style gauges (controller
    cycle counters, stall-inspector queue depth) that would be wasteful
    to push on every event.
    """

    def __init__(self, enabled: Optional[bool] = None) -> None:
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.RLock()
        self._collectors: Dict[str, Callable[[], None]] = {}
        self.enabled = (
            enabled if enabled is not None
            else env_util.get_bool(env_util.HVD_METRICS, True)
        )

    # -- registration -------------------------------------------------------
    def _register(self, cls, name, help, labelnames, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != cls.kind or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with different "
                        f"kind/labels ({m.kind}{m.labelnames} vs "
                        f"{cls.kind}{tuple(labelnames)})"
                    )
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    def register_collector(self, key: str, fn: Callable[[], None]) -> None:
        """Pre-snapshot callback; keyed so re-registration replaces (the
        stall-inspector singleton re-registers across hvd.init cycles)."""
        with self._lock:
            self._collectors[key] = fn

    def unregister_collector(self, key: str) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    # -- export -------------------------------------------------------------
    def _run_collectors(self) -> None:
        with self._lock:
            fns = list(self._collectors.values())
        for fn in fns:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a broken collector must
                pass           # never take down the scrape

    def snapshot(self) -> dict:
        """JSON-able state: the wire form ranks push to the launcher."""
        # a disabled registry is silent end to end: call sites don't
        # push, and pull-gauges don't refresh (their ``updated`` stamp
        # would otherwise tick on every scrape)
        if self.enabled:
            self._run_collectors()
        out: Dict[str, dict] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            samples = []
            updated = 0.0
            for labels, child in m.samples():
                if child.ts > updated:
                    updated = child.ts
                if m.kind == "histogram":
                    with child._lock:
                        samples.append({
                            "labels": labels,
                            "buckets": list(child.counts),
                            "sum": child.sum,
                            "count": child.count,
                        })
                else:
                    samples.append({"labels": labels, "value": child.get()})
            entry = {"type": m.kind, "help": m.help, "samples": samples}
            if m.kind == "histogram":
                entry["le"] = list(m.buckets)
            # per-family staleness stamp (None = registered but never
            # updated): lets GET /metrics.json consumers and the observe/
            # watchdog flag one dead signal inside an otherwise-fresh
            # snapshot, instead of trusting the snapshot-level ts alone
            entry["updated"] = updated or None
            out[m.name] = entry
        return {"metrics": out, "ts": time.time()}

    def to_prometheus(self, extra_labels: Optional[Dict[str, str]] = None) -> str:
        """This registry's state in Prometheus text exposition format."""
        return render_prometheus([(extra_labels or {}, self.snapshot())])

    def dump(self, path: str) -> None:
        """Write the JSON snapshot (the per-rank ``metrics.json`` artifact
        landing next to ``comm.json`` in the trace dir)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1)

    def reset(self) -> None:
        """Zero every family's samples (tests).  Families are kept —
        module-level instruments hold references to them, so dropping
        the objects would silently disconnect all instrumentation from
        the registry; clearing children resets values while `.labels()`
        keeps repopulating the same live families."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                m._children.clear()


def render_prometheus(
    snapshots: Sequence[Tuple[Dict[str, str], dict]],
) -> str:
    """Merge one or more JSON snapshots into a single valid Prometheus
    text page: one ``# HELP``/``# TYPE`` block per metric family even
    when every rank contributes samples (``extra_labels`` — typically
    ``{"rank": N}`` — distinguishes them).  This is what the rendezvous
    server's ``GET /metrics`` serves for the whole job."""
    # family name -> (type, help, [ (labels, sample_dict, le) ... ])
    families: Dict[str, list] = {}
    order: List[str] = []
    for extra, snap in snapshots:
        for name, entry in (snap.get("metrics") or {}).items():
            fam = families.get(name)
            if fam is None:
                families[name] = fam = [entry.get("type", "untyped"),
                                        entry.get("help", ""), []]
                order.append(name)
            for s in entry.get("samples", ()):
                labels = dict(s.get("labels") or {})
                labels.update(extra)
                fam[2].append((labels, s, entry.get("le")))
    lines: List[str] = []
    for name in order:
        kind, help_s, samples = families[name]
        if help_s:
            lines.append(f"# HELP {name} {help_s}")
        lines.append(f"# TYPE {name} {kind}")
        for labels, s, le in samples:
            if kind == "histogram":
                counts = s.get("buckets") or []
                cum = 0
                for ub, n in zip(le or [], counts):
                    cum += n
                    bl = dict(labels)
                    bl["le"] = _fmt(float(ub))
                    lines.append(f"{name}_bucket{_label_str(bl)} {cum}")
                bl = dict(labels)
                bl["le"] = "+Inf"
                lines.append(
                    f"{name}_bucket{_label_str(bl)} {s.get('count', 0)}"
                )
                lines.append(
                    f"{name}_sum{_label_str(labels)} "
                    f"{_fmt(float(s.get('sum', 0.0)))}"
                )
                lines.append(
                    f"{name}_count{_label_str(labels)} {s.get('count', 0)}"
                )
            else:
                lines.append(
                    f"{name}{_label_str(labels)} "
                    f"{_fmt(float(s.get('value', 0.0)))}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


#: the process-wide registry every instrumented layer reports into
registry = MetricsRegistry()
