"""Fused optimizer update: one flattened elementwise kernel per step.

The compute-anatomy profiler (PR 9) attributes a steady ~9% of the
ResNet-50 step to ``optimizer_update`` — not because the math is heavy
(SGD-momentum is 3 flops/param) but because the optax path traverses the
parameter pytree per leaf: hundreds of tiny elementwise kernels, each
paying dispatch + HBM round-trip overhead on tensors far below the VPU's
efficient tile size.  This module is the fused alternative: the gradient
and parameter pytrees are flattened into ONE contiguous buffer per dtype
and the whole update (momentum/Adam moments included) runs as a single
elementwise kernel over it — Pallas on TPU, a jnp expression off-TPU
that is bit-identical (same elementwise ops in the same order), with a
NumPy oracle for the tests (the ``numpy_adasum`` pattern, ops/adasum.py).

Three rules, matching optax expression-for-expression so parity is
pinned, not approximate:

* ``sgd``        — ``p += (-lr) * g``
* ``momentum``   — ``t = m*t + g;  p += (-lr) * t`` (optax ``trace``)
* ``adam``       — optax ``scale_by_adam`` with the same
  ``(1-b)·g + b·m`` moment updates and ``1 - b**count`` bias correction

The optimizer state is the flat layout itself
(:class:`FusedOptState`: per-dtype flat moment buffers + step count), so
the fused and per-leaf paths share ONE state pytree and the autotuner's
``fused_optimizer`` knob can flip between them through the re-jit seam
without a state migration.  ``update()`` (optax-compatible signature,
per-leaf traversal — the A side of the A/B) and :meth:`fused_update`
(the fused kernel — the B side) compute identical numbers.

Donation safety: the fused path writes fresh buffers from the flat
views; it never aliases into the (possibly donated) inputs, so a
``donate_argnums`` train state cannot observe a stale buffer
(tests/test_fused_update.py pins this against a non-donated run).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import env as env_util

#: the supported update rules (KIND values)
SGD, MOMENTUM, ADAM = "sgd", "momentum", "adam"

#: flat buffers are blocked [rows, _LANES] for the Pallas path
_LANES = 128
#: per-buffer VMEM budget, same sizing rule as ops/elementwise.py
_BLOCK_BYTES = 2 << 20


class FusedOptState(NamedTuple):
    """Flat optimizer state: ``count`` plus per-dtype-group moment
    buffers keyed like the parameter groups (``{dtype_name: flat}``).
    SGD carries empty dicts — the structure is still fixed, so
    ``lax.scan`` carries and elastic rebuilds keep one shape."""

    count: jnp.ndarray          # int32 scalar, optax-style step counter
    mu: Dict[str, Any]          # first moment / momentum trace, or {}
    nu: Dict[str, Any]          # second moment (adam only), or {}


# ---------------------------------------------------------------------------
# flat layout
# ---------------------------------------------------------------------------
def _group_leaves(tree) -> Tuple[Dict[str, List[int]], List[Any], Any]:
    """Leaves grouped by dtype name (one fused buffer per dtype — mixed
    f32/bf16 parameter trees each get their own kernel).  Returns
    ``(groups, leaves, treedef)`` with groups mapping dtype name to leaf
    indices in flatten order."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: Dict[str, List[int]] = {}
    for i, leaf in enumerate(leaves):
        groups.setdefault(jnp.asarray(leaf).dtype.name, []).append(i)
    return groups, leaves, treedef


def flatten_by_dtype(tree) -> Tuple[Dict[str, jnp.ndarray], Any]:
    """``{dtype_name: 1-D flat buffer}`` plus the metadata needed to
    invert it (:func:`unflatten_by_dtype`)."""
    groups, leaves, treedef = _group_leaves(tree)
    flat = {
        name: jnp.concatenate(
            [jnp.ravel(leaves[i]) for i in idxs]) if idxs else None
        for name, idxs in groups.items()
    }
    meta = (groups, [jnp.shape(l) for l in leaves], treedef)
    return flat, meta


def unflatten_by_dtype(flat: Dict[str, jnp.ndarray], meta):
    groups, shapes, treedef = meta
    leaves: List[Any] = [None] * len(shapes)
    for name, idxs in groups.items():
        buf = flat[name]
        offset = 0
        for i in idxs:
            size = int(np.prod(shapes[i], dtype=np.int64)) if shapes[i] \
                else 1
            leaves[i] = jnp.reshape(buf[offset:offset + size], shapes[i])
            offset += size
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# the update math — ONE definition per rule, returning the optax-style
# UPDATE (delta) plus new moments.  Both runtime paths (fused jnp and
# per-leaf) consume THESE, which is what makes the fused_optimizer
# knob-flip bit-equal by construction; the Pallas kernels and the NumPy
# oracle are independent twins of the same expressions, pinned against
# this definition by tests/test_fused_update.py.
# ---------------------------------------------------------------------------
def _sgd_update(g, lr):
    return (-lr) * g


def _momentum_update(g, t, lr, m):
    t = m * t + g
    return (-lr) * t, t


def _adam_update(g, mu, nu, lr, b1, b2, eps, inv_bc1, inv_bc2):
    """optax ``scale_by_adam`` expression order: moments as
    ``(1-b)·g + b·m``, hats via the precomputed ``1/(1-b**count)``."""
    mu = (1.0 - b1) * g + b1 * mu
    nu = (1.0 - b2) * (g * g) + b2 * nu
    step = (mu * inv_bc1) / (jnp.sqrt(nu * inv_bc2) + eps)
    return (-lr) * step, mu, nu


# -- Pallas kernels (same math over [rows, 128] VMEM blocks) ----------------
def _sgd_kernel(lr, p_ref, g_ref, o_ref):
    o_ref[...] = p_ref[...] + (-lr) * g_ref[...]


def _momentum_kernel(lr, m, p_ref, g_ref, t_ref, o_ref, tn_ref):
    t = m * t_ref[...] + g_ref[...]
    tn_ref[...] = t
    o_ref[...] = p_ref[...] + (-lr) * t


def _adam_kernel(lr, b1, b2, eps, p_ref, g_ref, mu_ref, nu_ref, bc_ref,
                 o_ref, mun_ref, nun_ref):
    g = g_ref[...]
    mu = (1.0 - b1) * g + b1 * mu_ref[...]
    nu = (1.0 - b2) * (g * g) + b2 * nu_ref[...]
    mun_ref[...] = mu
    nun_ref[...] = nu
    mu_hat = mu * bc_ref[0, 0]
    nu_hat = nu * bc_ref[0, 1]
    o_ref[...] = p_ref[...] + (-lr) * (mu_hat / (jnp.sqrt(nu_hat) + eps))


def _pad_rows(flat: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    n = flat.shape[0]
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, _LANES), n


def _pallas_elementwise(kernel, flats, n_out: int, *, scalars=()):
    """Run ``kernel`` over same-length flat buffers blocked to
    [rows, 128]; ``scalars`` (each a [1, 128] row, e.g. the Adam bias
    corrections) are appended after the flats and broadcast whole to
    every block.  Returns ``n_out`` flat buffers trimmed back to the
    unpadded length."""
    from jax.experimental import pallas as pl

    from ..ops.flash_attention import _resolve_interpret

    blocked, n = [], None
    for a in flats:
        b2, n = _pad_rows(a)
        blocked.append(b2)
    rows = blocked[0].shape[0]
    dtype = blocked[0].dtype
    cap = max(8, _BLOCK_BYTES // (_LANES * dtype.itemsize))
    block = min(cap, rows)
    in_specs = [pl.BlockSpec((block, _LANES), lambda i: (i, 0))
                for _ in blocked]
    in_specs += [pl.BlockSpec((1, _LANES), lambda i: (0, 0))
                 for _ in scalars]
    outs = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, block),),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((block, _LANES), lambda i: (i, 0))
                   for _ in range(n_out)],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), dtype)
                   for _ in range(n_out)],
        interpret=_resolve_interpret(None),
    )(*blocked, *scalars)
    return [o.reshape(-1)[:n] for o in outs]


def _resolve_pallas(use_pallas: Optional[bool]) -> bool:
    """Pallas on real TPU, jnp elsewhere (interpret mode would be pure
    overhead); ``HVD_FUSED_UPDATE_PALLAS`` forces either way (the tests
    force it on to pin pallas-vs-jnp bit identity on CPU)."""
    env = env_util.get_str(env_util.HVD_FUSED_UPDATE_PALLAS)
    if env is not None:
        return env_util.parse_bool(env)
    if use_pallas is not None:
        return use_pallas
    from ..ops.flash_attention import _on_tpu

    return _on_tpu()


# ---------------------------------------------------------------------------
# the optimizer
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class FusedOptimizer:
    """A fusable SGD/momentum/Adam optimizer with optax-compatible
    surface (``init`` / ``update``) plus the fused entry
    (:meth:`fused_update`) the training step's ``HVD_FUSED_OPTIMIZER``
    path dispatches — both over one shared flat state layout."""

    kind: str = SGD
    learning_rate: float = 0.01
    momentum: float = 0.0
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    use_pallas: Optional[bool] = None

    def __post_init__(self):
        if self.kind not in (SGD, MOMENTUM, ADAM):
            raise ValueError(f"unknown fused optimizer kind {self.kind!r}")

    # -- state ---------------------------------------------------------------
    def init(self, params) -> FusedOptState:
        flat, _ = flatten_by_dtype(params)
        zeros = {k: jnp.zeros_like(v) for k, v in flat.items()}
        if self.kind == SGD:
            mu, nu = {}, {}
        elif self.kind == MOMENTUM:
            mu, nu = zeros, {}
        else:
            mu = zeros
            nu = {k: jnp.zeros_like(v) for k, v in flat.items()}
        return FusedOptState(count=jnp.zeros((), jnp.int32), mu=mu, nu=nu)

    # -- the fused path (one kernel per dtype group) -------------------------
    def fused_update(self, grads, state: FusedOptState, params):
        """``(new_params, new_state)`` — flatten, one elementwise kernel
        per dtype group, unflatten.  No per-leaf traversal."""
        pf, meta = flatten_by_dtype(params)
        gf, _ = flatten_by_dtype(grads)
        count = state.count + 1
        pallas = _resolve_pallas(self.use_pallas)
        new_p: Dict[str, jnp.ndarray] = {}
        new_mu: Dict[str, jnp.ndarray] = {}
        new_nu: Dict[str, jnp.ndarray] = {}
        for name, p in pf.items():
            g = gf[name].astype(p.dtype)
            lr = p.dtype.type(self.learning_rate)
            if self.kind == SGD:
                if pallas:
                    (o,) = _pallas_elementwise(
                        partial(_sgd_kernel, lr), [p, g], 1)
                else:
                    o = p + _sgd_update(g, lr)
                new_p[name] = o
            elif self.kind == MOMENTUM:
                m = p.dtype.type(self.momentum)
                if pallas:
                    o, t = _pallas_elementwise(
                        partial(_momentum_kernel, lr, m),
                        [p, g, state.mu[name]], 2)
                else:
                    u, t = _momentum_update(g, state.mu[name], lr, m)
                    o = p + u
                new_p[name], new_mu[name] = o, t
            else:
                inv_bc1, inv_bc2 = self._bias_corrections(count, p.dtype)
                if pallas:
                    bc = jnp.zeros((1, _LANES), p.dtype)
                    bc = bc.at[0, 0].set(inv_bc1).at[0, 1].set(inv_bc2)
                    o, mu, nu = _pallas_elementwise(
                        partial(_adam_kernel, lr, p.dtype.type(self.b1),
                                p.dtype.type(self.b2),
                                p.dtype.type(self.eps)),
                        [p, g, state.mu[name], state.nu[name]],
                        3, scalars=[bc])
                else:
                    u, mu, nu = _adam_update(
                        g, state.mu[name], state.nu[name], lr,
                        p.dtype.type(self.b1), p.dtype.type(self.b2),
                        p.dtype.type(self.eps), inv_bc1, inv_bc2)
                    o = p + u
                new_p[name], new_mu[name], new_nu[name] = o, mu, nu
        return (unflatten_by_dtype(new_p, meta),
                FusedOptState(count=count, mu=new_mu, nu=new_nu))

    def _bias_corrections(self, count, dtype):
        c = count.astype(jnp.float32)
        inv1 = (1.0 / (1.0 - jnp.power(jnp.float32(self.b1), c))).astype(dtype)
        inv2 = (1.0 / (1.0 - jnp.power(jnp.float32(self.b2), c))).astype(dtype)
        return inv1, inv2

    # -- the per-leaf reference path (optax-compatible) ----------------------
    def update(self, grads, state: FusedOptState, params=None):
        """optax signature: ``(updates, new_state)`` with per-leaf
        traversal — the unfused A side the autotuner's knob compares
        against.  Same math, same flat state layout."""
        del params
        gf_tree_groups, g_leaves, treedef = _group_leaves(grads)
        count = state.count + 1
        upd_leaves: List[Any] = [None] * len(g_leaves)
        new_mu: Dict[str, jnp.ndarray] = {}
        new_nu: Dict[str, jnp.ndarray] = {}
        for name, idxs in gf_tree_groups.items():
            # per-leaf views of the flat moment buffers
            sizes = [int(np.prod(jnp.shape(g_leaves[i]), dtype=np.int64))
                     if jnp.shape(g_leaves[i]) else 1 for i in idxs]
            offs = np.concatenate([[0], np.cumsum(sizes)])
            dtype = jnp.asarray(g_leaves[idxs[0]]).dtype
            lr = dtype.type(self.learning_rate)
            mu_parts, nu_parts = [], []
            for j, i in enumerate(idxs):
                g = g_leaves[i]
                shape = jnp.shape(g)
                if self.kind == SGD:
                    upd_leaves[i] = _sgd_update(g, lr)
                elif self.kind == MOMENTUM:
                    t = state.mu[name][offs[j]:offs[j + 1]].reshape(shape)
                    upd_leaves[i], t = _momentum_update(
                        g, t, lr, dtype.type(self.momentum))
                    mu_parts.append(jnp.ravel(t))
                else:
                    mu = state.mu[name][offs[j]:offs[j + 1]].reshape(shape)
                    nu = state.nu[name][offs[j]:offs[j + 1]].reshape(shape)
                    inv_bc1, inv_bc2 = self._bias_corrections(count, dtype)
                    upd_leaves[i], mu, nu = _adam_update(
                        g, mu, nu, lr, dtype.type(self.b1),
                        dtype.type(self.b2), dtype.type(self.eps),
                        inv_bc1, inv_bc2)
                    mu_parts.append(jnp.ravel(mu))
                    nu_parts.append(jnp.ravel(nu))
            if mu_parts:
                new_mu[name] = jnp.concatenate(mu_parts)
            if nu_parts:
                new_nu[name] = jnp.concatenate(nu_parts)
        updates = jax.tree_util.tree_unflatten(treedef, upd_leaves)
        return updates, FusedOptState(count=count, mu=new_mu, nu=new_nu)

    # -- test twins ----------------------------------------------------------
    @property
    def reference(self):
        """The exact optax construction this rule mirrors (parity
        oracle for the tests — NOT used on any runtime path)."""
        import optax

        if self.kind == ADAM:
            return optax.adam(self.learning_rate, b1=self.b1, b2=self.b2,
                              eps=self.eps)
        return optax.sgd(self.learning_rate,
                         momentum=self.momentum or None)


def fused_sgd(learning_rate: float, momentum: float = 0.0,
              **kw) -> FusedOptimizer:
    return FusedOptimizer(kind=MOMENTUM if momentum else SGD,
                          learning_rate=learning_rate, momentum=momentum,
                          **kw)


def fused_adam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8, **kw) -> FusedOptimizer:
    return FusedOptimizer(kind=ADAM, learning_rate=learning_rate, b1=b1,
                          b2=b2, eps=eps, **kw)


# ---------------------------------------------------------------------------
# NumPy oracle (the adasum pattern: pure numpy, used only by tests)
# ---------------------------------------------------------------------------
def numpy_fused_update(opt: FusedOptimizer, params, grads,
                       state: Optional[dict] = None):
    """Reference implementation over numpy pytrees.  ``state`` is
    ``{"count": int, "mu": {leaf_path_index: array}, ...}`` keyed by
    flatten order; returns ``(new_params, new_state)``."""
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    g_leaves = jax.tree_util.tree_flatten(grads)[0]
    if state is None:
        state = {"count": 0,
                 "mu": [np.zeros_like(np.asarray(p)) for p in p_leaves],
                 "nu": [np.zeros_like(np.asarray(p)) for p in p_leaves]}
    count = state["count"] + 1
    out, mus, nus = [], [], []
    for i, (p, g) in enumerate(zip(p_leaves, g_leaves)):
        p = np.asarray(p)
        g = np.asarray(g, p.dtype)
        lr = p.dtype.type(opt.learning_rate)
        if opt.kind == SGD:
            out.append(p + (-lr) * g)
            mus.append(state["mu"][i])
            nus.append(state["nu"][i])
        elif opt.kind == MOMENTUM:
            m = p.dtype.type(opt.momentum)
            t = m * state["mu"][i] + g
            out.append(p + (-lr) * t)
            mus.append(t)
            nus.append(state["nu"][i])
        else:
            b1 = p.dtype.type(opt.b1)
            b2 = p.dtype.type(opt.b2)
            mu = (1 - b1) * g + b1 * state["mu"][i]
            nu = (1 - b2) * (g * g) + b2 * state["nu"][i]
            mu_hat = mu / (1 - np.float32(opt.b1) ** count)
            nu_hat = nu / (1 - np.float32(opt.b2) ** count)
            out.append(p + (-lr) * (mu_hat / (np.sqrt(nu_hat)
                                              + p.dtype.type(opt.eps))))
            mus.append(mu)
            nus.append(nu)
    return (jax.tree_util.tree_unflatten(treedef, out),
            {"count": count, "mu": mus, "nu": nus})
