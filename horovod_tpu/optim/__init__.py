from .distributed import (  # noqa: F401
    DistributedOptimizer,
    DistributedGradientTape,
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_variables,
)
