from .distributed import (  # noqa: F401
    DistributedOptimizer,
    DistributedGradientTape,
    broadcast_parameters,
    broadcast_optimizer_state,
    broadcast_variables,
)
from . import profile_guided  # noqa: F401
FusionPlanSpec = profile_guided.FusionPlanSpec
ProfileGuidedTuner = profile_guided.ProfileGuidedTuner
plan_from_summary = profile_guided.plan_from_summary
plan_from_trace = profile_guided.plan_from_trace
warm_start_manager = profile_guided.warm_start_manager
from .fused_update import (  # noqa: E402,F401
    FusedOptimizer,
    fused_adam,
    fused_sgd,
)
from .compute_knobs import (  # noqa: E402,F401
    COMPUTE_AUTOTUNE_EXPECTED,
    compute_plans_from_anatomy,
)
