"""Online autotuning of communication knobs via Bayesian optimization.

Re-design of the reference autotuner (horovod/common/parameter_manager.cc/.h:
joint Bayesian optimization of fusion-threshold + cycle-time plus
categorical hierarchical-allreduce/allgather/cache flags, scored by
bytes/sec, warmup-discard + steps-per-sample batching, winning params
synced to all ranks; GP + expected-improvement machinery in
horovod/common/optim/{bayesian_optimization.cc, gaussian_process.cc}).

TPU translation (SURVEY §7.3(2)): the knobs that matter under XLA are the
**gradient bucket size** (ops/fusion.py threshold) and **hierarchical vs
flat** allreduce — the double-batching interaction with XLA's own combiner
is exactly why the autotuner owns both.  Cycle time has no analog (no
background negotiation loop on the hot path).  Re-tuning triggers a re-jit
(shapes of fused buckets change), which is the compiled-world equivalent of
the reference's "new parameters take effect next cycle".

Pure NumPy GP (RBF kernel + jitter, Cholesky solves) — no SciPy needed.
"""

from __future__ import annotations

import dataclasses
import os
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)


class GaussianProcessRegressor:
    """RBF-kernel GP regression (reference optim/gaussian_process.cc)."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-6,
                 signal_var: float = 1.0):
        self.length_scale = length_scale
        self.noise = noise
        self.signal_var = signal_var
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).reshape(-1)
        self._ymean = y.mean() if y.size else 0.0
        self._ystd = y.std() if y.size and y.std() > 0 else 1.0
        yn = (y - self._ymean) / self._ystd
        k = self._kernel(x, x) + self.noise * np.eye(len(x))
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn)
        )
        self._x, self._y = x, yn

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.ones(len(x))
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.clip(
            self.signal_var + self.noise - (v ** 2).sum(0), 1e-12, None
        )
        return mu * self._ystd + self._ymean, np.sqrt(var) * self._ystd


def expected_improvement(mu: np.ndarray, sigma: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (reference optim/bayesian_optimization.cc)."""
    from math import erf, sqrt

    z = (mu - best - xi) / np.maximum(sigma, 1e-12)
    phi = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
    Phi = 0.5 * (1.0 + np.vectorize(erf)(z / np.sqrt(2)))
    return (mu - best - xi) * Phi + sigma * phi


class BayesianOptimization:
    """Sequential EI maximization over a normalized box with optional
    categorical dimensions enumerated exhaustively.

    Prior points (``observe_prior``) live on their own list because the
    warm-start model scores in different units than live observations
    (the α–β prior predicts comm-only bytes/sec; ``record_step`` scores
    whole-step bytes/sec, compute included, typically orders of
    magnitude smaller).  Mixing them raw would let the prior win every
    argmax and make real measurements unable to override the model.
    ``set_prior_scale`` anchors the prior into live units (the
    ParameterManager sets it from the first live sample); until the
    scale is known, priors are used alone (scale cancels in an argmax
    over priors only) and dropped from any mix with live data."""

    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 noise: float = 1e-3, seed: int = 0):
        self.bounds = np.asarray(bounds, np.float64)
        self.gp = GaussianProcessRegressor(length_scale=0.3, noise=noise)
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []
        self.prior_xs: List[np.ndarray] = []
        self.prior_ys: List[float] = []
        self.prior_scale: Optional[float] = None
        self._rng = np.random.default_rng(seed)

    def _norm(self, x):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (np.asarray(x, np.float64) - lo) / np.maximum(hi - lo, 1e-12)

    def _denorm(self, u):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + np.asarray(u) * (hi - lo)

    def _merged(self) -> Tuple[List[np.ndarray], List[float]]:
        if self.prior_ys and (self.prior_scale is not None or not self.ys):
            s = self.prior_scale if self.prior_scale is not None else 1.0
            return (self.prior_xs + self.xs,
                    [y * s for y in self.prior_ys] + self.ys)
        return self.xs, self.ys

    def _refit(self) -> None:
        xs, ys = self._merged()
        if xs:
            self.gp.fit(np.stack(xs), np.asarray(ys))

    def observe(self, x, y: float) -> None:
        self.xs.append(self._norm(x))
        self.ys.append(float(y))
        self._refit()

    def observe_prior(self, x, y: float) -> None:
        self.prior_xs.append(self._norm(x))
        self.prior_ys.append(float(y))
        self._refit()

    def prior_at(self, x) -> Optional[float]:
        """Raw (unscaled) prior value at the prior point nearest ``x`` —
        the anchor the ParameterManager rescales against."""
        if not self.prior_xs:
            return None
        u = self._norm(x)
        d = [float(((u - p) ** 2).sum()) for p in self.prior_xs]
        return self.prior_ys[int(np.argmin(d))]

    def set_prior_scale(self, s: float) -> None:
        self.prior_scale = float(s)
        self._refit()

    def suggest(self, n_candidates: int = 256):
        xs, ys = self._merged()
        if len(xs) < 2:
            return self._denorm(self._rng.uniform(size=len(self.bounds)))
        cand = self._rng.uniform(size=(n_candidates, len(self.bounds)))
        mu, sigma = self.gp.predict(cand)
        ei = expected_improvement(mu, sigma, max(ys))
        return self._denorm(cand[int(np.argmax(ei))])

    def best(self):
        # Live observations only: the prior scale anchors ONE point into
        # live units, so elsewhere on the curve a scaled prior can still
        # outrank every real measurement — the final argmax must never
        # pin a never-measured model prediction (priors shape suggest()'s
        # EI, nothing more).  Priors alone are the fallback when nothing
        # was measured at all.
        xs, ys = (self.xs, self.ys) if self.ys else self._merged()
        if not xs:
            return None, None
        i = int(np.argmax(ys))
        return self._denorm(xs[i]), ys[i]


@dataclass
class TunableParams:
    """The knob set (reference ParameterManager's tunables, translated).

    The GP encoding is split in two, and the split is part of the
    contract:

    * :meth:`as_vector` — the CONTINUOUS dimensions only (today: log2 of
      the fusion threshold).  Categorical flags are deliberately NOT
      encoded here: an RBF kernel over a {0,1} coordinate would smear
      observations across categories that share nothing.
    * :meth:`category` — the categorical coordinates
      (``hierarchical_allreduce`` plus the compute knobs below), which
      select WHICH per-category GP an observation lands in (the
      reference enumerates categorical combinations the same way).  A
      flipped flag therefore always maps to a different GP; it can
      never silently share one.

    **Compute knobs** (the PR 9→14 compute tier, docs/PERF.md):
    ``fused_optimizer`` selects the flat fused update kernel over the
    per-leaf optax traversal (optim/fused_update.py) and
    ``remat_policy`` rematerializes the loss closure
    (none/full/dots).  Both default to ``None`` = *knob absent*: a job
    whose optimizer isn't fusable (or that never opts into remat) keeps
    exactly the legacy ``(hierarchical,)`` category key, so pre-compute
    GP state and tests are untouched.  A non-None value appends a
    ``(name, value)`` coordinate — distinct per value, so flipping
    ``fused_optimizer`` can never share observations with any other
    category's fusion-threshold GP.

    ``fusion_plan`` pins an explicit profile-guided plan
    (optim/profile_guided.py FusionPlanSpec): while set, the plan's
    bucket vector (and its ``compute`` knob dict) overrides the scalar
    knobs in the training step's rebuild, and the GP loop is paused
    (the planner owns the knobs).
    """

    fusion_threshold_bytes: int = env_util.DEFAULT_FUSION_THRESHOLD_BYTES
    hierarchical_allreduce: bool = False
    fused_optimizer: Optional[bool] = None
    remat_policy: Optional[str] = None
    fusion_plan: Optional[object] = None

    #: dimension inventory backing the split (documentation + tests)
    CONTINUOUS_DIMS = ("fusion_threshold_bytes",)
    CATEGORICAL_DIMS = ("hierarchical_allreduce", "fused_optimizer",
                        "remat_policy")

    def as_vector(self) -> np.ndarray:
        # log2 of threshold in MB-ish units for a smooth GP landscape;
        # continuous dims ONLY — see the class docstring
        return np.array([np.log2(max(self.fusion_threshold_bytes, 1024))],
                        np.float64)

    def category(self) -> Tuple:
        """The per-category-GP key (one GP per value of this tuple).
        Absent (None) compute knobs contribute no coordinate — the key
        stays backward compatible with the comm-only era."""
        cat: list = [bool(self.hierarchical_allreduce)]
        if self.fused_optimizer is not None:
            cat.append(("fused_optimizer", bool(self.fused_optimizer)))
        if self.remat_policy is not None:
            cat.append(("remat_policy", str(self.remat_policy)))
        return tuple(cat)


class ParameterManager:
    """Collects per-step (bytes, time) scores and tunes the knobs.

    Mirrors the reference flow (parameter_manager.cc): discard
    ``warmup_samples``, average ``steps_per_sample`` steps per observation,
    observe score = bytes/sec, move to the next suggestion; after
    ``bayes_opt_max_samples`` observations, freeze at the best.  The
    categorical hierarchical flag is handled by running a separate GP per
    category (the reference enumerates categorical combinations the same
    way).  ``on_update(params)`` fires when the active knobs change so the
    training step can re-build (re-jit) its fusion plan.
    """

    def __init__(
        self,
        *,
        enabled: Optional[bool] = None,
        warmup_samples: Optional[int] = None,
        steps_per_sample: Optional[int] = None,
        max_samples: Optional[int] = None,
        log_file: Optional[str] = None,
        on_update: Optional[Callable[[TunableParams], None]] = None,
        tune_hierarchical: bool = True,
        tune_fused_optimizer: bool = False,
        tune_remat: bool = False,
        initial: Optional[TunableParams] = None,
    ):
        self.enabled = enabled if enabled is not None else \
            env_util.get_bool(env_util.HVD_AUTOTUNE)
        self.warmup_samples = warmup_samples if warmup_samples is not None \
            else env_util.get_int(env_util.HVD_AUTOTUNE_WARMUP_SAMPLES, 3)
        self.steps_per_sample = steps_per_sample if steps_per_sample is not None \
            else env_util.get_int(env_util.HVD_AUTOTUNE_STEPS_PER_SAMPLE, 10)
        # resolved AFTER the category rotation is built (below): the
        # default budget is per-category, so opting into the compute
        # dims doesn't silently starve every GP
        self._max_samples_arg = max_samples
        noise = env_util.get_float(
            env_util.HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8
        )
        self.log_file = log_file or env_util.get_str(env_util.HVD_AUTOTUNE_LOG)
        self.on_update = on_update

        # log2(threshold bytes) in [log2(1MB), log2(256MB)]; one GP per
        # categorical combination (TunableParams.category) — the
        # explicit split a flipped flag can't cross
        self._noise = noise
        self.current = initial if initial is not None else TunableParams()
        # proposal rotation: the product of every TUNED dim's settings,
        # untuned dims pinned at the initial value — an untuned flag
        # must never be flipped by the rotation (tune_hierarchical=False
        # with hierarchical=True would otherwise alternate the flag
        # every sample, re-jitting and overriding the caller's pin).
        # Compute knobs only enter the product when explicitly tuned
        # (tune_fused_optimizer / tune_remat) — a knob a job can't
        # apply (no FusedOptimizer) must stay pinned at None/absent.
        import itertools

        hier_vals = [False, True] if tune_hierarchical \
            else [bool(self.current.hierarchical_allreduce)]
        fused_vals = [False, True] if tune_fused_optimizer \
            else [self.current.fused_optimizer]
        # "none" (not None) when tuned: None means *knob absent* and
        # would read as "leave unchanged" at the training rebuild seam.
        # The current value always joins the rotation — a caller pinned
        # to a custom policy must stay reachable, not be overridden by
        # the first proposal and lost from every category.
        remat_vals = list(dict.fromkeys(
            ["none", "full", "dots", self.current.remat_policy or "none"])) \
            if tune_remat else [self.current.remat_policy]
        self._category_knobs: List[dict] = [
            {"hierarchical_allreduce": h, "fused_optimizer": f,
             "remat_policy": r}
            for h, f, r in itertools.product(hier_vals, fused_vals,
                                             remat_vals)
        ]
        self._categories: List[Tuple] = [
            TunableParams(**k).category() for k in self._category_knobs
        ]
        # normalize the INITIAL params onto the rotation's coordinates:
        # with a compute dim tuned, an absent (None) knob would key an
        # orphan category no proposal ever revisits — the first
        # (default-config) observation must land in the rotation's
        # matching category, not start that category cold
        if tune_fused_optimizer and self.current.fused_optimizer is None:
            self.current = dataclasses.replace(self.current,
                                               fused_optimizer=False)
        if tune_remat and self.current.remat_policy is None:
            self.current = dataclasses.replace(self.current,
                                               remat_policy="none")
        self._bo = {
            cat: BayesianOptimization([(20.0, 28.0)], noise=noise, seed=17 + i)
            for i, cat in enumerate(self._categories)
        }
        self._knobs_by_cat = dict(zip(self._categories,
                                      self._category_knobs))
        # the sample budget scales with the rotation (default 10 real
        # observations per category — the 2-category comm-only default
        # stays exactly the reference's 20): freezing 8+ categories on
        # a fixed global 20 would leave ~2 noisy samples each
        if self._max_samples_arg is not None:
            self.max_samples = self._max_samples_arg
        else:
            self.max_samples = env_util.get_int(
                env_util.HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 0) \
                or 10 * len(self._categories)
        self._cat_idx = 0
        self._plan_prev_frozen: Optional[bool] = None
        self._samples_seen = 0
        self._warmup_left = self.warmup_samples
        self._step_scores: List[float] = []
        self.frozen = not self.enabled
        self._log_header_written = False

        # Prefer the native state machine (csrc/autotune.cc — the analog of
        # the reference's C++ parameter_manager + optim/ GP); the NumPy
        # implementation above stays as the fallback and the test oracle.
        # Compute-knob rotations stay on the python path: the native
        # machine's category table predates them.
        self._native = None
        self._native_lib = None
        if self.enabled and not env_util.get_bool("HVD_AUTOTUNE_PYTHON") \
                and not (tune_fused_optimizer or tune_remat):
            try:
                from ..runtime import native

                self._native_lib = native.load()
                self._native = self._native_lib.hvd_tuner_create(
                    20.0, 28.0, float(self.current.as_vector()[0]),
                    len(self._categories), float(noise),
                    int(self.warmup_samples), int(self.steps_per_sample),
                    int(self.max_samples), 17,
                )
            except Exception as e:  # noqa: BLE001
                log.warning("native autotuner unavailable (%s); python path", e)
                self._native = None

    # -- scoring ------------------------------------------------------------
    def record_step(self, nbytes: float, seconds: float) -> None:
        """Feed one training step's communication volume and duration
        (reference scores bytes/sec over all tensors in the cycle)."""
        if self.frozen:
            return
        if seconds <= 0:
            return
        if self._native is not None:
            changed = self._native_lib.hvd_tuner_record(
                self._native, float(nbytes), float(seconds)
            )
            if changed:
                x = self._native_lib.hvd_tuner_x(self._native)
                cat = self._native_lib.hvd_tuner_category(self._native)
                self._set_params(self._params_for(
                    self._categories[cat], int(2 ** float(x))))
                self._log(self._native_lib.hvd_tuner_last_score(self._native))
            if self._native_lib.hvd_tuner_frozen(self._native):
                self.frozen = True
                log.info(
                    "autotune frozen (native): threshold=%d hierarchical=%s "
                    "(score %.3g)", self.current.fusion_threshold_bytes,
                    self.current.hierarchical_allreduce,
                    self._native_lib.hvd_tuner_best_score(self._native),
                )
            return
        self._step_scores.append(nbytes / seconds)
        if len(self._step_scores) >= self.steps_per_sample:
            self._finish_sample()

    def _finish_sample(self) -> None:
        score = float(np.median(self._step_scores))
        self._step_scores = []
        if self._warmup_left > 0:
            self._warmup_left -= 1
            return
        # the observation lands in the GP selected by the CURRENT params'
        # categorical coordinates — not by loop position, so a flag that
        # moved out-of-band still scores against its own surface (an
        # unseen category gets its own GP without joining the proposal
        # rotation — scoring must never start flipping an untuned flag)
        cat = self.current.category()
        bo = self._bo.get(cat)
        if bo is None:
            bo = self._bo[cat] = BayesianOptimization(
                [(20.0, 28.0)], noise=self._noise, seed=17 + len(self._bo))
            # remember the out-of-band knob values so _freeze can map
            # this category's best back to concrete params
            self._knobs_by_cat[cat] = {
                k: getattr(self.current, k)
                for k in TunableParams.CATEGORICAL_DIMS}
        if bo.prior_ys and bo.prior_scale is None:
            # anchor the warm-start prior into live units: the model's
            # prediction at the point we just measured is declared equal
            # to the measurement, so the prior contributes its SHAPE but
            # can never outrank reality by unit mismatch alone.  One
            # scale for every category (same score_fn units).
            ref = bo.prior_at(self.current.as_vector())
            if ref and ref > 0 and score > 0:
                for b in self._bo.values():
                    b.set_prior_scale(score / ref)
        bo.observe(self.current.as_vector(), score)
        self._log(score)
        self._samples_seen += 1
        if self._samples_seen >= self.max_samples:
            self._freeze()
            return
        # round-robin categories; suggest next threshold within category
        self._cat_idx = (self._cat_idx + 1) % len(self._categories)
        nxt_cat = self._categories[self._cat_idx]
        vec = self._bo[nxt_cat].suggest()
        self._set_params(self._params_for(nxt_cat, int(2 ** float(vec[0]))))

    def _params_for(self, cat: Tuple, threshold: int) -> TunableParams:
        """Concrete params for one category key + threshold, preserving
        any pinned knob values the key doesn't encode."""
        knobs = self._knobs_by_cat.get(cat) or {
            k: getattr(self.current, k)
            for k in TunableParams.CATEGORICAL_DIMS}
        return TunableParams(fusion_threshold_bytes=threshold, **knobs)

    def _freeze(self) -> None:
        best_cat, best_vec, best_y = None, None, -np.inf
        for cat, bo in self._bo.items():
            vec, y = bo.best()
            if y is not None and y > best_y:
                best_cat, best_vec, best_y = cat, vec, y
        if best_vec is not None:
            self._set_params(self._params_for(
                best_cat, int(2 ** float(best_vec[0]))))
        self.frozen = True
        log.info("autotune frozen: threshold=%d hierarchical=%s (score %.3g)",
                 self.current.fusion_threshold_bytes,
                 self.current.hierarchical_allreduce, best_y)

    # -- profile-guided seams ------------------------------------------------
    def warm_start(self, score_fn: Callable[[TunableParams], float],
                   n_points: int = 8) -> int:
        """Seed every per-category GP with ``score_fn``'s predicted score
        over a threshold grid (optim/profile_guided.py feeds the α–β
        model's bytes/sec here), so Bayesian exploration starts near the
        simulator's predicted optimum instead of at a random draw.  Prior
        points do NOT consume the ``max_samples`` budget — warm-started
        runs converge in fewer real observations — and they live on the
        GP's separate prior list: the first live sample anchors their
        scale into measured units (comm-only model bytes/sec vs
        whole-step live bytes/sec differ by orders of magnitude), so the
        model contributes shape, never an unbeatable score.  Returns the
        number of prior points injected."""
        if self._native is not None:
            log.info("autotune warm start: falling back to the python "
                     "tuner (the native state machine takes no priors)")
            self._native = None
        injected = 0
        for cat, bo in self._bo.items():
            lo, hi = bo.bounds[0]
            for x in np.linspace(lo, hi, n_points):
                p = self._params_for(cat, int(2 ** float(x)))
                try:
                    y = float(score_fn(p))
                except Exception as e:  # noqa: BLE001
                    log.warning("warm start scorer failed at %s: %s", p, e)
                    continue
                if np.isfinite(y):
                    bo.observe_prior(p.as_vector(), y)
                    injected += 1
        return injected

    def apply_plan(self, plan) -> None:
        """Pin an explicit profile-guided fusion plan: fires
        ``on_update`` with the plan attached and pauses GP exploration
        (the planner owns the knobs until :meth:`clear_plan`)."""
        if self._plan_prev_frozen is None:
            self._plan_prev_frozen = self.frozen
        self.frozen = True
        self._set_params(dataclasses.replace(self.current, fusion_plan=plan))

    def clear_plan(self) -> None:
        """Roll the pinned plan back to threshold bucketing; GP
        exploration resumes in whatever state it was paused in."""
        if self.current.fusion_plan is None:
            return
        self._set_params(dataclasses.replace(self.current, fusion_plan=None))
        if self._plan_prev_frozen is not None:
            self.frozen = self._plan_prev_frozen
            self._plan_prev_frozen = None

    def _set_params(self, p: TunableParams) -> None:
        changed = (
            p.fusion_threshold_bytes != self.current.fusion_threshold_bytes
            or p.hierarchical_allreduce != self.current.hierarchical_allreduce
            or p.fused_optimizer != self.current.fused_optimizer
            or p.remat_policy != self.current.remat_policy
            or p.fusion_plan is not self.current.fusion_plan
        )
        self.current = p
        if changed and self.on_update:
            self.on_update(p)

    def _log(self, score: float) -> None:
        if not self.log_file:
            return
        new = not os.path.exists(self.log_file) and not self._log_header_written
        with open(self.log_file, "a") as f:
            if new:
                f.write("timestamp,fusion_threshold,hierarchical,score_bytes_per_sec\n")
                self._log_header_written = True
            f.write(f"{time.time()},{self.current.fusion_threshold_bytes},"
                    f"{int(self.current.hierarchical_allreduce)},{score}\n")
