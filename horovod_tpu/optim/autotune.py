"""Online autotuning of communication knobs via Bayesian optimization.

Re-design of the reference autotuner (horovod/common/parameter_manager.cc/.h:
joint Bayesian optimization of fusion-threshold + cycle-time plus
categorical hierarchical-allreduce/allgather/cache flags, scored by
bytes/sec, warmup-discard + steps-per-sample batching, winning params
synced to all ranks; GP + expected-improvement machinery in
horovod/common/optim/{bayesian_optimization.cc, gaussian_process.cc}).

TPU translation (SURVEY §7.3(2)): the knobs that matter under XLA are the
**gradient bucket size** (ops/fusion.py threshold) and **hierarchical vs
flat** allreduce — the double-batching interaction with XLA's own combiner
is exactly why the autotuner owns both.  Cycle time has no analog (no
background negotiation loop on the hot path).  Re-tuning triggers a re-jit
(shapes of fused buckets change), which is the compiled-world equivalent of
the reference's "new parameters take effect next cycle".

Pure NumPy GP (RBF kernel + jitter, Cholesky solves) — no SciPy needed.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)


class GaussianProcessRegressor:
    """RBF-kernel GP regression (reference optim/gaussian_process.cc)."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-6,
                 signal_var: float = 1.0):
        self.length_scale = length_scale
        self.noise = noise
        self.signal_var = signal_var
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).reshape(-1)
        self._ymean = y.mean() if y.size else 0.0
        self._ystd = y.std() if y.size and y.std() > 0 else 1.0
        yn = (y - self._ymean) / self._ystd
        k = self._kernel(x, x) + self.noise * np.eye(len(x))
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn)
        )
        self._x, self._y = x, yn

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return np.zeros(len(x)), np.ones(len(x))
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.clip(
            self.signal_var + self.noise - (v ** 2).sum(0), 1e-12, None
        )
        return mu * self._ystd + self._ymean, np.sqrt(var) * self._ystd


def expected_improvement(mu: np.ndarray, sigma: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (reference optim/bayesian_optimization.cc)."""
    from math import erf, sqrt

    z = (mu - best - xi) / np.maximum(sigma, 1e-12)
    phi = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
    Phi = 0.5 * (1.0 + np.vectorize(erf)(z / np.sqrt(2)))
    return (mu - best - xi) * Phi + sigma * phi


class BayesianOptimization:
    """Sequential EI maximization over a normalized box with optional
    categorical dimensions enumerated exhaustively."""

    def __init__(self, bounds: Sequence[Tuple[float, float]],
                 noise: float = 1e-3, seed: int = 0):
        self.bounds = np.asarray(bounds, np.float64)
        self.gp = GaussianProcessRegressor(length_scale=0.3, noise=noise)
        self.xs: List[np.ndarray] = []
        self.ys: List[float] = []
        self._rng = np.random.default_rng(seed)

    def _norm(self, x):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (np.asarray(x, np.float64) - lo) / np.maximum(hi - lo, 1e-12)

    def _denorm(self, u):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + np.asarray(u) * (hi - lo)

    def observe(self, x, y: float) -> None:
        self.xs.append(self._norm(x))
        self.ys.append(float(y))
        self.gp.fit(np.stack(self.xs), np.asarray(self.ys))

    def suggest(self, n_candidates: int = 256):
        if len(self.xs) < 2:
            return self._denorm(self._rng.uniform(size=len(self.bounds)))
        cand = self._rng.uniform(size=(n_candidates, len(self.bounds)))
        mu, sigma = self.gp.predict(cand)
        ei = expected_improvement(mu, sigma, max(self.ys))
        return self._denorm(cand[int(np.argmax(ei))])

    def best(self):
        if not self.xs:
            return None, None
        i = int(np.argmax(self.ys))
        return self._denorm(self.xs[i]), self.ys[i]


@dataclass
class TunableParams:
    """The knob set (reference ParameterManager's tunables, translated)."""

    fusion_threshold_bytes: int = env_util.DEFAULT_FUSION_THRESHOLD_BYTES
    hierarchical_allreduce: bool = False

    def as_vector(self) -> np.ndarray:
        # log2 of threshold in MB-ish units for a smooth GP landscape
        return np.array([np.log2(max(self.fusion_threshold_bytes, 1024))],
                        np.float64)


class ParameterManager:
    """Collects per-step (bytes, time) scores and tunes the knobs.

    Mirrors the reference flow (parameter_manager.cc): discard
    ``warmup_samples``, average ``steps_per_sample`` steps per observation,
    observe score = bytes/sec, move to the next suggestion; after
    ``bayes_opt_max_samples`` observations, freeze at the best.  The
    categorical hierarchical flag is handled by running a separate GP per
    category (the reference enumerates categorical combinations the same
    way).  ``on_update(params)`` fires when the active knobs change so the
    training step can re-build (re-jit) its fusion plan.
    """

    def __init__(
        self,
        *,
        enabled: Optional[bool] = None,
        warmup_samples: Optional[int] = None,
        steps_per_sample: Optional[int] = None,
        max_samples: Optional[int] = None,
        log_file: Optional[str] = None,
        on_update: Optional[Callable[[TunableParams], None]] = None,
        tune_hierarchical: bool = True,
        initial: Optional[TunableParams] = None,
    ):
        self.enabled = enabled if enabled is not None else \
            env_util.get_bool(env_util.HVD_AUTOTUNE)
        self.warmup_samples = warmup_samples if warmup_samples is not None \
            else env_util.get_int(env_util.HVD_AUTOTUNE_WARMUP_SAMPLES, 3)
        self.steps_per_sample = steps_per_sample if steps_per_sample is not None \
            else env_util.get_int(env_util.HVD_AUTOTUNE_STEPS_PER_SAMPLE, 10)
        self.max_samples = max_samples if max_samples is not None \
            else env_util.get_int(env_util.HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20)
        noise = env_util.get_float(
            env_util.HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8
        )
        self.log_file = log_file or env_util.get_str(env_util.HVD_AUTOTUNE_LOG)
        self.on_update = on_update

        # log2(threshold bytes) in [log2(1MB), log2(256MB)]
        self._categories = [False, True] if tune_hierarchical else [False]
        self._bo = {
            cat: BayesianOptimization([(20.0, 28.0)], noise=noise, seed=17 + i)
            for i, cat in enumerate(self._categories)
        }
        self._cat_idx = 0
        self.current = initial if initial is not None else TunableParams()
        self._samples_seen = 0
        self._warmup_left = self.warmup_samples
        self._step_scores: List[float] = []
        self.frozen = not self.enabled
        self._log_header_written = False

        # Prefer the native state machine (csrc/autotune.cc — the analog of
        # the reference's C++ parameter_manager + optim/ GP); the NumPy
        # implementation above stays as the fallback and the test oracle.
        self._native = None
        self._native_lib = None
        if self.enabled and not env_util.get_bool("HVD_AUTOTUNE_PYTHON"):
            try:
                from ..runtime import native

                self._native_lib = native.load()
                self._native = self._native_lib.hvd_tuner_create(
                    20.0, 28.0, float(self.current.as_vector()[0]),
                    len(self._categories), float(noise),
                    int(self.warmup_samples), int(self.steps_per_sample),
                    int(self.max_samples), 17,
                )
            except Exception as e:  # noqa: BLE001
                log.warning("native autotuner unavailable (%s); python path", e)
                self._native = None

    # -- scoring ------------------------------------------------------------
    def record_step(self, nbytes: float, seconds: float) -> None:
        """Feed one training step's communication volume and duration
        (reference scores bytes/sec over all tensors in the cycle)."""
        if self.frozen:
            return
        if seconds <= 0:
            return
        if self._native is not None:
            changed = self._native_lib.hvd_tuner_record(
                self._native, float(nbytes), float(seconds)
            )
            if changed:
                x = self._native_lib.hvd_tuner_x(self._native)
                cat = self._native_lib.hvd_tuner_category(self._native)
                self._set_params(TunableParams(
                    fusion_threshold_bytes=int(2 ** float(x)),
                    hierarchical_allreduce=self._categories[cat],
                ))
                self._log(self._native_lib.hvd_tuner_last_score(self._native))
            if self._native_lib.hvd_tuner_frozen(self._native):
                self.frozen = True
                log.info(
                    "autotune frozen (native): threshold=%d hierarchical=%s "
                    "(score %.3g)", self.current.fusion_threshold_bytes,
                    self.current.hierarchical_allreduce,
                    self._native_lib.hvd_tuner_best_score(self._native),
                )
            return
        self._step_scores.append(nbytes / seconds)
        if len(self._step_scores) >= self.steps_per_sample:
            self._finish_sample()

    def _finish_sample(self) -> None:
        score = float(np.median(self._step_scores))
        self._step_scores = []
        if self._warmup_left > 0:
            self._warmup_left -= 1
            return
        cat = self._categories[self._cat_idx]
        self._bo[cat].observe(self.current.as_vector(), score)
        self._log(score)
        self._samples_seen += 1
        if self._samples_seen >= self.max_samples:
            self._freeze()
            return
        # round-robin categories; suggest next threshold within category
        self._cat_idx = (self._cat_idx + 1) % len(self._categories)
        nxt_cat = self._categories[self._cat_idx]
        vec = self._bo[nxt_cat].suggest()
        self._set_params(TunableParams(
            fusion_threshold_bytes=int(2 ** float(vec[0])),
            hierarchical_allreduce=nxt_cat,
        ))

    def _freeze(self) -> None:
        best_cat, best_vec, best_y = None, None, -np.inf
        for cat, bo in self._bo.items():
            vec, y = bo.best()
            if y is not None and y > best_y:
                best_cat, best_vec, best_y = cat, vec, y
        if best_vec is not None:
            self._set_params(TunableParams(
                fusion_threshold_bytes=int(2 ** float(best_vec[0])),
                hierarchical_allreduce=bool(best_cat),
            ))
        self.frozen = True
        log.info("autotune frozen: threshold=%d hierarchical=%s (score %.3g)",
                 self.current.fusion_threshold_bytes,
                 self.current.hierarchical_allreduce, best_y)

    def _set_params(self, p: TunableParams) -> None:
        changed = (
            p.fusion_threshold_bytes != self.current.fusion_threshold_bytes
            or p.hierarchical_allreduce != self.current.hierarchical_allreduce
        )
        self.current = p
        if changed and self.on_update:
            self.on_update(p)

    def _log(self, score: float) -> None:
        if not self.log_file:
            return
        new = not os.path.exists(self.log_file) and not self._log_header_written
        with open(self.log_file, "a") as f:
            if new:
                f.write("timestamp,fusion_threshold,hierarchical,score_bytes_per_sec\n")
                self._log_header_written = True
            f.write(f"{time.time()},{self.current.fusion_threshold_bytes},"
                    f"{int(self.current.hierarchical_allreduce)},{score}\n")
