"""Profile-guided tuning: close the replay→autotune loop.

PR 3's replay engine can rank what-ifs for a measured step DAG and PR 1's
autotuner can move the fusion knobs — this module connects them into the
loop the reference fork exists for (PAPER.md §0: dPRO auto-profiling
layered on Horovod's ``parameter_manager``): every trace window,

1. **analyze** — the stitcher + simulator replay *this job's* measured
   step DAG and emit ranked scenarios, the ``fuse_buckets_<k>`` ones
   carrying machine-readable plan payloads
   (timeline/replay/simulator.py ``bucket_plan_search``);
2. **plan** — :func:`plan_from_summary` translates the winning scenario
   into a :class:`FusionPlanSpec`: explicit per-tensor fusion buckets in
   dispatch order (the overlap schedule) plus a cycle/flush cadence;
3. **apply** — the plan goes live through the existing
   ``ParameterManager.on_update`` re-jit seam (``apply_plan`` pins the
   bucket vector; training.py rebuilds the SPMD step with
   ``named_buckets``);
4. **verify** — the next window's realized step time is compared against
   the simulator's prediction; both sides are exported as the
   ``hvd_autotune_{predicted,realized}_speedup`` gauges and pushed to
   the rendezvous ``GET /autotune`` table, and a realized speedup more
   than ``HVD_AUTOTUNE_GUARD_BAND_PCT`` below the prediction triggers
   automatic rollback to threshold bucketing.

The GP side is warm-started from the same α–β model
(:func:`warm_start_manager` seeds every per-category GP with
``predict_collective_us``-derived scores) so Bayesian exploration starts
near the simulator's predicted optimum instead of at a random draw.

``scripts/hvd_autotune.py`` drives the same pipeline offline (trace dir
in → recommended plan out) and ``--check`` replays the hand-computed
fixture (timeline/replay/fixture.py ``AUTOTUNE_EXPECTED``) end to end.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, List, Optional

import numpy as np

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

#: scenarios the planner may apply: only the ones whose plan payload maps
#: to concrete knobs (overlap_comm / remove_straggler / bandwidth are
#: diagnostics — there is no knob that buys them)
PLANNABLE_PREFIX = "fuse_buckets_"


@dataclasses.dataclass
class FusionPlanSpec:
    """A concrete, applicable tuning decision derived from replay.

    ``buckets`` is the vector-of-buckets knob: tensor names grouped into
    explicit fusion buckets, listed in dispatch order — bucket 0 goes on
    the wire first, which is the overlap schedule (early gradients
    transfer while later compute still runs).  ``compression`` is the
    per-bucket wire-format knob (ops/compression.py registry names
    aligned with ``buckets``; None entries ride uncompressed) — the
    simulator's staged choice search fills it, and training.py applies
    it through ``allreduce_pytree(bucket_compression=...)`` with error
    feedback, so compression decisions verify and roll back through the
    SAME guard-band machinery as fusion decisions.
    ``cycle_flush_steps`` is the flush cadence: how many optimizer
    steps a *verified* plan stays pinned before the tuner re-measures
    and re-plans from a fresh trace window (the compiled-world analog
    of the reference's cycle time; 0 pins the plan for the rest of the
    job).

    ``compute`` carries the compute-knob decisions of the compute tier
    (optim/compute_knobs.py): ``{"fused_optimizer": bool,
    "remat_policy": str, "loss_fetch_steps": int}`` entries override
    the training step's defaults in the SAME rebuild
    (training.py ``_rebuild``), so a compute decision is applied,
    verified, and rolled back through the machinery fusion decisions
    already use.  A compute-only plan has ``buckets == []`` — the
    threshold bucketing stays untouched."""

    buckets: List[List[str]]
    overlap: bool = True
    compression: Optional[List[Optional[str]]] = None
    compute: Optional[dict] = None
    cycle_flush_steps: int = 0
    predicted_step_us: float = 0.0
    baseline_step_us: float = 0.0
    predicted_speedup_pct: float = 0.0
    source_step: Optional[int] = None
    plan_id: int = 0

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FusionPlanSpec":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def plan_from_what_if(wi: dict, *, step: Optional[int] = None,
                      baseline_us: Optional[float] = None
                      ) -> Optional[FusionPlanSpec]:
    """The best *implementable* scenario of one step's what-if report as
    a :class:`FusionPlanSpec` (None when nothing plannable beats the
    baseline)."""
    best = None
    for sc in wi.get("scenarios", ()):
        if not str(sc.get("scenario", "")).startswith(PLANNABLE_PREFIX):
            continue
        if "plan" not in sc:
            continue
        if best is None or sc["predicted_step_us"] < best["predicted_step_us"]:
            best = sc
    if best is None:
        return None
    base = baseline_us if baseline_us is not None \
        else float(wi.get("baseline_replay_us", 0.0))
    plan = best["plan"]
    comp = plan.get("compression")
    return FusionPlanSpec(
        buckets=[list(b) for b in plan["buckets"]],
        overlap=bool(plan.get("overlap", True)),
        compression=[c if c else None for c in comp]
        if comp is not None else None,
        predicted_step_us=float(best["predicted_step_us"]),
        baseline_step_us=base,
        predicted_speedup_pct=float(best.get("speedup_pct", 0.0)),
        source_step=step,
    )


def plan_from_summary(summary: dict) -> Optional[FusionPlanSpec]:
    """The best plan across every replayed step of an ``analyze()``
    summary — the step whose winning bucket plan predicts the largest
    speedup wins (plans are per-DAG-shape; SPMD steps share one shape,
    so any step's plan applies to all)."""
    best: Optional[FusionPlanSpec] = None
    for s in summary.get("steps", ()):
        p = plan_from_what_if(s.get("what_if", {}), step=s.get("step"))
        if p is None:
            continue
        if best is None or p.predicted_speedup_pct > best.predicted_speedup_pct:
            best = p
    return best


def plan_from_trace(trace_dir: str, *, cost_model=None,
                    step: Optional[int] = None) -> Optional[FusionPlanSpec]:
    """Offline entry (scripts/hvd_autotune.py): stitch + replay a trace
    dir and return the recommended plan."""
    from ..timeline.replay import analyze

    return plan_from_summary(
        analyze(trace_dir, step=step, cost_model=cost_model).summary)


# ---------------------------------------------------------------------------
# GP warm start from the α–β model
# ---------------------------------------------------------------------------
def predicted_score_fn(total_grad_bytes: float, world: int, *,
                       ici_bytes_per_sec: Optional[float] = None,
                       hop_latency_us: Optional[float] = None
                       ) -> Callable:
    """A ``TunableParams -> predicted bytes/sec`` scorer built on the
    shared cost model: threshold ``t`` splits the gradient volume into
    ``ceil(bytes / t)`` buckets, each paying one α, all sharing the β of
    the total payload (``predict_collective_us`` with ``calls`` = bucket
    count) — the same arithmetic the what-if simulator prices fusion
    with, so the GP's prior optimum and the simulator's agree."""
    from ..timeline.comm_report import predict_collective_us

    bw = ici_bytes_per_sec if ici_bytes_per_sec is not None else \
        env_util.get_float(env_util.HVD_REPLAY_ICI_GBPS, 186.0) * 1e9
    hop = hop_latency_us if hop_latency_us is not None else \
        env_util.get_float(env_util.HVD_REPLAY_HOP_US, 1.0)
    nbytes = max(float(total_grad_bytes), 1.0)

    def score(params) -> float:
        buckets = max(int(math.ceil(
            nbytes / max(params.fusion_threshold_bytes, 1))), 1)
        t_us = predict_collective_us(
            "all-reduce", int(nbytes), max(world, 2), calls=buckets,
            ici_bytes_per_sec=bw, ici_hop_latency=hop * 1e-6)
        return nbytes / (t_us * 1e-6) if t_us > 0 else 0.0

    return score


def warm_start_manager(pm, total_grad_bytes: float,
                       world: Optional[int] = None,
                       n_points: int = 8, **model_kw) -> int:
    """Seed ``pm``'s per-category GPs with the α–β model's predicted
    scores (see :func:`predicted_score_fn`).  Returns the number of
    prior points injected."""
    if world is None:
        from .. import core

        world = core.size() if core.is_initialized() else 2
    return pm.warm_start(
        predicted_score_fn(total_grad_bytes, world, **model_kw),
        n_points=n_points)


# ---------------------------------------------------------------------------
# the in-job closed loop
# ---------------------------------------------------------------------------
class ProfileGuidedTuner:
    """The in-job loop: measure a window → plan from replay → apply →
    verify → keep or roll back.

    ``analyze_fn()`` must return an ``analyze()``-shaped summary dict (or
    None when the trace isn't ready yet — the tuner simply retries at the
    next window boundary).  ``apply_fn(plan_or_None)`` makes the plan
    live (None restores threshold bucketing); training.py routes it
    through ``ParameterManager.apply_plan`` so the re-jit seam is shared
    with classic autotuning.

    ``on_step(step_seconds)`` is the only hot-path surface: one float
    append per step, no device synchronization inside the tuner
    (training.py feeds dispatch-to-dispatch intervals and adds its own
    per-step result sync while the loop is active, so both the baseline
    and the verify window measure honest serialized step time — the
    same trade the GP path makes while tuning).

    Multi-process jobs must not let ranks decide differently (a rank
    that rolls back while another keeps its plan dispatches a different
    collective sequence → hang or sanitizer divergence).  ``window_sync``
    (window µs → process-mean) and ``plan_sync`` (plan dict or None →
    process 0's choice) make every rank see one measurement and one
    decision — the PG analog of the GP path's "synchronize the
    measurement instead of the decision" allreduce; here the plan is an
    object, so the decision itself is broadcast too.
    """

    PHASE_BASELINE = "baseline"
    PHASE_VERIFY = "verify"
    PHASE_STEADY = "steady"
    PHASE_FROZEN = "frozen"

    def __init__(self, *, analyze_fn: Callable[[], Optional[dict]],
                 apply_fn: Callable[[Optional[FusionPlanSpec]], None],
                 anatomy_fn: Optional[Callable[[], Optional[dict]]] = None,
                 fused_available: bool = True,
                 active_compute: Optional[dict] = None,
                 window_steps: Optional[int] = None,
                 guard_band_pct: Optional[float] = None,
                 rollback: Optional[bool] = None,
                 min_speedup_pct: float = 1.0,
                 max_plan_attempts: int = 8,
                 cycle_flush_steps: Optional[int] = None,
                 window_sync: Optional[Callable[[float], float]] = None,
                 plan_sync: Optional[Callable[[Optional[dict]],
                                              Optional[dict]]] = None,
                 plan_root: bool = True,
                 push_target: Optional[tuple] = None):
        self.analyze_fn = analyze_fn
        self.apply_fn = apply_fn
        self.anatomy_fn = anatomy_fn
        self.fused_available = fused_available
        #: compute knobs the job's BASE config already has on — a plan
        #: proposing one of these would be a no-op that is guaranteed
        #: to miss its prediction, get condemned, and waste two
        #: measure windows plus a re-jit (training.py fills this from
        #: the resolved fused/loss-fetch defaults)
        self.active_compute = dict(active_compute or {})
        self.window_steps = window_steps if window_steps is not None else \
            env_util.get_int(env_util.HVD_AUTOTUNE_WINDOW_STEPS,
                             env_util.DEFAULT_AUTOTUNE_WINDOW_STEPS)
        self.guard_band_pct = guard_band_pct if guard_band_pct is not None \
            else env_util.get_float(env_util.HVD_AUTOTUNE_GUARD_BAND_PCT,
                                    env_util.DEFAULT_AUTOTUNE_GUARD_BAND_PCT)
        self.rollback_enabled = rollback if rollback is not None else \
            env_util.get_bool(env_util.HVD_AUTOTUNE_ROLLBACK, True)
        self.min_speedup_pct = min_speedup_pct
        self.max_plan_attempts = max_plan_attempts
        self.cycle_flush_steps = cycle_flush_steps \
            if cycle_flush_steps is not None else env_util.get_int(
                env_util.HVD_AUTOTUNE_CYCLE_FLUSH_STEPS,
                env_util.DEFAULT_AUTOTUNE_CYCLE_FLUSH_STEPS)
        self.window_sync = window_sync
        self.plan_sync = plan_sync
        self.plan_root = plan_root
        self.push_target = push_target
        self.phase = self.PHASE_BASELINE
        self.plan: Optional[FusionPlanSpec] = None
        self.baseline_us: Optional[float] = None
        self.history: List[dict] = []
        self._window: List[float] = []
        self._plan_seq = 0
        self._plan_attempts = 0
        self._steady_left = 0
        # compute-tier bookkeeping (optim/compute_knobs.py): knobs a
        # verified plan pinned, knobs condemned by a rollback, and the
        # last verified plan a compute regression falls back to
        self._verified_compute: dict = {}
        self._condemned_compute: set = set()
        self._last_good_plan: Optional[FusionPlanSpec] = None
        # flight-recorder: the apply event roots the plan's causal
        # chain — verify/rollback chain onto it (observe/events.py)
        self._apply_event_id: Optional[str] = None

    @property
    def active(self) -> bool:
        return self.phase != self.PHASE_FROZEN

    @property
    def measuring(self) -> bool:
        """True in the phases that collect step intervals — the steady
        (plan-pinned) phase only counts steps, so callers must not pay
        measurement-honesty syncs for it."""
        return self.phase in (self.PHASE_BASELINE, self.PHASE_VERIFY)

    # -- hot path ------------------------------------------------------------
    def on_step(self, step_seconds: float) -> None:
        if not self.active or step_seconds <= 0:
            return
        if self.phase == self.PHASE_STEADY:
            # verified plan pinned for its flush cadence; when it runs
            # out, re-measure a fresh baseline (with the plan still
            # applied) and re-plan from the current trace — the adaptive
            # cycle the reference's cycle-time knob times
            self._steady_left -= 1
            if self._steady_left <= 0:
                self.phase = self.PHASE_BASELINE
                self._window = []
                self._plan_attempts = 0
            return
        self._window.append(float(step_seconds))
        if len(self._window) < self.window_steps:
            return
        window_us = float(np.median(self._window)) * 1e6
        self._window = []
        if self.window_sync is not None:
            # every process scores the same process-mean window, so the
            # phase machine below transitions identically on all ranks
            window_us = float(self.window_sync(window_us))
        if self.phase == self.PHASE_BASELINE:
            self._plan_window(window_us)
        elif self.phase == self.PHASE_VERIFY:
            self._verify_window(window_us)

    # -- plan ----------------------------------------------------------------
    def _plan_window(self, baseline_us: float) -> None:
        self.baseline_us = baseline_us
        self._plan_attempts += 1
        if self.plan_sync is not None and not self.plan_root:
            # the decision is process 0's broadcast below — stitching the
            # whole trace and running the bucket search here would be
            # thrown away, so non-root ranks only join the broadcast
            plan = None
        else:
            try:
                summary = self.analyze_fn()
            except Exception as e:  # noqa: BLE001
                log.debug("profile-guided analyze failed (%s); retrying next "
                          "window", e)
                summary = None
            plan = plan_from_summary(summary) if summary else None
            # the compute tier: knob candidates priced from the
            # profiler's per-block anatomy compete with the comm plan
            # on the same predicted-speedup scale
            cplan = self._best_compute_plan()
            if cplan is not None and (
                    plan is None
                    or cplan.predicted_speedup_pct
                    > plan.predicted_speedup_pct
                    # a comm re-plan landing on the plan already
                    # running would only be retained — spend the
                    # window on the next compute knob instead
                    or self._same_plan(plan, self.plan)):
                plan = cplan
            elif plan is not None and self._verified_compute:
                # a comm plan must re-assert the compute knobs earlier
                # windows verified: the training rebuild is whole-state,
                # so a plan without them would silently revert verified
                # optimizations (and they'd stay excluded forever)
                plan.compute = {**self._verified_compute,
                                **(plan.compute or {})}
        if self.plan_sync is not None:
            # unconditional (all ranks must join the broadcast): process
            # 0's plan-or-None wins, so a trace that flushed late on one
            # rank can't leave it bucketing differently from its peers
            d = self.plan_sync(plan.to_dict() if plan is not None else None)
            plan = FusionPlanSpec.from_dict(d) if d else None
        if plan is None:
            # trace not ready (or it carries no per-tensor comm spans —
            # e.g. a fully compiled plane) — retry next window, but not
            # forever: re-stitching a plan-less trace every window is
            # wasted work on a job that will never yield one
            if self._plan_attempts >= self.max_plan_attempts:
                self.phase = self.PHASE_FROZEN
                self._record({"outcome": "no_plan_available",
                              "windows_tried": self._plan_attempts})
                log.info("profile-guided: no applicable plan after %d "
                         "windows (no per-tensor comm spans in the "
                         "trace?) — loop frozen", self._plan_attempts)
            return
        if plan.predicted_speedup_pct < self.min_speedup_pct:
            self.phase = self.PHASE_FROZEN
            self._record({"outcome": "no_worthwhile_plan",
                          "predicted_speedup_pct":
                              plan.predicted_speedup_pct})
            log.info("profile-guided: best plan predicts only %.2f%% — "
                     "keeping threshold bucketing",
                     plan.predicted_speedup_pct)
            return
        if self._same_plan(plan, self.plan):
            # cycle-flush re-plan landed on the plan already running:
            # keep it without a re-jit.  Crucially this must NOT enter
            # verify — the new baseline was measured WITH the plan
            # applied, so the stale trace's predicted speedup would read
            # as a false regression and roll back a verified-good plan.
            self._record(dict(plan.to_dict(), plan_id=self.plan.plan_id,
                              outcome="retained"))
            if self.cycle_flush_steps > 0:
                self.phase = self.PHASE_STEADY
                self._steady_left = self.cycle_flush_steps
            else:
                self.phase = self.PHASE_FROZEN
            return
        self._plan_seq += 1
        plan.plan_id = self._plan_seq
        plan.cycle_flush_steps = self.cycle_flush_steps
        self.plan = plan
        self.apply_fn(plan)
        self._metrics_predicted(plan.predicted_speedup_pct)
        self._record(dict(plan.to_dict(), outcome="applied"))
        log.info("profile-guided: applied plan %d (%d buckets, compute "
                 "%s, predicted %+.2f%%)", plan.plan_id, plan.num_buckets,
                 plan.compute or {}, plan.predicted_speedup_pct)
        self.phase = self.PHASE_VERIFY

    @staticmethod
    def _same_plan(a: Optional[FusionPlanSpec],
                   b: Optional[FusionPlanSpec]) -> bool:
        """Same applied decision (bucketing + wire formats + compute
        knobs) — predicted numbers excluded, they drift per window."""
        return a is not None and b is not None \
            and a.buckets == b.buckets and a.overlap == b.overlap \
            and a.compression == b.compression \
            and (a.compute or None) == (b.compute or None)

    # -- the compute tier ----------------------------------------------------
    def _compute_candidates(self) -> List[FusionPlanSpec]:
        """Ranked un-tried compute-knob plans from the profiler anatomy
        ([] without an anatomy source or when every knob is applied or
        condemned)."""
        if self.anatomy_fn is None:
            return []
        try:
            anatomy = self.anatomy_fn()
        except Exception as e:  # noqa: BLE001
            log.debug("profile-guided anatomy read failed: %s", e)
            return []
        if not anatomy:
            return []
        from .compute_knobs import compute_plans_from_anatomy

        exclude = set(self._verified_compute) | self._condemned_compute \
            | set(self.active_compute)
        if self.plan is not None and self.plan.compute:
            exclude |= set(self.plan.compute)
        return compute_plans_from_anatomy(
            anatomy, exclude=exclude, fused_available=self.fused_available)

    def _best_compute_plan(self) -> Optional[FusionPlanSpec]:
        cands = self._compute_candidates()
        if not cands:
            return None
        best = cands[0]
        # accumulate the knobs earlier windows verified: the rebuild is
        # whole-state, so a new plan must re-assert them or lose them
        best.compute = {**self._verified_compute, **(best.compute or {})}
        if self.plan is not None and self.plan.buckets:
            # keep a verified comm layout while trying a compute knob
            best.buckets = [list(b) for b in self.plan.buckets]
            best.compression = list(self.plan.compression) \
                if self.plan.compression else None
        return best

    # -- verify --------------------------------------------------------------
    def _verify_window(self, realized_us: float) -> None:
        plan = self.plan
        realized_pct = (self.baseline_us - realized_us) \
            / self.baseline_us * 100.0 if self.baseline_us else 0.0
        self._metrics_realized(realized_pct)
        # The simulator predicts its speedup against the DAG replay
        # makespan; the measured window also carries host time outside
        # the DAG (input pipeline, dispatch).  Verify against the saving
        # re-based onto the measured baseline — min() with the replay
        # fraction so an overestimating replay can't demand more than
        # its own fraction either — or realistic host overhead would
        # dilute realized_pct and roll back correctly working plans.
        saved_us = plan.baseline_step_us - plan.predicted_step_us
        expected_pct = min(
            plan.predicted_speedup_pct,
            saved_us / self.baseline_us * 100.0) \
            if self.baseline_us and saved_us > 0 \
            else plan.predicted_speedup_pct
        shortfall = expected_pct - realized_pct
        rec = dict(plan.to_dict(), realized_step_us=round(realized_us, 3),
                   realized_speedup_pct=round(realized_pct, 2),
                   expected_realized_pct=round(expected_pct, 2),
                   shortfall_pct=round(shortfall, 2))
        if self.rollback_enabled and shortfall > self.guard_band_pct:
            # fall back to the LAST VERIFIED plan (None = threshold
            # bucketing) and condemn any compute knob this plan newly
            # introduced so the next window doesn't re-propose it
            fallback = self._last_good_plan
            self.apply_fn(fallback)
            self.plan = fallback
            prior = set((fallback.compute or {})) if fallback is not None \
                else set()
            self._condemned_compute |= set(plan.compute or {}) - prior
            rec["outcome"] = "rolled_back"
            self._metrics_rollback()
            log.warning(
                "profile-guided: plan %d realized %+.2f%% vs predicted "
                "%+.2f%% (guard band %.1f%%) — rolled back",
                plan.plan_id, realized_pct, plan.predicted_speedup_pct,
                self.guard_band_pct)
        else:
            rec["outcome"] = "verified"
            self._last_good_plan = plan
            if plan.compute:
                self._verified_compute.update(plan.compute)
            log.info("profile-guided: plan %d verified (realized %+.2f%% "
                     "vs predicted %+.2f%%)", plan.plan_id, realized_pct,
                     plan.predicted_speedup_pct)
        self._record(rec)
        # un-tried compute knobs remain?  The anatomy is PER-RANK data
        # (host-gap share differs across ranks), so multi-process jobs
        # take process 0's answer through the plan broadcast — every
        # rank must keep (or stop) joining the window collectives in
        # lockstep, same invariant as the plan decision itself.
        more_compute = bool(self._compute_candidates()) if self.plan_root \
            else False
        if self.plan_sync is not None:
            d = self.plan_sync({"more_compute": more_compute})
            more_compute = bool((d or {}).get("more_compute"))
        if more_compute:
            # measure a fresh baseline (with everything verified so far
            # still applied) and try the next compute knob through the
            # same apply→verify machinery
            self.phase = self.PHASE_BASELINE
            self._window = []
            self._plan_attempts = 0
        elif rec["outcome"] == "verified" and plan.cycle_flush_steps > 0:
            self.phase = self.PHASE_STEADY
            self._steady_left = plan.cycle_flush_steps
        else:
            self.phase = self.PHASE_FROZEN

    # -- bookkeeping ---------------------------------------------------------
    def _record(self, rec: dict) -> None:
        rec = dict(rec, plan_id=rec.get("plan_id", self._plan_seq))
        self.history.append(rec)
        self._record_flight_event(rec)
        if self.push_target is None:
            return
        try:
            from ..run.http_client import put_autotune_plan

            addr, port, secret = self.push_target
            put_autotune_plan(addr, port, len(self.history), rec,
                              secret=secret)
        except Exception as e:  # noqa: BLE001
            log.debug("autotune push failed: %s", e)

    def _record_flight_event(self, rec: dict) -> None:
        """Mirror apply/verify/rollback outcomes into the control-plane
        flight recorder with the predicted-vs-realized numbers; the
        verify/rollback events chain onto their plan's apply event."""
        kind = {"applied": "autotune.apply",
                "verified": "autotune.verify",
                "rolled_back": "autotune.rollback"}.get(rec.get("outcome"))
        if kind is None:
            return
        try:
            from ..observe import events as events_mod

            eid = events_mod.record_event(
                kind,
                severity="warning" if kind == "autotune.rollback"
                else "info",
                payload={
                    "plan_id": rec.get("plan_id"),
                    "predicted_speedup_pct":
                        rec.get("predicted_speedup_pct"),
                    "realized_speedup_pct":
                        rec.get("realized_speedup_pct"),
                    "shortfall_pct": rec.get("shortfall_pct"),
                    "num_buckets": len(rec.get("buckets") or []),
                    "compute": rec.get("compute"),
                },
                cause_id=None if kind == "autotune.apply"
                else self._apply_event_id)
            if kind == "autotune.apply":
                self._apply_event_id = eid
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass

    def _metrics_predicted(self, pct: float) -> None:
        try:
            from .. import metrics

            if metrics.on():
                metrics.AUTOTUNE_PREDICTED_SPEEDUP.set(pct)
                metrics.AUTOTUNE_PLANS_APPLIED.inc()
        except Exception:  # noqa: BLE001
            pass

    def _metrics_realized(self, pct: float) -> None:
        try:
            from .. import metrics

            if metrics.on():
                metrics.AUTOTUNE_REALIZED_SPEEDUP.set(pct)
        except Exception:  # noqa: BLE001
            pass

    def _metrics_rollback(self) -> None:
        try:
            from .. import metrics

            if metrics.on():
                metrics.AUTOTUNE_ROLLBACKS.inc()
        except Exception:  # noqa: BLE001
            pass


def tuner_from_env(analyze_fn, apply_fn, anatomy_fn=None,
                   fused_available=True,
                   active_compute=None) -> ProfileGuidedTuner:
    """A tuner wired to the job's rendezvous server (push target from the
    metrics-pusher env triple) — the training.py construction path.

    Multi-process jobs get the window/plan sync hooks (process-mean
    measurement + process-0 decision broadcast) so every rank applies
    and rolls back the same plan, and only process 0 pushes the
    `/autotune` table (the scope is single-writer)."""
    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    push = (addr, port, bytes.fromhex(secret_hex) if secret_hex else None) \
        if addr and port else None

    window_sync = plan_sync = None
    plan_root = True
    from .. import core

    if core.is_initialized() and core.process_size() > 1:
        from .. import eager
        from ..ops.collectives import Average as _Avg

        def window_sync(us: float) -> float:
            return float(eager.process_allreduce(
                np.asarray([us], np.float64), op=_Avg,
                name="autotune.pg_window")[0])

        def plan_sync(d: Optional[dict]) -> Optional[dict]:
            return eager.broadcast_object(d, root_rank=0,
                                          name="autotune.pg_plan")

        if core.process_rank() != 0:
            push = None
            plan_root = False
    return ProfileGuidedTuner(analyze_fn=analyze_fn, apply_fn=apply_fn,
                              anatomy_fn=anatomy_fn,
                              fused_available=fused_available,
                              active_compute=active_compute,
                              window_sync=window_sync, plan_sync=plan_sync,
                              plan_root=plan_root, push_target=push)
