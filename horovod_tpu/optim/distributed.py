"""Distributed optimizer wrappers.

TPU-native re-design of Horovod's framework wrappers:

* ``DistributedOptimizer`` — reference horovod/tensorflow/__init__.py:267-319
  (wraps ``compute_gradients`` with per-grad allreduce) and
  horovod/torch/__init__.py:122-217 (per-parameter grad-accumulator hooks
  firing async allreduces during backward, ``backward_passes_per_step``
  delay counters, ``synchronize``).  Here the idiomatic carrier is an
  ``optax.GradientTransformation``: the wrapper allreduces the incoming
  gradients (fused, compressed) before delegating to the inner transform.
  Horovod's "async during backward" overlap is subsumed by XLA's scheduler,
  which overlaps the psum with independent compute inside the compiled step
  — latency hiding moves from the hook machinery into the compiler.
* ``DistributedGradientTape`` — reference
  horovod/tensorflow/__init__.py:483-539: wraps a gradient function so its
  output is allreduced.
* ``broadcast_parameters`` / ``broadcast_optimizer_state`` — reference
  horovod/torch/__init__.py:446-578: rank-0's values are pushed to all
  ranks at start-up (the checkpoint/resume idiom: rank 0 restores, then
  broadcasts).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .. import core
from ..core import Average, Sum, Adasum
from ..ops import collectives
from ..ops.compression import Compression, ErrorFeedback
from ..ops.fusion import allreduce_pytree


class _AccumulationState(NamedTuple):
    inner: Any
    counter: jnp.ndarray          # steps since last sync
    accum: Any                    # gradient accumulation pytree


class _ErrorFeedbackState(NamedTuple):
    """Optimizer-state carrier for the error-feedback residual
    (docs/compression.md): living inside the optax state pytree, the
    residual survives jit, rides ``utils/checkpoint.py`` saves/restores
    with the rest of the train state, and is rebuilt consistently on
    elastic epochs (the state is broadcast with everything else)."""

    inner: Any
    residual: Any                 # quantization-error carry pytree


def DistributedOptimizer(
    optimizer,
    *,
    op: str = Average,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    process_set: Optional[collectives.ProcessSet] = None,
    threshold_bytes: Optional[int] = None,
    sparse_as_dense: bool = False,
):
    """Wrap an ``optax.GradientTransformation`` so updates see
    globally-reduced gradients.

    Must be used inside an SPMD region (an ``hvd.spmd`` step).  With
    ``backward_passes_per_step > 1``, gradients are accumulated locally and
    the allreduce fires only every Nth update — the reference's delay
    counters (horovod/torch/__init__.py:141-157) expressed as optax state;
    off-sync steps return zero updates (parameters hold still), matching
    the semantics of skipping ``optimizer.step()`` while accumulating.

    An :class:`~horovod_tpu.ops.compression.ErrorFeedback` ``compression``
    makes the wrapper stateful: the quantization residual lives in the
    optax state (:class:`_ErrorFeedbackState`), initialized to zeros by
    ``init`` and updated by every synchronizing allreduce — so it is
    checkpointed, broadcast, and elastic-rebuilt with the rest of the
    optimizer state (docs/compression.md).
    """
    import optax

    n = int(backward_passes_per_step)
    if n < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    ef = isinstance(compression, ErrorFeedback)
    if ef and op == Adasum:
        raise ValueError(
            "error-feedback compression composes with Sum/Average "
            "allreduce, not Adasum (the scale-invariant merge is not "
            "linear in the residual)")

    from ..ops.sparse import densify_tree

    def reduce_grads(grads, residual=None):
        """Returns ``(reduced, new_residual)`` — residual is None
        throughout when error feedback is off."""
        if op == Adasum:
            # Adasum has no sparse form (reference: sparse tensors are not
            # routed to Adasum either) — densify first.
            grads = densify_tree(grads)
            leaves, treedef = jax.tree_util.tree_flatten(grads)
            reduced = [
                collectives.allreduce(g, op=Adasum) for g in leaves
            ]
            return jax.tree_util.tree_unflatten(treedef, reduced), None
        reduced = allreduce_pytree(
            grads, op=op, compression=compression,
            process_set=process_set, threshold_bytes=threshold_bytes,
            sparse_as_dense=sparse_as_dense, residual=residual,
        )
        new_residual = None
        if residual is not None:
            reduced, new_residual = reduced
        # optax update rules consume dense arrays; the communication was
        # sparse, the application is a scatter-add (TF applies IndexedSlices
        # natively — optax has no sparse update, so densify post-reduce).
        return densify_tree(reduced), new_residual

    if n == 1:
        def init_fn(params):
            inner = optimizer.init(params)
            if ef:
                return _ErrorFeedbackState(
                    inner=inner, residual=ErrorFeedback.init_state(params))
            return inner

        def update_fn(grads, state, params=None, **extra):
            if ef:
                grads = densify_tree(grads)  # residuals are dense trees
                reduced, residual = reduce_grads(grads, state.residual)
                updates, inner = optimizer.update(
                    reduced, state.inner, params, **extra)
                return updates, _ErrorFeedbackState(inner, residual)
            reduced, _ = reduce_grads(grads)
            return optimizer.update(reduced, state, params, **extra)

        return optax.GradientTransformation(init_fn, update_fn)

    def init_fn(params):
        inner = optimizer.init(params)
        if ef:
            inner = _ErrorFeedbackState(
                inner=inner, residual=ErrorFeedback.init_state(params))
        return _AccumulationState(
            inner=inner,
            counter=jnp.zeros((), jnp.int32),
            accum=jax.tree_util.tree_map(jnp.zeros_like, params),
        )

    def update_fn(grads, state, params=None, **extra):
        # accumulation buffers are dense (zeros_like(params)); sparse grads
        # scatter-add into them
        grads = densify_tree(grads)
        accum = jax.tree_util.tree_map(lambda a, g: a + g, state.accum, grads)
        count = state.counter + 1
        sync = count >= n

        def do_sync(_):
            mean = jax.tree_util.tree_map(lambda a: a / n, accum)
            if ef:
                reduced, residual = reduce_grads(mean, state.inner.residual)
                updates, inner = optimizer.update(
                    reduced, state.inner.inner, params, **extra)
                inner = _ErrorFeedbackState(inner, residual)
            else:
                reduced, _ = reduce_grads(mean)
                updates, inner = optimizer.update(
                    reduced, state.inner, params, **extra)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return updates, _AccumulationState(inner, jnp.zeros((), jnp.int32), zeros)

        def no_sync(_):
            updates = jax.tree_util.tree_map(jnp.zeros_like, grads)
            return updates, _AccumulationState(state.inner, count, accum)

        return jax.lax.cond(sync, do_sync, no_sync, None)

    return optax.GradientTransformation(init_fn, update_fn)


class DistributedGradientTape:
    """Wrap a gradient function so its gradients are allreduced.

    API-parity shim for TF2's ``hvd.DistributedGradientTape`` (reference
    horovod/tensorflow/__init__.py:483-539).  JAX has no tape; the
    equivalent object is the gradient *function*::

        tape = hvd.DistributedGradientTape(jax.grad(loss_fn))
        grads = tape.gradient(params, batch)      # inside hvd.spmd
    """

    def __init__(self, grad_fn: Callable, *, op: str = Average,
                 compression=Compression.none,
                 process_set: Optional[collectives.ProcessSet] = None):
        self._grad_fn = grad_fn
        self._op = op
        self._compression = compression
        self._process_set = process_set

    def gradient(self, *args, **kwargs):
        grads = self._grad_fn(*args, **kwargs)
        return allreduce_pytree(
            grads, op=self._op, compression=self._compression,
            process_set=self._process_set,
        )

    def __call__(self, *args, **kwargs):
        return self.gradient(*args, **kwargs)


def grad(fun: Callable, *grad_args, op: str = Average,
         compression=Compression.none, **grad_kwargs) -> Callable:
    """``jax.grad`` with a built-in allreduce — the most idiomatic entry::

        g = hvd.grad(loss_fn)(params, batch)   # inside hvd.spmd
    """
    gf = jax.grad(fun, *grad_args, **grad_kwargs)

    def wrapped(*args, **kwargs):
        return allreduce_pytree(gf(*args, **kwargs), op=op,
                                compression=compression)

    return wrapped


# ---------------------------------------------------------------------------
# start-up state synchronization (host-level)
# ---------------------------------------------------------------------------
def broadcast_parameters(params, root_rank: int = 0):
    """Make every controller process's copy of ``params`` equal to
    ``root_rank``'s (reference horovod/torch/__init__.py:446-478).

    Under single-controller JAX, replicated arrays are identical by
    construction, so this is the multi-host synchronization point only.
    Returns the synchronized pytree (functional style — JAX arrays are
    immutable, unlike the reference's in-place tensor broadcast).
    """
    core._require_init()
    if core.process_size() == 1:
        return params
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(
        params, is_source=core.process_rank() == root_rank
    )


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Same contract for optimizer state (reference
    horovod/torch/__init__.py:480-578 walks the state dict; a pytree walk
    here is the whole implementation)."""
    return broadcast_parameters(opt_state, root_rank)


def broadcast_variables(variables, root_rank: int = 0):
    """TF-flavored alias (reference horovod/tensorflow/__init__.py
    ``broadcast_variables`` / BroadcastGlobalVariablesHook)."""
    return broadcast_parameters(variables, root_rank)
