"""Compute-knob planning: per-block anatomy → applicable compute plans.

The comm tier plans from the replay simulator (profile_guided.py:
stitched DAG → bucket search → FusionPlanSpec).  The compute tier plans
from the compute-anatomy profiler (timeline/profiler.py): each knob is
priced against the per-block attribution it attacks, so the
ProfileGuidedTuner can apply it through the same
ParameterManager-re-jit seam, verify realized-vs-predicted against the
same guard band, and roll it back on regression — per-block anatomy is
the *scoring*, the whole-step window stays the *verification*.

Knob models (deliberately simple α-style fractions, calibrated by the
bench A/B rather than fitted):

* ``fused_optimizer`` — the flat fused update (optim/fused_update.py)
  replaces the per-leaf optax traversal; modeled to save
  ``FUSED_UPDATE_SAVE_FRAC`` of the ``optimizer_update`` block's
  per-step device time (the per-leaf path's overhead is dispatch + HBM
  round-trips on sub-tile tensors, roughly half the block on the
  profiled ResNet run — docs/PERF.md compute-tier table).
* ``loss_fetch_steps`` — the trailing async loss fetch (training.py)
  removes the per-step host sync; modeled to recover
  ``ASYNC_GAP_SAVE_FRAC`` of the anatomy's measured host gap (the gap
  that remains is input-pipeline, which the prefetch loader owns).

A plan's ``predicted_step_us``/``baseline_step_us`` are priced against
the ANATOMY's own step time; the tuner's verify step re-bases the
absolute saving onto the measured window baseline exactly as it does
for fusion plans, so an anatomy captured under the decomposed
(profiled) step cannot inflate the expectation.

:data:`COMPUTE_AUTOTUNE_EXPECTED` is the hand-computed fixture in the
style of ``AUTOTUNE_EXPECTED`` (timeline/replay/fixture.py), derived
from the profiler's own two-rank fixture (rank 0: 1000 µs steps,
optimizer_update 50 µs/step, host gap 100 µs/step):

========================  =======================================
loss_fetch_steps plan     saves 0.9 × 100 = 90 µs → 910 µs, +9.0%
fused_optimizer plan      saves 0.5 × 50  = 25 µs → 975 µs, +2.5%
========================  =======================================

``scripts/compute_path_bench.py --check`` and
tests/test_compute_knobs.py recover it exactly.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

#: knob names as they appear in ``FusionPlanSpec.compute`` and in the
#: training step's rebuild seam (training.py ``_rebuild``)
KNOB_FUSED_OPTIMIZER = "fused_optimizer"
KNOB_LOSS_FETCH = "loss_fetch_steps"
KNOB_REMAT = "remat_policy"

#: fraction of the optimizer_update block the fused kernel is modeled
#: to save (per-leaf dispatch + sub-tile HBM overhead)
FUSED_UPDATE_SAVE_FRAC = 0.5
#: fraction of the measured host gap the async loss fetch recovers
ASYNC_GAP_SAVE_FRAC = 0.9
#: don't propose a knob for less than this share of the step
MIN_BLOCK_FRACTION = 0.01


def compute_plans_from_anatomy(
        anatomy: Optional[dict], *,
        exclude: Sequence[str] = (),
        fused_available: bool = True,
        loss_fetch_steps: Optional[int] = None,
        fused_save_frac: float = FUSED_UPDATE_SAVE_FRAC,
        gap_save_frac: float = ASYNC_GAP_SAVE_FRAC) -> List:
    """Ranked compute-knob plans for one rank's profiler anatomy
    (``compute.json["anatomy"]`` / ``ComputeProfiler.anatomy``), best
    predicted speedup first; ``[]`` when the anatomy is empty or every
    applicable knob is excluded (already applied or condemned)."""
    from .profile_guided import FusionPlanSpec

    if not anatomy or not anatomy.get("steps"):
        return []
    steps = int(anatomy["steps"])
    wall = float(anatomy.get("wall_us") or 0.0)
    if wall <= 0.0 or steps <= 0:
        return []
    step_us = wall / steps
    exclude = set(exclude)
    plans: List[FusionPlanSpec] = []

    gap_us = float((anatomy.get("host_gap") or {}).get("per_step_us", 0.0))
    if KNOB_LOSS_FETCH not in exclude \
            and gap_us / step_us >= MIN_BLOCK_FRACTION:
        if loss_fetch_steps is None:
            loss_fetch_steps = env_util.get_int(
                env_util.HVD_LOSS_FETCH_STEPS,
                env_util.DEFAULT_LOSS_FETCH_STEPS) or \
                env_util.DEFAULT_LOSS_FETCH_STEPS
        saved = gap_us * gap_save_frac
        plans.append(FusionPlanSpec(
            buckets=[],
            compute={KNOB_LOSS_FETCH: int(loss_fetch_steps)},
            predicted_step_us=step_us - saved,
            baseline_step_us=step_us,
            predicted_speedup_pct=saved / step_us * 100.0))

    opt_us = float(((anatomy.get("segments") or {})
                    .get("optimizer_update") or {}).get("per_step_us", 0.0))
    if KNOB_FUSED_OPTIMIZER not in exclude and fused_available \
            and opt_us / step_us >= MIN_BLOCK_FRACTION:
        saved = opt_us * fused_save_frac
        plans.append(FusionPlanSpec(
            buckets=[],
            compute={KNOB_FUSED_OPTIMIZER: True},
            predicted_step_us=step_us - saved,
            baseline_step_us=step_us,
            predicted_speedup_pct=saved / step_us * 100.0))

    plans.sort(key=lambda p: -p.predicted_speedup_pct)
    return plans


# ---------------------------------------------------------------------------
# hand-computed fixture (the AUTOTUNE_EXPECTED style: numbers derived by
# hand from the profiler fixture, recovered exactly by the planner)
# ---------------------------------------------------------------------------
COMPUTE_AUTOTUNE_EXPECTED: Dict[str, float] = {
    # profiler fixture rank 0 (timeline/profiler.py PROFILE_EXPECTED):
    # two 1000 µs steps, optimizer_update 50 µs/step, host gap 100 µs/step
    "baseline_step_us": 1000.0,
    "optimizer_update_us": 50.0,
    "host_gap_us": 100.0,
    # loss_fetch plan: 0.9 × 100 µs = 90 µs saved
    "async_saved_us": 90.0,
    "async_predicted_step_us": 910.0,
    "async_speedup_pct": 9.0,
    # fused_optimizer plan: 0.5 × 50 µs = 25 µs saved
    "fused_saved_us": 25.0,
    "fused_predicted_step_us": 975.0,
    "fused_speedup_pct": 2.5,
    # both applied: 885 µs — the end state the two-knob exploration
    # (tests/test_compute_knobs.py) converges to
    "combined_step_us": 885.0,
}


def compute_fixture_anatomy() -> dict:
    """Rank 0's anatomy from the compute-anatomy profiler's own
    hand-computed fixture — the corpus the planner's pinned numbers
    above are derived from."""
    from ..timeline.profiler import (
        PROFILE_GAP_THRESHOLD_US, PROFILE_HBM_BYTES_PER_SEC,
        PROFILE_PEAK_FLOPS, profile_fixture_events, reduce_trace_events,
    )

    return reduce_trace_events(
        profile_fixture_events(0),
        peak_flops=PROFILE_PEAK_FLOPS,
        hbm_bytes_per_sec=PROFILE_HBM_BYTES_PER_SEC,
        gap_threshold_us=PROFILE_GAP_THRESHOLD_US)


def check_fixture() -> bool:
    """Planner-vs-hand-computed self-test
    (``scripts/compute_path_bench.py --check``)."""
    exp = COMPUTE_AUTOTUNE_EXPECTED
    plans = compute_plans_from_anatomy(compute_fixture_anatomy())
    ok = len(plans) == 2
    ok = ok and KNOB_LOSS_FETCH in plans[0].compute
    ok = ok and abs(plans[0].predicted_step_us
                    - exp["async_predicted_step_us"]) < 1e-6
    ok = ok and abs(plans[0].predicted_speedup_pct
                    - exp["async_speedup_pct"]) < 1e-6
    ok = ok and plans[1].compute == {KNOB_FUSED_OPTIMIZER: True}
    ok = ok and abs(plans[1].predicted_step_us
                    - exp["fused_predicted_step_us"]) < 1e-6
    ok = ok and abs(plans[1].predicted_speedup_pct
                    - exp["fused_speedup_pct"]) < 1e-6
    return ok


# ---------------------------------------------------------------------------
# the bench fixture: fused+async ON vs OFF on the current (CPU) mesh
# ---------------------------------------------------------------------------
def run_bench_fixture(*, steps: int = 40, batch_per_rank: int = 8,
                      dim: int = 64, classes: int = 8,
                      host_delay_s: float = 0.003,
                      profile_steps: int = 6) -> dict:
    """The compute-path A/B bench.py's ``--child-compute-opt`` leg runs:
    the SAME tiny MLP job twice on the current mesh — baseline (per-leaf
    optax update, synchronous loader, a ``device_get`` sync every step)
    vs optimized (fused update kernel, 2-deep device prefetch, trailing
    loss fetch) — plus a profiler window on the optimized path for the
    ``host_gap_pct`` number.  An injected per-batch host delay
    (``host_delay_s``) stands in for a real input pipeline so the
    prefetch overlap is measurable on the dev CPU mesh.  Losses must
    match to fp32 tolerance (the fused update is the only numeric
    delta, and it is expression-identical to optax)."""
    import os
    import tempfile
    import time

    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from ..data.loader import prefetch_to_device
    from ..models.mlp import MLP
    from ..training import init_train_state, make_train_step, shard_batch
    from .fused_update import fused_sgd

    if not hvd.is_initialized():
        hvd.init()
    rng = np.random.default_rng(7)
    n = batch_per_rank * hvd.size()
    x_host = rng.normal(size=(n, dim)).astype(np.float32)
    y_host = rng.integers(0, classes, size=(n,)).astype(np.int32)

    def loss_fn(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    model = MLP(features=(32, classes))

    def batches():
        for _ in range(steps):
            time.sleep(host_delay_s)       # the injected host pipeline
            yield shard_batch(x_host), shard_batch(y_host)

    def drive(optimized: bool) -> dict:
        opt = fused_sgd(0.05, momentum=0.9) if optimized \
            else optax.sgd(0.05, momentum=0.9)
        step = make_train_step(
            apply_fn=lambda v, a, train=True: model.apply(v, a),
            loss_fn=loss_fn, optimizer=opt,
            fused_optimizer=optimized,
            loss_fetch_steps=16 if optimized else 0,
        )
        state = init_train_state(model, opt, jnp.zeros((2, dim)))
        it = batches()
        if optimized:
            it = prefetch_to_device(it, 2)
        # compile outside the timed loop (both sides pay it equally)
        warm_x, warm_y = shard_batch(x_host), shard_batch(y_host)
        state, loss = step(state, warm_x, warm_y)
        jax.device_get(loss)
        t0 = time.perf_counter()
        for bx, by in it:
            state, loss = step(state, bx, by)
            if not optimized:
                # the per-step honesty sync the async pipeline removes
                jax.device_get(loss)
        final = float(np.asarray(jax.device_get(loss)))
        dt = time.perf_counter() - t0
        return {"img_sec": n * steps / dt, "final_loss": final}

    base = drive(optimized=False)
    opti = drive(optimized=True)

    # host_gap_pct: the step's own decomposed profiler window over the
    # OPTIMIZED path (make_train_step profile-from-env — the same
    # machinery a real job's HVD_PROFILE=1 uses, docs/profiling.md)
    host_gap_pct = None
    keys = ("HVD_TIMELINE", "HVD_PROFILE", "HVD_PROFILE_START_STEP",
            "HVD_PROFILE_END_STEP")
    saved_env = {k: os.environ.get(k) for k in keys}
    try:
        with tempfile.TemporaryDirectory() as td:
            os.environ.update({
                "HVD_TIMELINE": td, "HVD_PROFILE": "1",
                "HVD_PROFILE_START_STEP": "2",
                "HVD_PROFILE_END_STEP": str(1 + profile_steps)})
            opt = fused_sgd(0.05, momentum=0.9)
            step = make_train_step(
                apply_fn=lambda v, a, train=True: model.apply(v, a),
                loss_fn=loss_fn, optimizer=opt,
                fused_optimizer=True, loss_fetch_steps=16)
            state = init_train_state(model, opt, jnp.zeros((2, dim)))
            xs, ys = shard_batch(x_host), shard_batch(y_host)
            for _ in range(profile_steps + 2):
                state, _ = step(state, xs, ys)
            prof = step.compute_profiler
            anatomy = prof.finalize() if prof is not None else None
            if anatomy:
                host_gap_pct = round(
                    anatomy["host_gap"]["fraction"] * 100.0, 2)
    except Exception as e:  # noqa: BLE001 — the gap number is advisory
        log.debug("host-gap capture failed: %s", e)
        if "PYTEST_CURRENT_TEST" in os.environ:
            raise
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    delta = (opti["img_sec"] - base["img_sec"]) / base["img_sec"] * 100.0
    loss_diff = abs(opti["final_loss"] - base["final_loss"])
    return {
        "img_sec_baseline": round(base["img_sec"], 2),
        "img_sec_optimized": round(opti["img_sec"], 2),
        "compute_opt_delta_pct": round(delta, 2),
        "host_gap_pct": host_gap_pct,
        "loss_baseline": base["final_loss"],
        "loss_optimized": opti["final_loss"],
        "loss_max_abs_diff": loss_diff,
        "loss_equal": bool(loss_diff <= 1e-5),
    }
