"""Topology discovery, initialization, and the SPMD rank model.

TPU-native re-design of Horovod's process/rank bootstrap
(reference: horovod/common/basics.py:22-212 and the extern-C API in
horovod/common/operations.cc:653-791).

Horovod's model: every *process* is a rank; ``hvd.init()`` ctypes-calls into a
C++ core that spawns a background thread and negotiates membership over
MPI/Gloo.  On TPU there is no MPI: the platform gives us the topology (the
ICI mesh), and XLA compiles collectives directly into the program.  So here:

* a **rank** is a *device* (TPU chip) in the global ``jax.sharding.Mesh``;
* the per-rank "script" is an SPMD function run under :func:`horovod_tpu.spmd`
  (``shard_map`` over the mesh) — inside it, :func:`rank` is the traced
  ``lax.axis_index``;
* the host Python process is a *controller* owning ``local_size()`` ranks;
  outside SPMD regions :func:`rank` reports the controller's process index
  (used for rank-0 gating: checkpoints, logging — same idiom as Horovod
  examples);
* multi-host bootstrap uses ``jax.distributed`` (the analog of Horovod's
  Gloo HTTP-rendezvous, reference horovod/common/gloo/gloo_context.cc:56-76),
  driven by ``HVD_*`` env vars set by the ``tpurun`` launcher.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from .utils import env as env_util
from .utils.logging import get_logger

log = get_logger(__name__)

# Reduction op constants, mirroring horovod.common.basics (reference
# horovod/common/basics.py:44-49 exposes horovod_reduce_op_average/_sum/
# _adasum read from the C++ enum in common/message.h).
Average = "Average"
Sum = "Sum"
Adasum = "Adasum"
Min = "Min"
Max = "Max"

#: Name of the global mesh axis spanning every rank (device).
AXIS = "hvd"
#: Hierarchical axes: "cross" spans hosts/slices (DCN), "local" spans the
#: devices within one host/slice (ICI) — the analog of Horovod's
#: LOCAL/CROSS communicators (reference horovod/common/common.h:110-114).
CROSS_AXIS = "cross"
LOCAL_AXIS = "local"


class NotInitializedError(RuntimeError):
    def __init__(self) -> None:
        super().__init__(
            "horovod_tpu has not been initialized; call hvd.init() first."
        )


@dataclass
class _GlobalState:
    """Python analog of HorovodGlobalState (reference
    horovod/common/global_state.h:42) — minus the background thread, which
    XLA's async dispatch makes unnecessary on the hot path."""

    initialized: bool = False
    devices: tuple = ()
    mesh: Optional[Mesh] = None
    hmesh: Optional[Mesh] = None
    size: int = 0
    local_size: int = 0
    cross_size: int = 0
    process_index: int = 0
    process_count: int = 1
    platform: Optional[str] = None
    # Monotone id so cached jitted collectives can be invalidated on re-init.
    epoch: int = 0
    extra: dict = field(default_factory=dict)


_state = _GlobalState()
_lock = threading.Lock()
# The kwargs of the last successful init(), replayed by reinit() so an
# elastic membership change rebuilds against the same device selection.
_init_kwargs: dict = {}


class _SpmdContext(threading.local):
    """Tracks whether we are tracing inside an SPMD (shard_map) region and
    which mesh axes constitute the rank axis there."""

    def __init__(self) -> None:
        self.axes: Optional[tuple] = None  # e.g. ("hvd",) or ("cross","local")
        self.local_axis: Optional[str] = None


_ctx = _SpmdContext()


def _pick_devices(platform: Optional[str]) -> list:
    if platform is not None:
        return list(jax.devices(platform))
    return list(jax.devices())


def init(
    *,
    platform: Optional[str] = None,
    devices: Optional[Sequence[Any]] = None,
    local_size: Optional[int] = None,
    comm: Optional[Sequence[int]] = None,
) -> None:
    """Initialize the framework: discover topology and build the global mesh.

    Mirrors ``hvd.init()`` (reference horovod/common/basics.py:33-65 →
    operations.cc:655 ``horovod_init``): idempotent, and accepts ``comm=``
    (a subset of ranks) the way Horovod accepts a sub-communicator.

    Args:
      platform: force a JAX platform ("tpu" / "cpu"); default = default
        backend.  Tests use ``platform="cpu"`` with
        ``--xla_force_host_platform_device_count=N`` — the analog of the
        reference's ``mpirun -np 2 -H localhost:2`` localhost simulation
        (reference docker-compose.test.yml:52).
      devices: explicit device list (overrides ``platform``).
      local_size: devices per "node" for the hierarchical (cross, local)
        mesh.  Defaults to this process's local device count; on a single
        process it can be overridden to simulate multiple nodes.
      comm: optional subset of global device indices to form the world from
        (reference operations.cc:655-663 ranks argument).
    """
    global _state, _init_kwargs
    with _lock:
        if _state.initialized:
            return
        _init_kwargs = {
            "platform": platform, "devices": devices,
            "local_size": local_size, "comm": comm,
        }
        # Elastic membership: adopt the committed epoch FIRST — a shrink
        # that raced this process's start-up rewrote the world, and the
        # identity/controller env must be read post-adoption (the ack
        # doubles as the driver's start barrier).
        try:
            from .elastic import membership

            membership.attach()
        except Exception as e:  # noqa: BLE001 — membership must never
            log.warning("membership attach failed: %s", e)  # block init
        if os.environ.get("HVD_COORDINATOR_ADDR"):
            # Multi-host bootstrap: the tpurun launcher sets these.  This is
            # the rendezvous step — the analog of GlooContext::Initialize's
            # HTTP KV-store handshake (reference gloo/gloo_context.cc:113-157).
            # Must run before anything touches the XLA backend; if the user
            # (or a passed `devices=` argument) already initialized it, fall
            # back to env-based process identity — the eager planes still
            # span the job through the native controller.
            try:
                jax.distributed.initialize(
                    coordinator_address=os.environ["HVD_COORDINATOR_ADDR"],
                    num_processes=int(os.environ.get("HVD_NUM_PROCESSES", "1")),
                    process_id=int(os.environ.get("HVD_PROCESS_ID", "0")),
                )
            except RuntimeError as e:
                # Only tolerate "backend already initialized" — a genuine
                # bootstrap failure (unreachable coordinator) must not
                # silently shrink the job to per-host training.
                msg = str(e)
                tolerable = ("must be called before" in msg
                             or "already initialized" in msg
                             or "only be called once" in msg)
                if not tolerable:
                    raise
                log.warning(
                    "jax.distributed bootstrap unavailable (%s); using "
                    "env-based process identity", e,
                )

        devs = list(devices) if devices is not None else _pick_devices(platform)
        # Process-major ordering so each controller's devices are contiguous
        # — this makes the (cross, local) reshape put intra-host links on
        # the fast axis, mirroring MPI_Comm_split_type(..., SHARED)
        # (reference mpi/mpi_context.cc).
        devs.sort(key=lambda d: (d.process_index, d.id))
        if comm is not None:
            devs = [devs[i] for i in comm]

        size = len(devs)
        if size == 0:
            raise RuntimeError("no devices available for horovod_tpu.init()")

        # identity must come from the backend the mesh devices live on —
        # jax.process_count()/process_index() default to the default
        # backend, which can be a single-process accelerator plugin while
        # the (e.g. CPU) mesh backend spans a jax.distributed job
        mesh_platform = devs[0].platform
        try:
            jax_nproc = jax.process_count(mesh_platform)
            jax_pidx = jax.process_index(mesh_platform)
        except Exception:  # noqa: BLE001 — backend without process info
            jax_nproc, jax_pidx = jax.process_count(), jax.process_index()

        if local_size is None:
            mine = [d for d in devs if d.process_index == jax_pidx]
            local_size = len(mine) if mine else size
        if size % local_size != 0:
            raise ValueError(
                f"global size {size} not divisible by local_size {local_size}"
            )
        cross_size = size // local_size

        mesh = Mesh(np.asarray(devs, dtype=object), (AXIS,))
        hmesh = Mesh(
            np.asarray(devs, dtype=object).reshape(cross_size, local_size),
            (CROSS_AXIS, LOCAL_AXIS),
        )

        # Process identity: jax.distributed when it spans processes, else
        # the HVD_* env set by the launcher (tpurun / function-mode run()) —
        # the native-controller-only deployment, where the XLA plane stays
        # per-process but the eager control/data planes span the job
        # (reference gloo_context.cc:128-156 reads HOROVOD_RANK/SIZE the
        # same way).  Elastic jobs always use the env identity: membership
        # epochs rewrite HVD_NUM_PROCESSES/HVD_PROCESS_ID on every world
        # change, while jax.distributed cannot be resized in process and
        # would pin the stale pre-shrink world.
        if jax_nproc > 1 and not env_util.get_bool(env_util.HVD_ELASTIC):
            process_index, process_count = jax_pidx, jax_nproc
        else:
            process_count = env_util.get_int(env_util.HVD_NUM_PROCESSES, 1)
            process_index = env_util.get_int(env_util.HVD_PROCESS_ID, 0)

        _state = _GlobalState(
            initialized=True,
            devices=tuple(devs),
            mesh=mesh,
            hmesh=hmesh,
            size=size,
            local_size=local_size,
            cross_size=cross_size,
            process_index=process_index,
            process_count=process_count,
            platform=devs[0].platform,
            epoch=_state.epoch + 1,
        )
        log.info(
            "initialized: size=%d local_size=%d cross_size=%d platform=%s",
            size, local_size, cross_size, _state.platform,
        )
        try:
            from .runtime import eager_controller

            eager_controller.setup_from_env(
                _state.process_index, _state.process_count
            )
        except Exception as e:  # noqa: BLE001
            # A requested native controller that can't start (e.g. its port
            # is taken on this host) means a multi-process job with no
            # transport — fail loudly rather than deadlock later.
            if env_util.get_str(env_util.HVD_CONTROLLER) == "native" \
                    and _state.process_count > 1:
                raise
            log.warning("eager controller setup failed: %s", e)
        # Env-driven timeline startup, as the reference core does when
        # HOROVOD_TIMELINE is set (reference operations.cc:392-400):
        # initialize() is a no-op when HVD_TIMELINE/HVD_TRACE_DIR is unset.
        from .timeline.timeline import timeline

        timeline.initialize()
        # Per-host relay election (run/relay.py, HVD_RELAY=1): local
        # rank 0 stands up the aggregator BEFORE the pusher/heartbeat
        # resolve their control endpoint, so this host's batchable
        # traffic rides one upstream connection from the first beat.
        try:
            from .run import relay

            relay.start_from_env()
        except Exception as e:  # noqa: BLE001 — the relay is an
            log.warning("relay setup failed: %s", e)  # optimization
        # Live metrics export: when the launcher stood up a rendezvous
        # server and passed its address (HVD_METRICS_KV_*), start pushing
        # this rank's snapshots so the launcher's GET /metrics sees us.
        try:
            from .metrics.push import start_pusher_from_env

            start_pusher_from_env(_state.process_index)
        except Exception as e:  # noqa: BLE001 — metrics must never
            log.warning("metrics pusher setup failed: %s", e)  # block init
        # Telemetry history flusher (metrics/timeseries.py): ships the
        # ring-buffer series the watchdog's detectors read, and polls
        # the observe/arm broadcast so an alert can arm this rank's
        # trace+profile window off the step path.
        try:
            from .metrics.timeseries import start_flusher_from_env

            start_flusher_from_env(_state.process_index)
        except Exception as e:  # noqa: BLE001 — history must never
            log.warning("timeseries flusher setup failed: %s", e)  # block init
        # Heartbeat leases + coordinated-abort polling (elastic/
        # heartbeat.py): active when the launcher exported rendezvous
        # wiring and this is a multi-process job.
        try:
            from .elastic.heartbeat import start_from_env

            start_from_env()
        except Exception as e:  # noqa: BLE001 — liveness reporting must
            log.warning("heartbeat setup failed: %s", e)  # never block init


def shutdown() -> None:
    """Tear down state (reference horovod/common/basics.py:67-70 →
    operations.cc ``horovod_shutdown``)."""
    global _state
    try:
        from .runtime import eager_controller

        eager_controller.shutdown()
    except Exception:  # noqa: BLE001
        pass
    try:
        from .timeline.timeline import timeline

        timeline.shutdown()
    except Exception:  # noqa: BLE001
        pass
    try:
        from .metrics.push import stop_pusher

        stop_pusher()  # flushes one final snapshot to the launcher
    except Exception:  # noqa: BLE001
        pass
    try:
        from .metrics.timeseries import stop_flusher

        stop_flusher()  # final history flush
    except Exception:  # noqa: BLE001
        pass
    try:
        from .elastic import heartbeat

        heartbeat.stop()
    except Exception:  # noqa: BLE001
        pass
    try:
        from .run import relay

        relay.stop()  # drains one final upstream flush
    except Exception:  # noqa: BLE001
        pass
    with _lock:
        _state = _GlobalState(epoch=_state.epoch + 1)


def reinit() -> None:
    """Tear down and re-initialize in process against the *current*
    environment — the elastic-membership rebuild (docs/fault_tolerance.md):
    after the driver commits a new epoch, `elastic/membership.py` rewrites
    ``HVD_NUM_PROCESSES``/``HVD_PROCESS_ID``/``HVD_CONTROLLER_ADDR`` and
    calls this, which re-creates the mesh, reconnects the eager controller
    client to the epoch's fresh ControllerServer, and restarts the
    heartbeat/metrics daemons — no process relaunch, no JIT cache loss
    beyond the step functions that must re-trace over the new mesh
    (training.make_train_step rebuilds those lazily via the mesh epoch).

    The device selection of the last :func:`init` is replayed; callers
    that never initialized get a plain :func:`init`."""
    kwargs = dict(_init_kwargs)
    shutdown()
    init(**kwargs)


def is_initialized() -> bool:
    return _state.initialized


def _require_init() -> _GlobalState:
    if not _state.initialized:
        raise NotInitializedError()
    return _state


def mesh() -> Mesh:
    """The global 1-D device mesh; axis name :data:`AXIS`."""
    return _require_init().mesh


def hierarchical_mesh() -> Mesh:
    """The 2-D (cross, local) mesh for hierarchical collectives."""
    return _require_init().hmesh


def size() -> int:
    """Total number of ranks (devices)."""
    return _require_init().size


def local_size() -> int:
    return _require_init().local_size


def cross_size() -> int:
    return _require_init().cross_size


def in_spmd() -> bool:
    """True while tracing inside an hvd SPMD region."""
    return _ctx.axes is not None


def _spmd_axes() -> Optional[tuple]:
    return _ctx.axes


def rank():
    """This rank's index.

    Inside an SPMD region: the traced per-device index along the rank axis
    (``lax.axis_index``).  Outside: the controller process index, which is
    what rank-0 gating in user scripts needs (reference idiom:
    examples/tensorflow2_mnist.py ``if hvd.rank() == 0``).
    """
    st = _require_init()
    if _ctx.axes is not None:
        from jax import lax

        if len(_ctx.axes) == 1:
            return lax.axis_index(_ctx.axes[0])
        # (cross, local) → flat rank = cross * local_size + local
        return (
            lax.axis_index(_ctx.axes[0]) * st.local_size
            + lax.axis_index(_ctx.axes[1])
        )
    return st.process_index


def local_rank():
    """Rank within the node (reference basics.py:152-160)."""
    st = _require_init()
    if _ctx.axes is not None:
        from jax import lax

        if len(_ctx.axes) == 2:
            return lax.axis_index(_ctx.axes[1])
        return lax.axis_index(_ctx.axes[0]) % st.local_size
    return 0


def cross_rank():
    """Node index of this rank (reference LOCAL/CROSS communicator split,
    horovod/common/common.h:110-114)."""
    st = _require_init()
    if _ctx.axes is not None:
        from jax import lax

        if len(_ctx.axes) == 2:
            return lax.axis_index(_ctx.axes[0])
        return lax.axis_index(_ctx.axes[0]) // st.local_size
    return st.process_index


def process_rank() -> int:
    return _require_init().process_index


def process_size() -> int:
    return _require_init().process_count


def is_homogeneous() -> bool:
    """All nodes have the same local_size — always true for a TPU slice
    (reference basics.py:171-179)."""
    _require_init()
    return True


# --- capability probes, mirroring horovod.common.util/basics feature checks
# (reference horovod/common/basics.py:83-150: mpi_enabled, mpi_built,
#  gloo_enabled, nccl_built, ddl_built, ccl_built, cuda_built, rocm_built).
def mpi_enabled() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def xla_built() -> bool:
    """The one true data plane here."""
    return True


def mpi_threads_supported() -> bool:
    return False
