"""horovod_tpu.keras — standalone-Keras entry point.

Mirror of ``horovod.keras`` (reference horovod/keras/__init__.py:33-60:
builds its DistributedOptimizer over the TensorFlow backend via
``_impl.create_distributed_optimizer``, plus the shared callbacks from
horovod/_keras).  In the TF2/Keras-3 era standalone Keras rides the same
backend, so this module re-exports the tensorflow.keras binding surface
— ``import horovod_tpu.keras as hvd`` works exactly like the reference's
``import horovod.keras as hvd``.
"""

from ..core import (  # noqa: F401 — capability probes (reference parity)
    ccl_built, ddl_built, gloo_built, gloo_enabled, mpi_built,
    mpi_enabled, mpi_threads_supported, nccl_built,
)
from ..tensorflow.keras import (  # noqa: F401
    Compression, DistributedOptimizer, allgather, allreduce, broadcast,
    broadcast_object, broadcast_variables, callbacks, cross_rank,
    cross_size, init, is_initialized, load_model, local_rank,
    local_size, rank, shutdown, size,
)
