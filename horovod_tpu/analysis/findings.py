"""Finding model + suppression comments for the collective linter.

A finding is one rule violation anchored to a file:line.  Suppressions
follow the pylint shape the repo already documents for its other lints:
``# hvd-lint: disable=HVD001`` on the offending line silences that rule
there; ``# hvd-lint: disable-file=HVD001,HVD004`` (or ``=all``) anywhere
in a file silences rules for the whole file.
"""

from __future__ import annotations

import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: severity vocabulary, ordered weakest → strongest
SEVERITIES = ("warning", "error")

_LINE_RE = re.compile(r"#.*?\bhvd-lint\s*:\s*disable=([A-Za-z0-9_,\s]+)")
_FILE_RE = re.compile(r"#.*?\bhvd-lint\s*:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclass
class Finding:
    rule: str           # e.g. "HVD001"
    message: str
    file: str
    line: int
    col: int = 0
    severity: str = "error"
    related: str = ""   # optional "see also" site ("other.py:12")
    #: machine-checkable payload (the model checker's counterexample)
    extra: Dict[str, object] = field(default_factory=dict)

    def format(self) -> str:
        rel = f"  (see {self.related})" if self.related else ""
        return (f"{self.file}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}{rel}")

    def as_dict(self) -> dict:
        return {
            "rule": self.rule, "message": self.message, "file": self.file,
            "line": self.line, "col": self.col, "severity": self.severity,
            **({"related": self.related} if self.related else {}),
            **(self.extra if self.extra else {}),
        }


@dataclass
class Suppressions:
    """Per-file suppression state parsed straight from source text.

    With :meth:`attach_spans`, suppressions map through the enclosing
    statement's line span: a ``# hvd-lint: disable=`` comment on a
    decorator line, or on the closing paren of a multi-line call,
    silences findings anchored anywhere in that statement — the comment
    and the reported line need not coincide.  Without spans (syntax-error
    files), matching stays exact-line."""

    by_line: Dict[int, Set[str]] = field(default_factory=dict)
    whole_file: Set[str] = field(default_factory=set)
    spans: List[Tuple[int, int]] = field(default_factory=list)

    @classmethod
    def parse(cls, source: str) -> "Suppressions":
        supp = cls()
        for lineno, text in _comment_tokens(source):
            m = _FILE_RE.search(text)
            if m:
                supp.whole_file |= _split_rules(m.group(1))
                continue
            m = _LINE_RE.search(text)
            if m:
                supp.by_line.setdefault(lineno, set()).update(
                    _split_rules(m.group(1))
                )
        return supp

    def attach_spans(self, spans: Sequence[Tuple[int, int]]) -> None:
        """Register statement line spans (visitor.statement_spans) so
        suppressions attach per statement instead of per physical line."""
        self.spans = [tuple(s) for s in spans]

    def _span_of(self, line: int) -> Optional[Tuple[int, int]]:
        best = None
        for start, end in self.spans:
            if start <= line <= end:
                if best is None or (end - start) < (best[1] - best[0]):
                    best = (start, end)
        return best

    def rules_for(self, line: int) -> Set[str]:
        rules = set(self.by_line.get(line, ()))
        span = self._span_of(line)
        if span is not None:
            for lineno in range(span[0], span[1] + 1):
                rules |= self.by_line.get(lineno, set())
        return rules

    def hides(self, finding: Finding) -> bool:
        if "all" in self.whole_file or finding.rule in self.whole_file:
            return True
        rules = self.rules_for(finding.line)
        return "all" in rules or finding.rule in rules


def _split_rules(raw: str) -> Set[str]:
    return {r.strip() for r in raw.split(",") if r.strip()}


def _comment_tokens(source: str) -> List[Tuple[int, str]]:
    """(line, text) per real COMMENT token — suppression syntax quoted in
    a docstring or string literal must NOT disable rules.  Falls back to
    a raw line scan when the file doesn't tokenize (it then carries an
    HVD000 finding anyway, so best effort is fine)."""
    try:
        return [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(io.StringIO(source).readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError, ValueError):
        return [(i, t) for i, t in enumerate(source.splitlines(), 1)
                if "#" in t]


def render_text(findings: Sequence[Finding]) -> str:
    lines = [f.format() for f in findings]
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    lines.append(
        f"hvd_lint: {len(findings)} finding(s) "
        f"({n_err} error(s), {n_warn} warning(s))"
        if findings else "hvd_lint: OK — no findings"
    )
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        {"findings": [f.as_dict() for f in findings],
         "count": len(findings)},
        indent=1,
    )


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.file, f.line, f.col, f.rule))
