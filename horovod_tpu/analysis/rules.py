"""The rule catalogue + the lint driver.

Each rule is a pure function over :class:`~.visitor.FileFacts` (or, for
the cross-file pairing rule, over every file's facts at once).  Rule IDs
are stable API — docs/analysis.md is the user-facing catalogue and the
fixture corpus under tests/lint_fixtures/ pins one known-bad snippet per
rule.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding, Suppressions, sort_findings
from .visitor import CollectiveCall, FileFacts, collect_facts

#: rule id → (severity, one-line summary) — the catalogue
RULES: Dict[str, Tuple[str, str]] = {
    "HVD001": ("error",
               "collective inside rank-divergent control flow (deadlock: "
               "other ranks never reach it)"),
    "HVD002": ("error",
               "collective under data-dependent if/while inside a traced "
               "(spmd/jit) region — ranks may trace different programs"),
    "HVD003": ("error",
               "mismatched collective signature between call sites naming "
               "the same tensor"),
    "HVD004": ("error",
               "blocking host I/O inside a traced (spmd/jit) region"),
    "HVD005": ("warning", "mutable default argument"),
    "HVD006": ("warning", "bare except swallows every error, including "
                          "collective divergence diagnostics"),
    "HVD007": ("warning", "undeclared HVD_* environment variable read "
                          "(not in the utils/env.py inventory)"),
    "HVD008": ("warning", "collective result discarded — the API is "
                          "functional, the reduced value is the return"),
    "HVD016": ("error",
               "ppermute permutation literal is not a bijection "
               "(duplicate source or destination — a duplicated "
               "destination silently overwrites the earlier send)"),
}


def _finding(rule: str, msg: str, path: str, line: int, col: int = 0,
             related: str = "") -> Finding:
    return Finding(rule=rule, message=msg, file=path, line=line, col=col,
                   severity=RULES[rule][0], related=related)


# ---------------------------------------------------------------------------
# per-file rules
# ---------------------------------------------------------------------------
def rule_hvd001(facts: FileFacts) -> List[Finding]:
    out = []
    for br in facts.rank_branches:
        body_kinds = sorted(c.tail for c in br.body)
        orelse_kinds = sorted(c.tail for c in br.orelse)
        if body_kinds == orelse_kinds:
            continue  # both arms run the same collectives, in kind
        # anchor on the collectives of the unbalanced arm(s)
        seen: Set[str] = set()
        for arm, other in ((br.body, orelse_kinds), (br.orelse, body_kinds)):
            counts = dict()
            for k in other:
                counts[k] = counts.get(k, 0) + 1
            for c in arm:
                if counts.get(c.tail, 0) > 0:
                    counts[c.tail] -= 1
                    continue
                key = f"{c.line}:{c.col}"
                if key in seen:
                    continue
                seen.add(key)
                out.append(_finding(
                    "HVD001",
                    f"collective '{c.tail}' runs only when the "
                    f"rank-dependent {br.kind} at line {br.line} takes this "
                    "arm; other ranks block in it forever",
                    facts.path, c.line, c.col,
                ))
    return out


def rule_hvd002(facts: FileFacts) -> List[Finding]:
    out = []
    for br in facts.dynamic_branches:
        for c in br.collectives:
            out.append(_finding(
                "HVD002",
                f"collective '{c.tail}' guarded by a data-dependent "
                f"{br.kind} (line {br.line}) inside a traced region; "
                "per-rank data can trace divergent programs — use "
                "jnp.where / lax.cond on replicated values instead",
                facts.path, c.line, c.col,
            ))
    return out


def rule_hvd003(all_facts: Sequence[FileFacts]) -> List[Finding]:
    """Cross-file: call sites that name the same tensor must agree on the
    collective kind and on every signature keyword both sites spell out."""
    sites: Dict[str, List[Tuple[str, CollectiveCall]]] = {}
    for facts in all_facts:
        for c in facts.calls:
            if c.name_kw:
                sites.setdefault(c.name_kw, []).append((facts.path, c))
    out = []
    for name, group in sites.items():
        if len(group) < 2:
            continue
        ref_path, ref = group[0]
        ref_site = f"{ref_path}:{ref.line}"
        for path, c in group[1:]:
            if c.tail != ref.tail:
                out.append(_finding(
                    "HVD003",
                    f"tensor '{name}' is a '{ref.tail}' at {ref_site} but "
                    f"a '{c.tail}' here — ranks disagreeing on the op kind "
                    "for one name deadlock at negotiation",
                    path, c.line, c.col, related=ref_site,
                ))
                continue
            for kw in sorted(set(ref.signature) & set(c.signature)):
                if ref.signature[kw] != c.signature[kw]:
                    out.append(_finding(
                        "HVD003",
                        f"tensor '{name}' called with {kw}="
                        f"{c.signature[kw]} here but {kw}="
                        f"{ref.signature[kw]} at {ref_site}",
                        path, c.line, c.col, related=ref_site,
                    ))
    return out


def rule_hvd004(facts: FileFacts) -> List[Finding]:
    return [
        _finding(
            "HVD004",
            f"blocking host call '{io.what}' inside a traced region: it "
            "runs at trace time only (never per step) and stalls "
            "compilation — use jax.debug.print/callback for debug output",
            facts.path, io.line, io.col,
        )
        for io in facts.io_calls
    ]


def rule_hvd005(facts: FileFacts) -> List[Finding]:
    return [
        _finding(
            "HVD005",
            f"mutable default argument in '{fn}()' is shared across calls",
            facts.path, line, col,
        )
        for line, col, fn in facts.mutable_defaults
    ]


def rule_hvd006(facts: FileFacts) -> List[Finding]:
    return [
        _finding(
            "HVD006",
            "bare 'except:' catches SystemExit/KeyboardInterrupt and hides "
            "collective divergence diagnostics — name the exceptions",
            facts.path, line, col,
        )
        for line, col in facts.bare_excepts
    ]


_DECL_RE = re.compile(r"^(HVD_[A-Z0-9_]+)\s*=", re.M)


def declared_knobs() -> Set[str]:
    """The HVD_* inventory: scripts/check_env_vars.py's ``declared_knobs``
    when the script is present (source checkouts), else the same
    module-level-assignment regex over utils/env.py directly."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(os.path.dirname(pkg_dir), "scripts",
                          "check_env_vars.py")
    if os.path.isfile(script):
        import importlib.util as _ilu

        spec = _ilu.spec_from_file_location("_hvd_check_env_vars", script)
        mod = _ilu.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
            return set(mod.declared_knobs())
        except Exception:  # noqa: BLE001 — fall through to the local parse
            pass
    try:
        with open(os.path.join(pkg_dir, "utils", "env.py")) as f:
            return set(_DECL_RE.findall(f.read()))
    except OSError:
        return set()


def rule_hvd007(facts: FileFacts,
                knobs: Optional[Set[str]] = None) -> List[Finding]:
    knobs = declared_knobs() if knobs is None else knobs
    return [
        _finding(
            "HVD007",
            f"env var '{er.var}' is read here but not declared in "
            "horovod_tpu/utils/env.py — invisible to tpurun/YAML/docs "
            "(see scripts/check_env_vars.py)",
            facts.path, er.line, er.col,
        )
        for er in facts.env_reads if er.var not in knobs
    ]


def rule_hvd008(facts: FileFacts) -> List[Finding]:
    from .collective_api import MUTATING_COLLECTIVES

    return [
        _finding(
            "HVD008",
            f"result of '{c.tail}' is discarded — collectives are "
            "functional here (no in-place mutation); assign the return "
            "value",
            facts.path, c.line, c.col,
        )
        for c in facts.calls
        if c.discarded and c.tail not in MUTATING_COLLECTIVES
    ]


def rule_hvd016(facts: FileFacts) -> List[Finding]:
    """A ppermute permutation literal must be a bijection on the pairs
    it names: each source sends at most once and each destination
    receives at most once.  A duplicated destination silently
    overwrites the earlier send (last-writer-wins, no error at
    dispatch); a duplicated source drops all but one of its sends."""
    out = []
    for pc in facts.perm_calls:
        srcs = [s for s, _ in pc.pairs]
        dsts = [d for _, d in pc.pairs]
        dup_src = sorted({s for s in srcs if srcs.count(s) > 1})
        dup_dst = sorted({d for d in dsts if dsts.count(d) > 1})
        if not dup_src and not dup_dst:
            continue
        bits = []
        if dup_dst:
            bits.append(
                "destination(s) " + ", ".join(map(str, dup_dst))
                + " receive from multiple sources — the later send "
                  "silently overwrites the earlier one")
        if dup_src:
            bits.append(
                "source(s) " + ", ".join(map(str, dup_src))
                + " send more than once — only one send survives")
        out.append(_finding(
            "HVD016",
            f"'{pc.tail}' permutation {pc.pairs} is not a bijection: "
            + "; ".join(bits),
            facts.path, pc.line, pc.col,
        ))
    return out


_FILE_RULES = (rule_hvd001, rule_hvd002, rule_hvd004, rule_hvd005,
               rule_hvd006, rule_hvd008, rule_hvd016)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def _disabled_from_env() -> Set[str]:
    from ..utils import env as env_util

    raw = env_util.get_str(env_util.HVD_LINT_DISABLE) or ""
    return {r.strip() for r in raw.split(",") if r.strip()}


def lint_sources(sources: Sequence[Tuple[str, str]],
                 disable: Iterable[str] = ()) -> List[Finding]:
    """Lint (path, source) pairs as one session (cross-file pairing sees
    the whole set).  ``disable`` drops rule IDs on top of any set in the
    HVD_LINT_DISABLE env knob."""
    disabled = set(disable) | _disabled_from_env()
    findings: List[Finding] = []
    all_facts: List[FileFacts] = []
    supp: Dict[str, Suppressions] = {}
    for path, source in sources:
        supp[path] = Suppressions.parse(source)
        try:
            facts = collect_facts(source, path)
            # suppressions attach through statement spans: a disable on a
            # decorator line or on the closing paren of a multi-line call
            # covers the statement's reported finding line
            supp[path].attach_spans(facts.spans)
            all_facts.append(facts)
        except SyntaxError as e:
            findings.append(Finding(
                rule="HVD000", message=f"syntax error: {e.msg}",
                file=path, line=e.lineno or 1, col=e.offset or 0,
                severity="error",
            ))
    knobs = declared_knobs()  # once per session, not per file
    for facts in all_facts:
        for rule in _FILE_RULES:
            findings.extend(rule(facts))
        findings.extend(rule_hvd007(facts, knobs))
    findings.extend(rule_hvd003(all_facts))
    findings = [
        f for f in findings
        if f.rule not in disabled
        and not (f.file in supp and supp[f.file].hides(f))
    ]
    return sort_findings(findings)


#: the repo's own known-bad fixture corpus — the ONE lint_fixtures dir
#: excluded from directory walks; a user dir that happens to share the
#: name is still linted
_OWN_FIXTURES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "lint_fixtures")


def iter_python_files(paths: Sequence[str]) -> List[str]:
    """Expand files/dirs into a sorted .py file list, skipping hidden
    dirs, conventional build output, and the linter's own known-bad
    fixture corpus (that exact path only)."""
    skip_dirs = {".git", "__pycache__", "build", "node_modules"}
    out: List[str] = []
    for p in paths:
        if not os.path.exists(p):
            # a typo'd CI path must be exit 2, not a green "OK" over
            # zero files (os.walk on a missing dir yields nothing)
            raise OSError(f"no such file or directory: {p}")
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d not in skip_dirs and not d.startswith(".")
                and os.path.abspath(os.path.join(root, d)) != _OWN_FIXTURES
            )
            out.extend(os.path.join(root, f) for f in sorted(files)
                       if f.endswith(".py"))
    return sorted(set(out))


def read_sources(paths: Sequence[str]) -> Tuple[List[Tuple[str, str]],
                                                List[Finding]]:
    """Expand + read files/dirs once for any analyzer: (path, source)
    pairs plus HVD000 findings for unreadable files.  Raises OSError on
    a nonexistent path (→ CLI exit 2).  Shared by lint_paths,
    schedule.check_paths, and ``hvd_lint --model-check`` (which runs
    both analyzers over one read of the tree)."""
    sources: List[Tuple[str, str]] = []
    unreadable: List[Finding] = []
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                sources.append((path, f.read()))
        except OSError as e:
            unreadable.append(
                Finding(rule="HVD000", message=f"unreadable: {e}",
                        file=path, line=1, severity="error")
            )
    return sources, unreadable


def lint_paths(paths: Sequence[str],
               disable: Iterable[str] = ()) -> List[Finding]:
    """Lint files/dirs.  Raises OSError on a nonexistent path (→ CLI
    exit 2); an unreadable file becomes an HVD000 finding without
    discarding the rest of the run."""
    sources, unreadable = read_sources(paths)
    return sort_findings(
        unreadable + lint_sources(sources, disable=disable)
    )
