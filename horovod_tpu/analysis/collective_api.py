"""Static model of the collective API surface the linter reasons about.

One place that knows which callables move data across ranks — the
device-plane ops (ops/collectives.py), the eager wrappers (eager.py),
the host-plane ``process_*`` bridges, the framework bindings' in-place
broadcasts, and the raw ``jax.lax`` primitives they all lower to.  The
linter matches call sites by the *final* attribute name (``hvd.allreduce``,
``collectives.allreduce`` and a bare imported ``allreduce`` all resolve to
``allreduce``): import-alias tracking would miss ``getattr`` indirection
anyway, and collective names are distinctive enough that tail matching is
the right precision/recall point for review-time linting.
"""

from __future__ import annotations

#: device-plane collectives (ops/collectives.py public surface)
DEVICE_COLLECTIVES = frozenset({
    "allreduce", "grouped_allreduce", "allreduce_gradients",
    "allgather", "allgatherv", "broadcast", "alltoall", "reducescatter",
    "allreduce_indexed_slices",
})

#: eager per-rank-list wrappers (eager.py)
EAGER_COLLECTIVES = frozenset({
    "allreduce_", "allgather_", "broadcast_",
})

#: host-plane (process) collectives, incl. the controller data plane
HOST_COLLECTIVES = frozenset({
    "process_allreduce", "process_allgather", "process_broadcast",
    "broadcast_object", "allgather_object",
    "allreduce_data", "allgather_data", "broadcast_data",
    "join_allreduce",
})

#: in-place / state-mutating collective helpers whose return value is
#: legitimately discarded (torch/TF parameter sync, elastic join)
MUTATING_COLLECTIVES = frozenset({
    "broadcast_parameters", "broadcast_variables",
    "broadcast_optimizer_state", "join",
})

#: raw XLA collective primitives (jax.lax)
LAX_COLLECTIVES = frozenset({
    "psum", "pmin", "pmax", "pmean", "all_gather", "psum_scatter",
    "all_to_all", "ppermute", "pshuffle",
})

#: every name that counts as "a collective runs here"
ALL_COLLECTIVES = (DEVICE_COLLECTIVES | EAGER_COLLECTIVES
                   | HOST_COLLECTIVES | MUTATING_COLLECTIVES
                   | LAX_COLLECTIVES)

#: rank-query calls: an ``if`` keyed on one of these diverges per rank
RANK_CALLS = frozenset({
    "rank", "local_rank", "cross_rank", "process_rank",
    "node_rank", "axis_index", "process_index",
})

#: decorators / wrappers that put a function on the compiled (traced) path
TRACE_WRAPPERS = frozenset({
    "spmd", "jit", "pjit", "shard_map", "pmap", "scan_steps",
})

#: call tails that block the host thread or touch the filesystem —
#: poison inside traced code (each trace replays them at compile time and
#: never at step time, which is almost never what the author meant)
BLOCKING_BARE_CALLS = frozenset({"print", "open", "input", "breakpoint"})
BLOCKING_DOTTED_CALLS = frozenset({
    ("time", "sleep"), ("os", "system"), ("os", "popen"),
    ("pickle", "dump"), ("pickle", "load"),
    ("np", "save"), ("np", "load"), ("numpy", "save"), ("numpy", "load"),
    ("json", "dump"), ("json", "load"),
})
#: any call whose base module is one of these is host I/O
BLOCKING_BASE_MODULES = frozenset({"subprocess", "requests", "urllib"})
#: debug-plane escapes that are legal inside traced code
TRACE_SAFE_DOTTED = frozenset({
    ("debug", "print"), ("debug", "callback"), ("debug", "breakpoint"),
})

#: keywords whose disagreement between two sites naming the same tensor
#: is a cross-rank signature mismatch (the coordinator would reject or,
#: worse, deadlock on it at runtime — controller.cc:377-610).
#: ``compression`` is the wire format: two ranks reducing one bucket in
#: different formats (docs/compression.md) sum incompatible payloads.
SIGNATURE_KEYWORDS = ("op", "root_rank", "process_set", "dtype",
                      "compression")

#: point-to-point lax primitives: collective-permutes with an explicit
#: sender→receiver pairing (the schedule checker lowers these to
#: SendRecv events; the sanitizer folds their permutation into the
#: fingerprint so permutation divergence is a signature mismatch)
P2P_COLLECTIVES = frozenset({"ppermute", "pshuffle"})

#: ``lax.all_to_all`` layout keywords — part of the dispatch identity:
#: two ranks disagreeing on split/concat axes or tiling exchange
#: incompatibly-shaped shards
SHUFFLE_KEYWORDS = ("split_axis", "concat_axis", "tiled")


#: tails too generic to match on name alone — only these attribute bases
#: (or a bare imported name) count.  ``join`` collides with
#: ``os.path.join`` / ``Thread.join`` / ``str.join``.
AMBIGUOUS_TAILS = {"join": frozenset({"hvd", "horovod_tpu", "elastic"})}


def is_collective(tail: str) -> bool:
    return tail in ALL_COLLECTIVES


def is_collective_call(dotted) -> bool:
    """Whether a call target (its dotted-name tuple) is a collective.
    Tail-name matching, except ambiguous tails require a known base."""
    if not dotted or dotted[-1] not in ALL_COLLECTIVES:
        return False
    bases = AMBIGUOUS_TAILS.get(dotted[-1])
    if bases is not None and len(dotted) > 1 and dotted[-2] not in bases:
        return False
    return True


def is_rank_call(tail: str) -> bool:
    return tail in RANK_CALLS


def is_trace_wrapper(tail: str) -> bool:
    return tail in TRACE_WRAPPERS
