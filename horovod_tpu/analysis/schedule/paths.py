"""Bounded symbolic enumeration of per-rank execution paths.

A *path* is one way one rank can execute an entry point: a tuple of
branch decisions plus the collective sequence those decisions project.
Decisions carry the taint flavor of their branch:

* **uniform** decisions are taken identically by every rank of one run —
  two paths model the same run only when their uniform decisions agree;
* **rank / data / exception** decisions may differ *between ranks of the
  same run* — these are the decisions a counterexample's branch chain is
  made of.

Loops are unrolled up to the loop bound (HVD_VERIFY_LOOP_BOUND); the
total number of paths per entry is capped (HVD_VERIFY_MAX_PATHS) with
the truncation surfaced to the caller — a bounded "verified" is reported
as bounded, never as exhaustive.  Calls are inlined through the call
graph with cycle detection; each projected collective remembers its call
stack so counterexamples can print the interprocedural route.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Tuple

from .callgraph import CallGraph
from .ir import (
    DIVERGENT_FLAVORS,
    Branch,
    Call,
    Collective,
    Entry,
    FunctionInfo,
    Loop,
    Raise,
    Return,
)

DEFAULT_MAX_PATHS = 64
DEFAULT_LOOP_BOUND = 2


@dataclass(frozen=True)
class Decision:
    site: str                 # "file:line"
    kind: str                 # "if" | "while" | "try" | "loop"
    flavor: str
    condition: str
    taken: str                # "then" | "else" | "raised" | "Nx" …

    def divergent(self) -> bool:
        return self.flavor in DIVERGENT_FLAVORS


@dataclass(frozen=True)
class Dispatch:
    """One projected collective dispatch on one path."""

    collective: Collective
    stack: Tuple[str, ...]    # call sites from the entry ("file:line fn")

    def key(self) -> Tuple:
        return self.collective.key()


@dataclass
class _Partial:
    decisions: Tuple[Decision, ...] = ()
    events: Tuple[Dispatch, ...] = ()
    terminated: Optional[str] = None      # None | "return" | "raise"


@dataclass(frozen=True)
class Path:
    entry: Entry
    decisions: Tuple[Decision, ...]
    events: Tuple[Dispatch, ...]

    def uniform_key(self) -> Tuple[Decision, ...]:
        return tuple(d for d in self.decisions if not d.divergent())

    def divergent_decisions(self) -> Tuple[Decision, ...]:
        return tuple(d for d in self.decisions if d.divergent())


@dataclass
class EnumerationResult:
    paths: List[Path] = field(default_factory=list)
    truncated: bool = False
    #: loops this entry's paths unrolled to the loop bound, as
    #: ("file:line", kind) in first-encounter order — surfaced so a
    #: truncated (pipeline micro-batch) deadlock search is visible,
    #: never silent
    loops: List[Tuple[str, str]] = field(default_factory=list)


class Enumerator:
    def __init__(self, graph: CallGraph, *,
                 max_paths: int = DEFAULT_MAX_PATHS,
                 loop_bound: int = DEFAULT_LOOP_BOUND):
        self.graph = graph
        self.max_paths = max(1, int(max_paths))
        self.loop_bound = max(0, int(loop_bound))
        self._truncated = False
        # per-callee path summaries (relative call stacks), so a callee
        # is enumerated once per session instead of once per caller
        # partial path — without this, nested calls go exponential
        self._fn_cache: dict = {}
        self._fn_in_progress: set = set()
        # per-branch-arm / per-loop-body enumerations, same reason
        self._arm_cache: dict = {}
        self._body_cache: dict = {}

    # -- public --------------------------------------------------------------
    def enumerate(self, entry: Entry) -> EnumerationResult:
        self._truncated = False
        partials = self._block(entry.fn.body, entry.fn,
                               inline=(entry.fn.qualname,))
        seen = set()
        paths: List[Path] = []
        loops: dict = {}
        for p in partials:
            path = Path(entry=entry, decisions=p.decisions, events=p.events)
            key = (path.decisions, tuple(d.key() for d in path.events))
            if key not in seen:
                seen.add(key)
                paths.append(path)
            for d in p.decisions:
                if d.kind == "loop":
                    loops.setdefault(d.site, d.condition)
        return EnumerationResult(paths=paths, truncated=self._truncated,
                                 loops=list(loops.items()))

    # -- internals -----------------------------------------------------------
    # Call stacks are attached only when a callee summary is spliced into
    # a caller (_compose), so _block always enumerates with relative
    # stacks and every sub-enumeration — callee bodies, branch arms, loop
    # bodies — is computed once and reused, keeping the whole pass
    # polynomial in program size (times the path cap).
    def _cap(self, partials: List[_Partial]) -> List[_Partial]:
        if len(partials) > self.max_paths:
            self._truncated = True
            return partials[: self.max_paths]
        return partials

    @staticmethod
    def _compose(p: _Partial, sub: _Partial,
                 frame: Optional[str] = None,
                 pre: Tuple[Decision, ...] = ()) -> _Partial:
        events = sub.events if frame is None else tuple(
            Dispatch(collective=d.collective, stack=(frame,) + d.stack)
            for d in sub.events)
        term = sub.terminated
        if frame is not None and term == "return":
            term = None  # a return only exits the callee
        return _Partial(
            decisions=p.decisions + pre + sub.decisions,
            events=p.events + events,
            terminated=term,
        )

    def _block(self, events, fn: FunctionInfo,
               inline: Tuple[str, ...]) -> List[_Partial]:
        partials = [_Partial()]
        for ev in events:
            nxt: List[_Partial] = []
            for p in partials:
                if p.terminated:
                    nxt.append(p)
                    continue
                nxt.extend(self._event(ev, p, fn, inline))
            partials = self._cap(nxt)
        return partials

    def _event(self, ev, p: _Partial, fn: FunctionInfo,
               inline: Tuple[str, ...]) -> List[_Partial]:
        if isinstance(ev, Collective):
            return [replace(p, events=p.events
                            + (Dispatch(collective=ev, stack=()),))]
        if isinstance(ev, Return):
            return [replace(p, terminated="return")]
        if isinstance(ev, Raise):
            return [replace(p, terminated="raise")]
        if isinstance(ev, Call):
            return self._call(ev, p, fn, inline)
        if isinstance(ev, Branch):
            return self._branch(ev, p, fn, inline)
        if isinstance(ev, Loop):
            return self._loop(ev, p, fn, inline)
        return [p]

    def _call(self, ev: Call, p: _Partial, fn: FunctionInfo,
              inline: Tuple[str, ...]) -> List[_Partial]:
        callee = self.graph.resolve(ev.target, from_file=fn.site.file)
        if callee is None or callee.qualname in inline \
                or callee.qualname in self._fn_in_progress:
            return [p]  # opaque / recursive — no schedule contribution
        subs = self._fn_summary(callee)
        if not subs:
            return [p]
        frame = f"{ev.site} {ev.target}()"
        return [self._compose(p, sub, frame=frame) for sub in subs]

    def _fn_summary(self, callee: FunctionInfo) -> List[_Partial]:
        """The callee's own path summaries (call stacks relative to the
        callee), computed once and reused at every call site.  Summaries
        with no decisions, events, or raise are collapsed away."""
        key = callee.qualname
        if key in self._fn_cache:
            return self._fn_cache[key]
        self._fn_in_progress.add(key)
        try:
            subs = self._block(callee.body, callee, inline=(key,))
        finally:
            self._fn_in_progress.discard(key)
        seen = set()
        pruned: List[_Partial] = []
        for sub in subs:
            term = "raise" if sub.terminated == "raise" else "return"
            if not (sub.decisions or sub.events or term == "raise"):
                continue
            k = (sub.decisions, tuple(d.key() for d in sub.events), term)
            if k in seen:
                continue
            seen.add(k)
            pruned.append(_Partial(decisions=sub.decisions,
                                   events=sub.events, terminated=term))
        self._fn_cache[key] = pruned
        return pruned

    def _arms(self, ev: Branch, fn: FunctionInfo, inline: Tuple[str, ...]):
        cached = self._arm_cache.get(id(ev))
        if cached is not None:
            return cached
        arms = [("then", ev.body), ("else", ev.orelse)]
        if ev.kind == "try":
            arms = [("no raise", ev.orelse), ("raised", ev.body)]
        elif ev.kind == "while":
            arms = [("enter once", ev.body), ("skip", ev.orelse)]
        out = [(taken, self._block(arm, fn, inline)) for taken, arm in arms]
        self._arm_cache[id(ev)] = out
        return out

    def _branch(self, ev: Branch, p: _Partial, fn: FunctionInfo,
                inline: Tuple[str, ...]) -> List[_Partial]:
        site = str(ev.site)
        out: List[_Partial] = []
        for taken, subs in self._arms(ev, fn, inline):
            d = Decision(site=site, kind=ev.kind, flavor=ev.flavor,
                         condition=ev.condition, taken=taken)
            for sub in subs:
                out.append(self._compose(p, sub, pre=(d,)))
        return out

    def _loop(self, ev: Loop, p: _Partial, fn: FunctionInfo,
              inline: Tuple[str, ...]) -> List[_Partial]:
        site = str(ev.site)
        body_variants = self._body_cache.get(id(ev))
        if body_variants is None:
            body_variants = self._block(ev.body, fn, inline)
            self._body_cache[id(ev)] = body_variants
        out: List[_Partial] = []
        for k in range(self.loop_bound + 1):
            d = Decision(site=site, kind="loop", flavor="uniform",
                         condition=ev.kind, taken=f"{k} iteration(s)")
            seeds = [replace(p, decisions=p.decisions + (d,))]
            for _ in range(k):
                nxt: List[_Partial] = []
                for seed in seeds:
                    if seed.terminated:
                        nxt.append(seed)
                        continue
                    for sub in body_variants:
                        nxt.append(self._compose(seed, sub))
                seeds = self._cap(nxt)
            out.extend(seeds)
        return self._cap(out)
