"""AST → schedule IR lowering (one file at a time).

Shares the linter's model of the collective surface (collective_api) and
its taint discipline (visitor.py): ``if``/``while`` conditions are
classified *rank*-flavored when keyed on a ``rank()``-family call or a
local tainted by one, *data*-flavored when keyed on a traced function's
own inputs, *uniform* otherwise.  On top of the flat facts the linter
collects, this keeps the tree structure — arms, loops, try/except, calls
— because the model checker needs whole-path ordering, not single
statements.

Group assignment for a collective call site:

* ``axis_index_groups=<expr>`` → ``local`` / ``cross`` when the expression
  text names one, else ``groups:<expr>``;
* ``process_set=<expr>`` → ``process_set:<expr>``;
* ``two_level=True`` / ``hierarchical=True`` kwargs, or a direct call to
  ``two_level_allreduce`` / ``hierarchical_allreduce``, expand into the
  three stage dispatches the runtime actually issues — reduce-scatter on
  the local group, the reduction on the cross group, all-gather on the
  local group (parallel/hierarchical.py);
* a raw ``lax`` primitive's positional/``axis_name=`` mesh-axis argument
  → ``axis:<name>`` for a string constant, ``axis:<expr>`` for a
  symbolic axis (two sites share the group iff they spell the same
  axis — same contract as ``groups:<expr>``);
* everything else → ``world``.

On top of groups, the mesh-specific lowerings for the ``parallel/``
islands: ``ppermute``/``pshuffle`` become :class:`~.ir.SendRecv` events
carrying their permutation (HVD013's input), ``lax.scan(body, …)`` over
a file-local ``def`` becomes a :class:`~.ir.Loop` of kind ``"scan"``
inlining the body (the pipeline micro-batch loop, unrolled to the loop
bound), mesh declarations (``jax.make_mesh((2, 3), ("dp", "pp"))`` and
``Mesh(mesh_utils.create_device_mesh(…), …)``) record literal axis sizes,
and an ``all_to_all`` splitting a literal-reshaped leading dimension
records that size as its axis-shape assumption (HVD015's input).  Branch
taint is unchanged: ``lax.axis_index(axis)`` is in the rank-call family,
so a branch on it is per-member of the axis — rank-flavored.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .. import collective_api as api
from ..visitor import _dotted, _sig_source, _tail
from .ir import (
    FLAVOR_DATA,
    FLAVOR_EXCEPTION,
    FLAVOR_RANK,
    FLAVOR_UNIFORM,
    GROUP_CROSS,
    GROUP_LOCAL,
    GROUP_WORLD,
    Branch,
    Call,
    Collective,
    Event,
    FunctionInfo,
    Loop,
    Raise,
    Return,
    SendRecv,
    Site,
    axis_group,
)

#: direct hierarchical entry points that expand into stage dispatches
_TWO_LEVEL_TAILS = frozenset({"two_level_allreduce", "hierarchical_allreduce"})

#: call tails that never resolve to user schedule code — don't record
#: Call events for them (keeps paths small and resolution unambiguous)
_OPAQUE_TAILS = frozenset({
    "print", "len", "range", "enumerate", "zip", "sorted", "isinstance",
    "int", "float", "str", "list", "dict", "set", "tuple", "getattr",
    "setattr", "hasattr", "super", "type", "format", "min", "max", "sum",
    "abs", "append", "extend", "update", "items", "keys", "values", "get",
    "join", "split", "strip", "reshape", "astype", "mean", "copy",
})


def _truthy_const(node) -> bool:
    return isinstance(node, ast.Constant) and bool(node.value)


def _expr_text(node, limit: int = 60) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # noqa: BLE001 — exotic node
        return "<expr>"
    return text if len(text) <= limit else text[: limit - 1] + "…"


def classify_groups_expr(text: str) -> str:
    """Map an ``axis_index_groups=`` expression to a group label by its
    source text — ``_local_groups()`` and friends carry their meaning in
    the name; anything else keeps the expression as an opaque label (two
    sites agree on the group iff they spell the same expression)."""
    low = text.lower()
    if "local" in low:
        return GROUP_LOCAL
    if "cross" in low or "dcn" in low:
        return GROUP_CROSS
    return f"groups:{text}"


#: point-to-point lax primitives, lowered to SendRecv events
_P2P_TAILS = frozenset({"ppermute", "pshuffle"})


def classify_axis_expr(node) -> str:
    """Map a lax primitive's mesh-axis argument to an ``axis:`` group
    label: a string constant names the axis directly; anything else is
    symbolic and keeps its source text (two sites share the axis iff
    they spell the same expression — the ``groups:<expr>`` contract)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return axis_group(node.value)
    return axis_group(_expr_text(node))


def _int_tuple(node) -> Optional[tuple]:
    """A literal tuple/list of ints, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for el in node.elts:
        if not (isinstance(el, ast.Constant) and isinstance(el.value, int)
                and not isinstance(el.value, bool)):
            return None
        out.append(el.value)
    return tuple(out)


def _str_tuple(node) -> Optional[tuple]:
    """A literal tuple/list of strings, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for el in node.elts:
        if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
            return None
        out.append(el.value)
    return tuple(out)


def _perm_literal_pairs(node) -> Optional[tuple]:
    """A literal ppermute permutation — ``[(src, dst), …]`` with int
    constants — as a tuple of pairs, else None (symbolic perms keep
    only their source text)."""
    if not isinstance(node, (ast.List, ast.Tuple)):
        return None
    pairs = []
    for el in node.elts:
        pair = _int_tuple(el)
        if pair is None or len(pair) != 2:
            return None
        pairs.append(pair)
    return tuple(pairs)


class _Frame:
    __slots__ = ("traced", "params", "rank_tainted", "data_tainted",
                 "leading_dim")

    def __init__(self, traced: bool, params: Set[str]):
        self.traced = traced
        self.params = params
        self.rank_tainted: Set[str] = set()
        self.data_tainted: Set[str] = set()
        #: locals last assigned from ``x.reshape(<int literal>, …)`` —
        #: the literal leading dimension an all_to_all over them splits
        self.leading_dim: Dict[str, int] = {}


class Extractor:
    """One file's extraction pass: produces a FunctionInfo per def (and
    one for the module body) with structured event lists."""

    def __init__(self, path: str, tree: ast.AST):
        self.path = path
        self.tree = tree
        self.functions: List[FunctionInfo] = []
        self._frames: List[_Frame] = [_Frame(False, set())]
        self._wrapped = self._wrapped_names(tree)
        self._elastic = self._elastic_bodies(tree)
        # whole-file def names: a local ``def broadcast_(…)`` shadows the
        # framework collective everywhere in the file (visitor.py rule)
        self._local_defs = {
            n.name for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        #: axis name → (declared size, site) from literal mesh
        #: declarations in this file — HVD015's ground truth
        self.axis_sizes: Dict[str, tuple] = self._mesh_axis_sizes(tree)

    def _mesh_axis_sizes(self, tree) -> Dict[str, tuple]:
        """Literal mesh-axis declarations: ``jax.make_mesh((2, 3),
        ("dp", "pp"))`` directly, or ``Mesh(mesh_utils.
        create_device_mesh((2, 3)), ("dp", "pp"))`` through the device
        mesh helper.  Only fully-literal shapes count — a symbolic mesh
        declares nothing the checker can hold collectives to."""
        sizes: Dict[str, tuple] = {}
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and len(node.args) >= 2):
                continue
            tail = _tail(node.func)
            shape = names = None
            if tail == "make_mesh":
                shape = _int_tuple(node.args[0])
                names = _str_tuple(node.args[1])
            elif tail == "Mesh":
                names = _str_tuple(node.args[1])
                dev = node.args[0]
                if isinstance(dev, ast.Call) \
                        and _tail(dev.func) == "create_device_mesh" \
                        and dev.args:
                    shape = _int_tuple(dev.args[0])
            if shape and names and len(shape) == len(names):
                site = self._site(node)
                for name, n in zip(names, shape):
                    sizes.setdefault(name, (n, site))
        return sizes

    # -- module-level discovery ---------------------------------------------
    @staticmethod
    def _wrapped_names(tree) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and api.is_trace_wrapper(_tail(node.func)) \
                    and node.args and isinstance(node.args[0], ast.Name):
                names.add(node.args[0].id)
        return names

    @staticmethod
    def _elastic_bodies(tree) -> Set[str]:
        """Functions passed to ``hvd.elastic.run(fn, …)`` — per-epoch
        entry points (elastic/membership.py run wrapper)."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _tail(node.func) == "run":
                d = _dotted(node.func)
                if len(d) >= 2 and d[-2] == "elastic" and node.args \
                        and isinstance(node.args[0], ast.Name):
                    names.add(node.args[0].id)
        return names

    def extract(self) -> List[FunctionInfo]:
        module = FunctionInfo(
            name="<module>", site=Site(self.path, 1), traced=False,
        )
        module.body = self._lower_block(self.tree.body)
        self.functions.append(module)
        return self.functions

    # -- helpers shared with the linter's visitor ---------------------------
    @property
    def _frame(self) -> _Frame:
        return self._frames[-1]

    def _rank_dep(self, expr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and api.is_rank_call(_tail(node)):
                return True
            if isinstance(node, ast.Name) \
                    and any(node.id in f.rank_tainted for f in self._frames):
                return True
        return False

    def _data_dep(self, expr) -> bool:
        f = self._frame
        if not f.traced:
            return False
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) \
                    and (node.id in f.params or node.id in f.data_tainted):
                return True
        return False

    def _flavor(self, test) -> str:
        if self._rank_dep(test):
            return FLAVOR_RANK
        if self._data_dep(test):
            return FLAVOR_DATA
        return FLAVOR_UNIFORM

    def _taint_targets(self, targets, value) -> None:
        rank = self._rank_dep(value)
        data = self._data_dep(value)
        if not (rank or data):
            return
        for t in targets:
            for node in ast.walk(t):
                if isinstance(node, ast.Name):
                    if rank:
                        self._frame.rank_tainted.add(node.id)
                    if data:
                        self._frame.data_tainted.add(node.id)

    def _site(self, node) -> Site:
        return Site(self.path, node.lineno, getattr(node, "col_offset", 0))

    def _track_leading_dim(self, targets, value) -> None:
        """Keep the frame's literal-leading-dimension map current: a
        single-Name assignment from ``x.reshape(<int>, …)`` records the
        literal; any other assignment to the name invalidates it."""
        if len(targets) != 1 or not isinstance(targets[0], ast.Name):
            return
        name = targets[0].id
        lead = None
        if isinstance(value, ast.Call) and _tail(value.func) == "reshape" \
                and value.args:
            first = value.args[0]
            if isinstance(first, ast.Constant) \
                    and isinstance(first.value, int) \
                    and not isinstance(first.value, bool):
                lead = first.value
        if lead is None:
            self._frame.leading_dim.pop(name, None)
        else:
            self._frame.leading_dim[name] = lead

    # -- collective lowering -------------------------------------------------
    def _collective_events(self, node: ast.Call, cleanup: str) -> List[Event]:
        tail = _tail(node.func)
        site = self._site(node)
        name_kw = None
        sig: Dict[str, str] = {}
        group = GROUP_WORLD
        staged = tail in _TWO_LEVEL_TAILS
        axis_kw = None
        perm_kw = None
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                name_kw = kw.value.value
            elif kw.arg in api.SIGNATURE_KEYWORDS:
                sig[kw.arg] = _sig_source(kw.value)
            elif kw.arg == "axis_index_groups" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None):
                group = classify_groups_expr(_expr_text(kw.value))
            elif kw.arg == "process_set" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None):
                group = f"process_set:{_expr_text(kw.value)}"
            elif kw.arg in ("two_level", "hierarchical") \
                    and _truthy_const(kw.value):
                staged = True
            elif kw.arg == "axis_name":
                axis_kw = kw.value
            elif kw.arg == "perm":
                perm_kw = kw.value
            elif kw.arg in api.SHUFFLE_KEYWORDS and tail == "all_to_all":
                sig[kw.arg] = _sig_source(kw.value)
        if staged:
            # the three stage dispatches the runtime issues
            # (parallel/hierarchical.py: local RS → cross AR → local AG)
            return [
                Collective(op="reducescatter", name=name_kw,
                           group=GROUP_LOCAL, signature={}, site=site,
                           cleanup=cleanup),
                Collective(op="allreduce", name=name_kw, group=GROUP_CROSS,
                           signature=sig, site=site, cleanup=cleanup),
                Collective(op="allgather", name=name_kw, group=GROUP_LOCAL,
                           signature={}, site=site, cleanup=cleanup),
            ]
        if tail in api.LAX_COLLECTIVES and group == GROUP_WORLD:
            # the raw primitives take the mesh axis positionally (or as
            # axis_name=): lax.psum(x, "pp") communicates on axis:pp, not
            # on the whole world — a subgroup label like local/cross wins
            # when axis_index_groups restricts membership further
            axis = axis_kw if axis_kw is not None else (
                node.args[1] if len(node.args) >= 2 else None)
            if axis is not None:
                group = classify_axis_expr(axis)
        if tail in _P2P_TAILS:
            perm = perm_kw if perm_kw is not None else (
                node.args[2] if len(node.args) >= 3 else None)
            return [SendRecv(
                op=tail, name=name_kw, group=group, signature=sig,
                site=site, cleanup=cleanup,
                perm=_expr_text(perm) if perm is not None else "",
                pairs=_perm_literal_pairs(perm),
            )]
        assumes = None
        if tail == "all_to_all" and sig.get("split_axis") == "0" \
                and sig.get("tiled") not in ("True", "true"):
            assumes = self._leading_literal(node.args[0]) if node.args \
                else None
        return [Collective(op=tail, name=name_kw, group=group, signature=sig,
                           site=site, cleanup=cleanup, assumes_size=assumes)]

    def _leading_literal(self, operand) -> Optional[int]:
        """The literal leading dimension of an all_to_all operand, when
        visible: either a direct ``x.reshape(<int>, …)`` or a local last
        assigned from one (frame-tracked).  That dimension is the split
        dimension, which an untiled split-axis-0 all_to_all requires to
        EQUAL the axis size — the MoE dispatch contract."""
        if isinstance(operand, ast.Call) and _tail(operand.func) == "reshape" \
                and operand.args:
            lead = operand.args[0]
            if isinstance(lead, ast.Constant) and isinstance(lead.value, int) \
                    and not isinstance(lead.value, bool):
                return lead.value
        if isinstance(operand, ast.Name):
            for f in reversed(self._frames):
                if operand.id in f.leading_dim:
                    return f.leading_dim[operand.id]
        return None

    def _expr_events(self, expr, cleanup: str = "") -> List[Event]:
        """Collective + call events inside one expression, in source
        order (good enough for left-to-right evaluation)."""
        if expr is None:
            return []
        out: List[Event] = []
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(node.func)
            d = _dotted(node.func)
            is_coll = api.is_collective_call(d) or tail in _TWO_LEVEL_TAILS
            # a file-local def shadowing a collective name isn't the
            # framework op (visitor.py applies the same rule)
            if is_coll and isinstance(node.func, ast.Name) \
                    and tail in self._local_defs \
                    and tail not in _TWO_LEVEL_TAILS:
                is_coll = False
            if is_coll:
                out.extend(self._collective_events(node, cleanup))
            elif tail == "scan" and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in self._local_defs:
                # lax.scan over a file-local body — the pipeline
                # micro-batch loop: trip count symbolic (stage count /
                # tick count), modelled as a bounded-unrolled Loop over
                # the body's schedule
                out.append(Loop(kind="scan", site=self._site(node),
                                body=[Call(target=node.args[0].id,
                                           site=self._site(node))]))
            elif tail and tail not in _OPAQUE_TAILS \
                    and not api.is_trace_wrapper(tail):
                out.append(Call(target=tail, site=self._site(node)))
        out.sort(key=lambda ev: (ev.site.line, ev.site.col))
        return out

    # -- statement lowering --------------------------------------------------
    def _lower_block(self, stmts, cleanup: str = "") -> List[Event]:
        out: List[Event] = []
        for stmt in stmts:
            out.extend(self._lower_stmt(stmt, cleanup))
        return out

    def _lower_stmt(self, stmt, cleanup: str) -> List[Event]:  # noqa: C901
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._lower_function(stmt)
            return []
        if isinstance(stmt, ast.ClassDef):
            # methods become plain named functions (tail-name resolution)
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._lower_function(sub)
            return []
        if isinstance(stmt, ast.Return):
            return self._expr_events(stmt.value, cleanup) \
                + [Return(self._site(stmt))]
        if isinstance(stmt, ast.Raise):
            return self._expr_events(stmt.exc, cleanup) \
                + [Raise(self._site(stmt))]
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(stmt, "value", None)
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            if value is not None:
                self._taint_targets(targets, value)
                self._track_leading_dim(targets, value)
            return self._expr_events(value, cleanup)
        if isinstance(stmt, ast.Expr):
            return self._expr_events(stmt.value, cleanup)
        if isinstance(stmt, (ast.If, ast.While)):
            return self._lower_branch(stmt, cleanup)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            body = self._lower_block(stmt.body, cleanup) \
                + self._lower_block(stmt.orelse, cleanup)
            if not body:
                return []
            return [Loop(kind="for", site=self._site(stmt), body=body)]
        if isinstance(stmt, ast.Try):
            return self._lower_try(stmt, cleanup)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            out: List[Event] = []
            for item in stmt.items:
                out.extend(self._expr_events(item.context_expr, cleanup))
            return out + self._lower_block(stmt.body, cleanup)
        if isinstance(stmt, ast.Assert):
            return self._expr_events(stmt.test, cleanup)
        if isinstance(stmt, (ast.Pass, ast.Break, ast.Continue, ast.Import,
                             ast.ImportFrom, ast.Global, ast.Nonlocal,
                             ast.Delete)):
            return []
        return []

    def _lower_branch(self, stmt, cleanup: str) -> List[Event]:
        flavor = self._flavor(stmt.test)
        pre = self._expr_events(stmt.test, cleanup)
        body = self._lower_block(stmt.body, cleanup)
        orelse = self._lower_block(stmt.orelse, cleanup)
        kind = "if" if isinstance(stmt, ast.If) else "while"
        if kind == "while" and flavor == FLAVOR_UNIFORM:
            # every rank runs the same trip count — a bounded loop
            if not (body or orelse):
                return pre
            return pre + [Loop(kind="while", site=self._site(stmt),
                               body=body)] + orelse
        if not (body or orelse):
            return pre
        return pre + [Branch(
            kind=kind, flavor=flavor, condition=_expr_text(stmt.test),
            site=self._site(stmt), body=body, orelse=orelse,
        )]

    def _lower_try(self, stmt: ast.Try, cleanup: str) -> List[Event]:
        """Normal path: try body + else.  Exceptional path: the handler —
        modelled as an exception-flavored branch *after* the body, since
        exceptions strike per rank (a collective in a handler is only
        reached by the ranks that raised: HVD012's shape).  ``finally``
        runs on both sides, so it stays unflavored."""
        out = self._lower_block(stmt.body, cleanup)
        handler_events: List[Event] = []
        for handler in stmt.handlers:
            handler_events.extend(
                self._lower_block(handler.body, cleanup or "except"))
        if handler_events:
            first = stmt.handlers[0]
            cond = _expr_text(first.type) if first.type is not None \
                else "Exception"
            out.append(Branch(
                kind="try", flavor=FLAVOR_EXCEPTION,
                condition=f"except {cond}", site=self._site(first),
                body=handler_events, orelse=[],
            ))
        out.extend(self._lower_block(stmt.orelse, cleanup))
        out.extend(self._lower_block(stmt.finalbody, cleanup))
        return out

    def _lower_function(self, node) -> None:
        traced = (
            self._frame.traced
            or node.name in self._wrapped
            or any(self._decorator_traced(d) for d in node.decorator_list)
        )
        a = node.args
        params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        if a.vararg:
            params.add(a.vararg.arg)
        if a.kwarg:
            params.add(a.kwarg.arg)
        info = FunctionInfo(
            name=node.name, site=self._site(node), traced=traced,
            wrapped=node.name in self._wrapped,
            elastic=node.name in self._elastic,
        )
        self.functions.append(info)  # registered first: shadows collectives
        self._frames.append(_Frame(traced, params))
        try:
            info.body = self._lower_block(node.body)
        finally:
            self._frames.pop()

    @staticmethod
    def _decorator_traced(dec) -> bool:
        if api.is_trace_wrapper(_tail(dec)):
            return True
        if isinstance(dec, ast.Call):
            if api.is_trace_wrapper(_tail(dec.func)):
                return True
            if _tail(dec.func) == "partial" and dec.args \
                    and api.is_trace_wrapper(_tail(dec.args[0])):
                return True
        return False


def extract_file(source: str, path: str) -> List[FunctionInfo]:
    """Parse + lower one file.  Raises SyntaxError on unparsable input —
    the driver turns that into an HVD000 finding like the linter does."""
    tree = ast.parse(source, filename=path)
    return Extractor(path, tree).extract()
