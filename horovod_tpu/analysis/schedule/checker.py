"""Pairwise schedule compatibility + counterexample construction.

For every entry point, every pair of enumerated paths that could be two
ranks of the *same* run (identical uniform decisions, differing on at
least one rank/data/exception decision) is compared group by group:

* their per-group collective sequences must be identical — a conflict at
  position *k* is **HVD009** (schedule divergence: the ranks negotiate
  different ops/names/signatures and deadlock), a strict-prefix
  relationship is **HVD010** (a blocking collective only a subset of
  ranks reaches — the classic rank-guarded collective, interprocedural),
  and a subset collective sitting on an exception/cleanup path is
  **HVD012** (peers that did not raise skip the drain);
* when all per-group sequences agree but two groups interleave in
  opposite orders on the two paths, that is **HVD011** (cross-group
  ordering inversion: intra-host vs cross-host stages issued in a
  different relative order deadlock even though each group's own
  schedule matches — the static twin of the sanitizer's vector-clock
  check), or **HVD014** when both groups are mesh axes (two axes'
  collectives issued in opposite orders on members that share both —
  HVD011 generalized to the DP×TP×PP mesh);
* a point-to-point event (SendRecv — ``lax.ppermute``) on one side of a
  conflict or prefix is **HVD013** (unmatched or cyclic point-to-point
  schedule: a send whose matching recv is unreachable on the peer's
  path, or mismatched permutations forming a wait-for cycle across
  stage ranks — the classic pipeline deadlock);
* independent of path enumeration, a collective whose literal shape
  assumption contradicts a literal mesh declaration — a permutation
  naming a stage rank outside the axis, or an untiled all_to_all whose
  split dimension differs from the axis size (MoE capacity vs
  expert-axis size) — is **HVD015** (axis-shape contract violation).

Each finding carries a machine-checkable counterexample: the entry, the
group, the collective, both projected sequences, and the exact branch
chain (file:line, condition, arm) that separates the two rank sets
(HVD015 substitutes the mesh declaration for the branch chain: its two
"rank sets" are the declared members vs the assumed participants).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..findings import Finding, Suppressions, sort_findings
from .callgraph import CallGraph
from .ir import (
    Collective,
    Entry,
    SendRecv,
    axis_name,
    is_axis_group,
    walk_events,
)
from .paths import (
    DEFAULT_LOOP_BOUND,
    DEFAULT_MAX_PATHS,
    Decision,
    Dispatch,
    Enumerator,
    Path,
)

#: the model checker's rule catalogue (merged into rules.RULES for
#: --list-rules and severity lookup; docs/analysis.md is the user copy)
SCHEDULE_RULES: Dict[str, Tuple[str, str]] = {
    "HVD009": ("error",
               "schedule divergence: ranks project different collective "
               "sequences for one communication group"),
    "HVD010": ("error",
               "potential deadlock: blocking collective reachable on a "
               "strict subset of ranks"),
    "HVD011": ("error",
               "cross-group ordering inversion: collectives of two groups "
               "issued in a different relative order on different ranks"),
    "HVD012": ("error",
               "collective reachable from an abort/cleanup path that "
               "peers skip"),
    "HVD013": ("error",
               "unmatched or cyclic point-to-point schedule: a ppermute "
               "send whose matching recv is unreachable on the peer's "
               "path, or mismatched permutations forming a wait-for "
               "cycle across stage ranks (pipeline deadlock)"),
    "HVD014": ("error",
               "cross-axis ordering inversion: two mesh axes' collectives "
               "issued in opposite orders on members that share both "
               "axes"),
    "HVD015": ("error",
               "axis-shape contract violation: collective assumes an "
               "axis size/divisibility the mesh declaration cannot "
               "satisfy"),
}


def _fmt_seq(events: Sequence[Dispatch], limit: int = 8) -> List[str]:
    out = [f"{d.collective.describe()} @ {d.collective.site}"
           for d in events[:limit]]
    if len(events) > limit:
        out.append(f"… {len(events) - limit} more")
    return out


def _chain_dicts(decisions: Iterable[Decision]) -> List[dict]:
    out = []
    for d in decisions:
        f, _, line = d.site.rpartition(":")
        out.append({
            "file": f, "line": int(line) if line.isdigit() else 0,
            "kind": d.kind, "flavor": d.flavor,
            "condition": d.condition, "taken": d.taken,
        })
    return out


def _rank_set(decisions: Sequence[Decision]) -> str:
    """A symbolic name for the rank set a divergent decision chain
    selects — the checker proves schedules per *decision*, so the rank
    set is the ranks on which those conditions evaluate this way."""
    if not decisions:
        return "all ranks"
    bits = []
    for d in decisions[:4]:
        rel = {"then": "is true", "else": "is false",
               "raised": "raises", "no raise": "does not raise",
               "enter once": "is true", "skip": "is false"}.get(
                   d.taken, d.taken)
        bits.append(f"({d.condition}) at {d.site} {rel}")
    return "ranks where " + " and ".join(bits)


def _differing(a: Path, b: Path) -> Tuple[Tuple[Decision, ...],
                                          Tuple[Decision, ...]]:
    """The divergent decisions that separate the two paths (symmetric
    difference, order preserved)."""
    da, db = a.divergent_decisions(), b.divergent_decisions()
    only_a = tuple(d for d in da if d not in db)
    only_b = tuple(d for d in db if d not in da)
    return only_a, only_b


class _Dedup:
    def __init__(self):
        self._seen: Set[Tuple] = set()

    def fresh(self, *key) -> bool:
        if key in self._seen:
            return False
        self._seen.add(key)
        return True


def _wait_cycle(sr: SendRecv) -> str:
    """The wait-for cycle an unmatched permute produces, named by
    concrete stage ranks when the permutation is a literal pair list."""
    if sr.pairs:
        s, d = sr.pairs[0]
        return (f"stage rank {s}'s send waits on stage rank {d} entering "
                f"the permute, and stage rank {d} waits on a dispatch it "
                f"never reaches: wait-for cycle stage {s} -> stage {d} "
                f"-> stage {s} (pipeline deadlock)")
    return ("each sender waits on a peer recv that never pairs up: "
            "wait-for cycle across stage ranks (pipeline deadlock)")


def _finding(rule: str, message: str, dispatch: Dispatch,
             counterexample: dict) -> Finding:
    site = dispatch.collective.site
    return Finding(
        rule=rule, message=message, file=site.file, line=site.line,
        col=site.col, severity=SCHEDULE_RULES[rule][0],
        extra={"counterexample": counterexample},
    )


def _counterexample(entry: Entry, group: Optional[str], dispatch: Dispatch,
                    a: Path, b: Path, chain_a, chain_b) -> dict:
    return {
        "entry": entry.fn.qualname,
        "entry_kind": entry.kind,
        "world": entry.world,
        "group": group,
        "collective": {
            "op": dispatch.collective.op,
            "name": dispatch.collective.name,
            "file": dispatch.collective.site.file,
            "line": dispatch.collective.site.line,
        },
        "rank_set_a": _rank_set(chain_a),
        "rank_set_b": _rank_set(chain_b),
        "branch_chain_a": _chain_dicts(chain_a),
        "branch_chain_b": _chain_dicts(chain_b),
        "call_stack": list(dispatch.stack),
        "schedule_a": _fmt_seq([d for d in a.events
                                if group is None
                                or d.collective.group == group]),
        "schedule_b": _fmt_seq([d for d in b.events
                                if group is None
                                or d.collective.group == group]),
    }


def _check_pair(entry: Entry, a: Path, b: Path,
                dedup: _Dedup) -> List[Finding]:
    chain_a, chain_b = _differing(a, b)
    groups = sorted({d.collective.group for d in a.events}
                    | {d.collective.group for d in b.events})
    out: List[Finding] = []
    all_equal = True
    for group in groups:
        sa = [d for d in a.events if d.collective.group == group]
        sb = [d for d in b.events if d.collective.group == group]
        k = 0
        while k < len(sa) and k < len(sb) and sa[k].key() == sb[k].key():
            k += 1
        if k == len(sa) and k == len(sb):
            continue  # this group's schedules agree
        all_equal = False
        if k < len(sa) and k < len(sb):
            da, db = sa[k], sb[k]
            p2p = isinstance(da.collective, SendRecv) \
                or isinstance(db.collective, SendRecv)
            rule = "HVD013" if p2p else "HVD009"
            if not dedup.fresh(rule, group, da.collective.site,
                               db.collective.site):
                continue
            if p2p:
                msg = (
                    f"cyclic point-to-point schedule in group '{group}': "
                    f"{_rank_set(chain_a)} dispatch "
                    f"{da.collective.describe()} while "
                    f"{_rank_set(chain_b)} dispatch "
                    f"{db.collective.describe()} at "
                    f"{db.collective.site} — the permutations do not "
                    "pair up; each stage rank waits for a send its peer "
                    "never issues (wait-for cycle across stage ranks)"
                )
            else:
                msg = (
                    f"schedule divergence in group '{group}': "
                    f"{_rank_set(chain_a)} dispatch "
                    f"{da.collective.describe()} as collective #{k + 1} "
                    f"while {_rank_set(chain_b)} dispatch "
                    f"{db.collective.describe()} at "
                    f"{db.collective.site} — the group deadlocks at "
                    "negotiation"
                )
            out.append(_finding(
                rule, msg,
                da, _counterexample(entry, group, da, a, b,
                                    chain_a, chain_b),
            ))
            continue
        # strict prefix: the longer path dispatches collectives the
        # other rank set never reaches
        longer, shorter = (a, b) if len(sa) > len(sb) else (b, a)
        extra = (sa if len(sa) > len(sb) else sb)[k]
        chain_l = chain_a if longer is a else chain_b
        chain_s = chain_b if longer is a else chain_a
        if isinstance(extra.collective, SendRecv):
            rule = "HVD013"
        elif extra.collective.cleanup:
            rule = "HVD012"
        else:
            rule = "HVD010"
        if not dedup.fresh(rule, group, extra.collective.site):
            continue
        if rule == "HVD013":
            msg = (
                f"unmatched point-to-point send: "
                f"{extra.collective.describe()} in group '{group}' is "
                f"reachable only by {_rank_set(chain_l)}; "
                f"{_rank_set(chain_s) if chain_s else 'the peer stage ranks'}"
                " never dispatch the matching recv — "
                + _wait_cycle(extra.collective)
            )
        elif rule == "HVD012":
            msg = (
                f"collective {extra.collective.describe()} runs on an "
                f"abort/cleanup path ({_rank_set(chain_l)}) that "
                f"{_rank_set(chain_s) if chain_s else 'peers'} skip — "
                "ranks that did not raise never join it"
            )
        else:
            msg = (
                f"blocking collective {extra.collective.describe()} in "
                f"group '{group}' is reachable only by "
                f"{_rank_set(chain_l)}; "
                f"{_rank_set(chain_s) if chain_s else 'the other ranks'} "
                "never dispatch it and the group deadlocks"
            )
        out.append(_finding(
            rule, msg, extra,
            _counterexample(entry, group, extra, longer, shorter,
                            chain_l, chain_s),
        ))
    if all_equal and len(groups) > 1:
        out.extend(_check_inversion(entry, a, b, groups, dedup,
                                    chain_a, chain_b))
    return out


def _check_inversion(entry: Entry, a: Path, b: Path, groups, dedup: _Dedup,
                     chain_a, chain_b) -> List[Finding]:
    """All per-group sequences agree — do the groups interleave in the
    same order?  Position maps: the n-th dispatch of group g is the same
    logical collective on both paths (their per-group sequences are
    equal), so opposite relative order of (g,i) vs (h,j) is a deadlock:
    each rank set blocks in a different group's collective."""

    def order(p: Path) -> Dict[Tuple[str, int], int]:
        counts: Dict[str, int] = {}
        out = {}
        for pos, d in enumerate(p.events):
            g = d.collective.group
            out[(g, counts.get(g, 0))] = pos
            counts[g] = counts.get(g, 0) + 1
        return out

    oa, ob = order(a), order(b)
    common = sorted(set(oa) & set(ob), key=lambda k: oa[k])
    found: List[Finding] = []
    for i in range(len(common)):
        for j in range(i + 1, len(common)):
            x, y = common[i], common[j]
            if x[0] == y[0]:
                continue
            if (oa[x] < oa[y]) == (ob[x] < ob[y]):
                continue
            da = a.events[oa[y]]
            db_ev = b.events[ob[x]]
            # HVD011 generalizes to HVD014 when both groups are mesh
            # axes: members sharing both axes see the two axes' streams
            # in opposite orders — the mesh-shaped inversion
            both_axes = is_axis_group(x[0]) and is_axis_group(y[0])
            rule = "HVD014" if both_axes else "HVD011"
            if not dedup.fresh(rule, x[0], y[0],
                               da.collective.site):
                continue
            if both_axes:
                msg = (
                    f"cross-axis ordering inversion: {_rank_set(chain_a)} "
                    f"issue {da.collective.describe()} "
                    f"(axis '{axis_name(y[0])}') after axis "
                    f"'{axis_name(x[0])}', but {_rank_set(chain_b)} issue "
                    f"{db_ev.collective.describe()} "
                    f"(axis '{axis_name(x[0])}') after axis "
                    f"'{axis_name(y[0])}' — members sharing both axes "
                    "block in different axes' collectives"
                )
            else:
                msg = (
                    f"cross-group ordering inversion: {_rank_set(chain_a)} "
                    f"issue {da.collective.describe()} (group '{y[0]}') "
                    f"after group '{x[0]}', but {_rank_set(chain_b)} issue "
                    f"{db_ev.collective.describe()} (group '{x[0]}') after "
                    f"group '{y[0]}' — each rank set blocks in a different "
                    "group's collective"
                )
            found.append(_finding(
                rule, msg,
                da, _counterexample(entry, None, da, a, b,
                                    chain_a, chain_b),
            ))
    return found


def _check_contracts(functions, axis_sizes: Dict[str, Tuple],
                     dedup: _Dedup) -> List[Finding]:
    """HVD015 — literal shape assumptions vs literal mesh declarations.
    Needs no path enumeration: the contract is violated on every path
    that reaches the dispatch.  Two assumption sources: a literal
    permutation naming a stage rank the axis does not have, and an
    untiled split-axis-0 all_to_all whose (frame-tracked) literal split
    dimension differs from the axis size — the MoE dispatch contract
    (parallel/moe.py reshapes to (ep, …) before its all_to_all)."""
    out: List[Finding] = []
    for fn in functions:
        for ev in walk_events(fn.body):
            if not isinstance(ev, Collective) or not is_axis_group(ev.group):
                continue
            name = axis_name(ev.group)
            decl = axis_sizes.get(name)
            if decl is None:
                continue
            size, decl_site = decl
            if isinstance(ev, SendRecv) and ev.pairs:
                top = max(max(p) for p in ev.pairs)
                if top < size:
                    continue
                msg = (
                    f"axis-shape contract violation: {ev.describe()} "
                    f"names stage rank {top}, but axis '{name}' is "
                    f"declared with {size} member(s) at {decl_site} — "
                    "the mesh cannot satisfy the permutation"
                )
                assumed = f"stage ranks up to {top} named by the permutation"
            elif ev.assumes_size is not None and ev.assumes_size != size:
                msg = (
                    f"axis-shape contract violation: {ev.describe()} "
                    f"splits a leading dimension of {ev.assumes_size} "
                    f"over axis '{name}', declared with {size} member(s) "
                    f"at {decl_site} — an untiled split-axis-0 "
                    "all_to_all requires the split dimension to equal "
                    "the axis size (MoE capacity vs expert-axis size)"
                )
                assumed = (f"the {ev.assumes_size} participant(s) the "
                           "split dimension assumes")
            else:
                continue
            if not dedup.fresh("HVD015", ev.site):
                continue
            dispatch = Dispatch(collective=ev, stack=())
            out.append(_finding("HVD015", msg, dispatch, {
                "entry": fn.qualname,
                "entry_kind": "contract",
                "world": "static",
                "group": ev.group,
                "collective": {"op": ev.op, "name": ev.name,
                               "file": ev.site.file, "line": ev.site.line},
                "rank_set_a": (f"all {size} member(s) of axis '{name}' "
                               f"(declared at {decl_site})"),
                "rank_set_b": assumed,
                "branch_chain_a": [],
                "branch_chain_b": [],
                "call_stack": [],
                "schedule_a": [f"{ev.describe()} @ {ev.site}"],
                "schedule_b": [f"axis '{name}' = {size} member(s) "
                               f"@ {decl_site}"],
            }))
    return out


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------
def _env_int(name_attr: str, default: int) -> int:
    try:
        from ...utils import env as env_util

        return env_util.get_int(getattr(env_util, name_attr), default)
    except Exception:  # noqa: BLE001 — standalone use outside the package
        return default


class CheckResult:
    def __init__(self):
        self.findings: List[Finding] = []
        self.entries: int = 0
        self.paths_explored: int = 0
        self.truncated: bool = False
        #: the loop bound the enumeration ran under, and every loop it
        #: unrolled to that bound — per entry, with file:line — so a
        #: truncated (pipeline micro-batch) deadlock search is visible
        self.loop_bound: int = DEFAULT_LOOP_BOUND
        self.loop_bounds: List[dict] = []


def check_sources(sources: Sequence[Tuple[str, str]], *,
                  entries: Optional[List[str]] = None,
                  max_paths: Optional[int] = None,
                  loop_bound: Optional[int] = None,
                  disable: Iterable[str] = ()) -> CheckResult:
    """Model-check (path, source) pairs as one program.  Mirrors
    rules.lint_sources: suppression comments and HVD_LINT_DISABLE apply
    to HVD009–HVD012 exactly as to the linter's rules."""
    from ..rules import _disabled_from_env

    if max_paths is None:
        max_paths = _env_int("HVD_VERIFY_MAX_PATHS", DEFAULT_MAX_PATHS)
    if loop_bound is None:
        loop_bound = _env_int("HVD_VERIFY_LOOP_BOUND", DEFAULT_LOOP_BOUND)
    disabled = set(disable) | _disabled_from_env()

    result = CheckResult()
    result.loop_bound = loop_bound
    functions = []
    axis_sizes: Dict[str, Tuple] = {}
    supp: Dict[str, Suppressions] = {}
    for path, source in sources:
        s = Suppressions.parse(source)
        try:
            import ast

            from .extract import Extractor

            tree = ast.parse(source, filename=path)  # ONE parse per file
            extractor = Extractor(path, tree)
            infos = extractor.extract()
        except SyntaxError as e:
            result.findings.append(Finding(
                rule="HVD000", message=f"syntax error: {e.msg}", file=path,
                line=e.lineno or 1, col=e.offset or 0, severity="error",
            ))
            continue
        try:
            s.attach_spans(statement_spans(tree))
        except Exception:  # noqa: BLE001 — spans are best-effort
            pass
        supp[path] = s
        functions.extend(infos)
        for name, decl in extractor.axis_sizes.items():
            axis_sizes.setdefault(name, decl)

    graph = CallGraph(functions)
    enum = Enumerator(graph, max_paths=max_paths, loop_bound=loop_bound)
    dedup = _Dedup()
    findings = list(result.findings)
    findings.extend(_check_contracts(functions, axis_sizes, dedup))
    for entry in graph.entries(explicit=entries):
        res = enum.enumerate(entry)
        result.entries += 1
        result.paths_explored += len(res.paths)
        result.truncated = result.truncated or res.truncated
        for loop_site, loop_kind in res.loops:
            lf, _, lline = loop_site.rpartition(":")
            result.loop_bounds.append({
                "entry": entry.fn.qualname, "file": lf,
                "line": int(lline) if lline.isdigit() else 0,
                "loop": loop_kind, "bound": loop_bound,
            })
        by_uniform: Dict[Tuple, List[Path]] = {}
        for p in res.paths:
            by_uniform.setdefault(p.uniform_key(), []).append(p)
        for group in by_uniform.values():
            for i in range(len(group)):
                for j in range(i + 1, len(group)):
                    a, b = group[i], group[j]
                    if a.divergent_decisions() == b.divergent_decisions():
                        continue  # same rank behavior — not two rank sets
                    findings.extend(_check_pair(entry, a, b, dedup))
    result.findings = sort_findings([
        f for f in findings
        if f.rule not in disabled
        and not (f.file in supp and supp[f.file].hides(f))
    ])
    return result


def check_paths(paths: Sequence[str], *,
                entries: Optional[List[str]] = None,
                max_paths: Optional[int] = None,
                loop_bound: Optional[int] = None,
                disable: Iterable[str] = ()) -> CheckResult:
    """Model-check files/dirs.  Raises OSError on a nonexistent path
    (CLI exit 2), like rules.lint_paths."""
    from ..rules import read_sources

    sources, unreadable = read_sources(paths)
    result = check_sources(sources, entries=entries, max_paths=max_paths,
                           loop_bound=loop_bound, disable=disable)
    result.findings = sort_findings(unreadable + result.findings)
    return result


def statement_spans(tree) -> List[Tuple[int, int]]:
    """(start, end) line spans for suppression mapping — re-exported from
    the visitor so both drivers share one definition."""
    from ..visitor import statement_spans as _spans

    return _spans(tree)


def render_result_text(result: CheckResult) -> str:
    lines = []
    for f in result.findings:
        lines.append(f.format())
        ce = f.extra.get("counterexample") if f.extra else None
        if not ce:
            continue
        lines.append(f"    entry: {ce['entry']} [{ce['entry_kind']}, "
                     f"{ce['world']} world]")
        if ce.get("group"):
            lines.append(f"    group: {ce['group']}")
        if ce.get("call_stack"):
            for frame in ce["call_stack"]:
                lines.append(f"    via {frame}")
        for label, chain_key, sched_key in (
                ("A", "branch_chain_a", "schedule_a"),
                ("B", "branch_chain_b", "schedule_b")):
            chain = ce.get(chain_key) or []
            lines.append(f"    rank set {label}: "
                         + (ce.get(f"rank_set_{label.lower()}")
                            or "all ranks"))
            for d in chain:
                lines.append(
                    f"      -> {d['file']}:{d['line']} {d['kind']} "
                    f"({d['condition']}) takes '{d['taken']}' "
                    f"[{d['flavor']}]")
            for s in ce.get(sched_key) or ["(no collectives)"]:
                lines.append(f"      dispatches {s}")
    n_err = sum(1 for f in result.findings if f.severity == "error")
    n_warn = len(result.findings) - n_err
    tail = (f"hvd_verify: {len(result.findings)} finding(s) "
            f"({n_err} error(s), {n_warn} warning(s))"
            if result.findings else "hvd_verify: OK — no findings")
    tail += (f"  [{result.entries} entr(ies), "
             f"{result.paths_explored} path(s)"
             + (", BOUNDED — raise HVD_VERIFY_MAX_PATHS for more"
                if result.truncated else "")
             + (f", {len(result.loop_bounds)} loop(s) unrolled to bound "
                f"{result.loop_bound} — see loop_bounds in --json"
                if result.loop_bounds else "") + "]")
    lines.append(tail)
    return "\n".join(lines)


def render_result_json(result: CheckResult) -> str:
    import json

    return json.dumps({
        "findings": [f.as_dict() for f in result.findings],
        "count": len(result.findings),
        "entries": result.entries,
        "paths_explored": result.paths_explored,
        "truncated": result.truncated,
        "loop_bound": result.loop_bound,
        "loop_bounds": result.loop_bounds,
    }, indent=1)
