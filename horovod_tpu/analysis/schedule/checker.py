"""Pairwise schedule compatibility + counterexample construction.

For every entry point, every pair of enumerated paths that could be two
ranks of the *same* run (identical uniform decisions, differing on at
least one rank/data/exception decision) is compared group by group:

* their per-group collective sequences must be identical — a conflict at
  position *k* is **HVD009** (schedule divergence: the ranks negotiate
  different ops/names/signatures and deadlock), a strict-prefix
  relationship is **HVD010** (a blocking collective only a subset of
  ranks reaches — the classic rank-guarded collective, interprocedural),
  and a subset collective sitting on an exception/cleanup path is
  **HVD012** (peers that did not raise skip the drain);
* when all per-group sequences agree but two groups interleave in
  opposite orders on the two paths, that is **HVD011** (cross-group
  ordering inversion: intra-host vs cross-host stages issued in a
  different relative order deadlock even though each group's own
  schedule matches — the static twin of the sanitizer's vector-clock
  check).

Each finding carries a machine-checkable counterexample: the entry, the
group, the collective, both projected sequences, and the exact branch
chain (file:line, condition, arm) that separates the two rank sets.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..findings import Finding, Suppressions, sort_findings
from .callgraph import CallGraph
from .ir import Entry
from .paths import (
    DEFAULT_LOOP_BOUND,
    DEFAULT_MAX_PATHS,
    Decision,
    Dispatch,
    Enumerator,
    Path,
)

#: the model checker's rule catalogue (merged into rules.RULES for
#: --list-rules and severity lookup; docs/analysis.md is the user copy)
SCHEDULE_RULES: Dict[str, Tuple[str, str]] = {
    "HVD009": ("error",
               "schedule divergence: ranks project different collective "
               "sequences for one communication group"),
    "HVD010": ("error",
               "potential deadlock: blocking collective reachable on a "
               "strict subset of ranks"),
    "HVD011": ("error",
               "cross-group ordering inversion: collectives of two groups "
               "issued in a different relative order on different ranks"),
    "HVD012": ("error",
               "collective reachable from an abort/cleanup path that "
               "peers skip"),
}


def _fmt_seq(events: Sequence[Dispatch], limit: int = 8) -> List[str]:
    out = [f"{d.collective.describe()} @ {d.collective.site}"
           for d in events[:limit]]
    if len(events) > limit:
        out.append(f"… {len(events) - limit} more")
    return out


def _chain_dicts(decisions: Iterable[Decision]) -> List[dict]:
    out = []
    for d in decisions:
        f, _, line = d.site.rpartition(":")
        out.append({
            "file": f, "line": int(line) if line.isdigit() else 0,
            "kind": d.kind, "flavor": d.flavor,
            "condition": d.condition, "taken": d.taken,
        })
    return out


def _rank_set(decisions: Sequence[Decision]) -> str:
    """A symbolic name for the rank set a divergent decision chain
    selects — the checker proves schedules per *decision*, so the rank
    set is the ranks on which those conditions evaluate this way."""
    if not decisions:
        return "all ranks"
    bits = []
    for d in decisions[:4]:
        rel = {"then": "is true", "else": "is false",
               "raised": "raises", "no raise": "does not raise",
               "enter once": "is true", "skip": "is false"}.get(
                   d.taken, d.taken)
        bits.append(f"({d.condition}) at {d.site} {rel}")
    return "ranks where " + " and ".join(bits)


def _differing(a: Path, b: Path) -> Tuple[Tuple[Decision, ...],
                                          Tuple[Decision, ...]]:
    """The divergent decisions that separate the two paths (symmetric
    difference, order preserved)."""
    da, db = a.divergent_decisions(), b.divergent_decisions()
    only_a = tuple(d for d in da if d not in db)
    only_b = tuple(d for d in db if d not in da)
    return only_a, only_b


class _Dedup:
    def __init__(self):
        self._seen: Set[Tuple] = set()

    def fresh(self, *key) -> bool:
        if key in self._seen:
            return False
        self._seen.add(key)
        return True


def _finding(rule: str, message: str, dispatch: Dispatch,
             counterexample: dict) -> Finding:
    site = dispatch.collective.site
    return Finding(
        rule=rule, message=message, file=site.file, line=site.line,
        col=site.col, severity=SCHEDULE_RULES[rule][0],
        extra={"counterexample": counterexample},
    )


def _counterexample(entry: Entry, group: Optional[str], dispatch: Dispatch,
                    a: Path, b: Path, chain_a, chain_b) -> dict:
    return {
        "entry": entry.fn.qualname,
        "entry_kind": entry.kind,
        "world": entry.world,
        "group": group,
        "collective": {
            "op": dispatch.collective.op,
            "name": dispatch.collective.name,
            "file": dispatch.collective.site.file,
            "line": dispatch.collective.site.line,
        },
        "rank_set_a": _rank_set(chain_a),
        "rank_set_b": _rank_set(chain_b),
        "branch_chain_a": _chain_dicts(chain_a),
        "branch_chain_b": _chain_dicts(chain_b),
        "call_stack": list(dispatch.stack),
        "schedule_a": _fmt_seq([d for d in a.events
                                if group is None
                                or d.collective.group == group]),
        "schedule_b": _fmt_seq([d for d in b.events
                                if group is None
                                or d.collective.group == group]),
    }


def _check_pair(entry: Entry, a: Path, b: Path,
                dedup: _Dedup) -> List[Finding]:
    chain_a, chain_b = _differing(a, b)
    groups = sorted({d.collective.group for d in a.events}
                    | {d.collective.group for d in b.events})
    out: List[Finding] = []
    all_equal = True
    for group in groups:
        sa = [d for d in a.events if d.collective.group == group]
        sb = [d for d in b.events if d.collective.group == group]
        k = 0
        while k < len(sa) and k < len(sb) and sa[k].key() == sb[k].key():
            k += 1
        if k == len(sa) and k == len(sb):
            continue  # this group's schedules agree
        all_equal = False
        if k < len(sa) and k < len(sb):
            da, db = sa[k], sb[k]
            if not dedup.fresh("HVD009", group, da.collective.site,
                               db.collective.site):
                continue
            out.append(_finding(
                "HVD009",
                f"schedule divergence in group '{group}': "
                f"{_rank_set(chain_a)} dispatch "
                f"{da.collective.describe()} as collective #{k + 1} while "
                f"{_rank_set(chain_b)} dispatch "
                f"{db.collective.describe()} at "
                f"{db.collective.site} — the group deadlocks at "
                "negotiation",
                da, _counterexample(entry, group, da, a, b,
                                    chain_a, chain_b),
            ))
            continue
        # strict prefix: the longer path dispatches collectives the
        # other rank set never reaches
        longer, shorter = (a, b) if len(sa) > len(sb) else (b, a)
        extra = (sa if len(sa) > len(sb) else sb)[k]
        chain_l = chain_a if longer is a else chain_b
        chain_s = chain_b if longer is a else chain_a
        rule = "HVD012" if extra.collective.cleanup else "HVD010"
        if not dedup.fresh(rule, group, extra.collective.site):
            continue
        if rule == "HVD012":
            msg = (
                f"collective {extra.collective.describe()} runs on an "
                f"abort/cleanup path ({_rank_set(chain_l)}) that "
                f"{_rank_set(chain_s) if chain_s else 'peers'} skip — "
                "ranks that did not raise never join it"
            )
        else:
            msg = (
                f"blocking collective {extra.collective.describe()} in "
                f"group '{group}' is reachable only by "
                f"{_rank_set(chain_l)}; "
                f"{_rank_set(chain_s) if chain_s else 'the other ranks'} "
                "never dispatch it and the group deadlocks"
            )
        out.append(_finding(
            rule, msg, extra,
            _counterexample(entry, group, extra, longer, shorter,
                            chain_l, chain_s),
        ))
    if all_equal and len(groups) > 1:
        out.extend(_check_inversion(entry, a, b, groups, dedup,
                                    chain_a, chain_b))
    return out


def _check_inversion(entry: Entry, a: Path, b: Path, groups, dedup: _Dedup,
                     chain_a, chain_b) -> List[Finding]:
    """All per-group sequences agree — do the groups interleave in the
    same order?  Position maps: the n-th dispatch of group g is the same
    logical collective on both paths (their per-group sequences are
    equal), so opposite relative order of (g,i) vs (h,j) is a deadlock:
    each rank set blocks in a different group's collective."""

    def order(p: Path) -> Dict[Tuple[str, int], int]:
        counts: Dict[str, int] = {}
        out = {}
        for pos, d in enumerate(p.events):
            g = d.collective.group
            out[(g, counts.get(g, 0))] = pos
            counts[g] = counts.get(g, 0) + 1
        return out

    oa, ob = order(a), order(b)
    common = sorted(set(oa) & set(ob), key=lambda k: oa[k])
    found: List[Finding] = []
    for i in range(len(common)):
        for j in range(i + 1, len(common)):
            x, y = common[i], common[j]
            if x[0] == y[0]:
                continue
            if (oa[x] < oa[y]) == (ob[x] < ob[y]):
                continue
            da = a.events[oa[y]]
            db_ev = b.events[ob[x]]
            if not dedup.fresh("HVD011", x[0], y[0],
                               da.collective.site):
                continue
            found.append(_finding(
                "HVD011",
                f"cross-group ordering inversion: {_rank_set(chain_a)} "
                f"issue {da.collective.describe()} (group '{y[0]}') after "
                f"group '{x[0]}', but {_rank_set(chain_b)} issue "
                f"{db_ev.collective.describe()} (group '{x[0]}') after "
                f"group '{y[0]}' — each rank set blocks in a different "
                "group's collective",
                da, _counterexample(entry, None, da, a, b,
                                    chain_a, chain_b),
            ))
    return found


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------
def _env_int(name_attr: str, default: int) -> int:
    try:
        from ...utils import env as env_util

        return env_util.get_int(getattr(env_util, name_attr), default)
    except Exception:  # noqa: BLE001 — standalone use outside the package
        return default


class CheckResult:
    def __init__(self):
        self.findings: List[Finding] = []
        self.entries: int = 0
        self.paths_explored: int = 0
        self.truncated: bool = False


def check_sources(sources: Sequence[Tuple[str, str]], *,
                  entries: Optional[List[str]] = None,
                  max_paths: Optional[int] = None,
                  loop_bound: Optional[int] = None,
                  disable: Iterable[str] = ()) -> CheckResult:
    """Model-check (path, source) pairs as one program.  Mirrors
    rules.lint_sources: suppression comments and HVD_LINT_DISABLE apply
    to HVD009–HVD012 exactly as to the linter's rules."""
    from ..rules import _disabled_from_env

    if max_paths is None:
        max_paths = _env_int("HVD_VERIFY_MAX_PATHS", DEFAULT_MAX_PATHS)
    if loop_bound is None:
        loop_bound = _env_int("HVD_VERIFY_LOOP_BOUND", DEFAULT_LOOP_BOUND)
    disabled = set(disable) | _disabled_from_env()

    result = CheckResult()
    functions = []
    supp: Dict[str, Suppressions] = {}
    for path, source in sources:
        s = Suppressions.parse(source)
        try:
            import ast

            from .extract import Extractor

            tree = ast.parse(source, filename=path)  # ONE parse per file
            infos = Extractor(path, tree).extract()
        except SyntaxError as e:
            result.findings.append(Finding(
                rule="HVD000", message=f"syntax error: {e.msg}", file=path,
                line=e.lineno or 1, col=e.offset or 0, severity="error",
            ))
            continue
        try:
            s.attach_spans(statement_spans(tree))
        except Exception:  # noqa: BLE001 — spans are best-effort
            pass
        supp[path] = s
        functions.extend(infos)

    graph = CallGraph(functions)
    enum = Enumerator(graph, max_paths=max_paths, loop_bound=loop_bound)
    dedup = _Dedup()
    findings = list(result.findings)
    for entry in graph.entries(explicit=entries):
        res = enum.enumerate(entry)
        result.entries += 1
        result.paths_explored += len(res.paths)
        result.truncated = result.truncated or res.truncated
        by_uniform: Dict[Tuple, List[Path]] = {}
        for p in res.paths:
            by_uniform.setdefault(p.uniform_key(), []).append(p)
        for group in by_uniform.values():
            for i in range(len(group)):
                for j in range(i + 1, len(group)):
                    a, b = group[i], group[j]
                    if a.divergent_decisions() == b.divergent_decisions():
                        continue  # same rank behavior — not two rank sets
                    findings.extend(_check_pair(entry, a, b, dedup))
    result.findings = sort_findings([
        f for f in findings
        if f.rule not in disabled
        and not (f.file in supp and supp[f.file].hides(f))
    ])
    return result


def check_paths(paths: Sequence[str], *,
                entries: Optional[List[str]] = None,
                max_paths: Optional[int] = None,
                loop_bound: Optional[int] = None,
                disable: Iterable[str] = ()) -> CheckResult:
    """Model-check files/dirs.  Raises OSError on a nonexistent path
    (CLI exit 2), like rules.lint_paths."""
    from ..rules import read_sources

    sources, unreadable = read_sources(paths)
    result = check_sources(sources, entries=entries, max_paths=max_paths,
                           loop_bound=loop_bound, disable=disable)
    result.findings = sort_findings(unreadable + result.findings)
    return result


def statement_spans(tree) -> List[Tuple[int, int]]:
    """(start, end) line spans for suppression mapping — re-exported from
    the visitor so both drivers share one definition."""
    from ..visitor import statement_spans as _spans

    return _spans(tree)


def render_result_text(result: CheckResult) -> str:
    lines = []
    for f in result.findings:
        lines.append(f.format())
        ce = f.extra.get("counterexample") if f.extra else None
        if not ce:
            continue
        lines.append(f"    entry: {ce['entry']} [{ce['entry_kind']}, "
                     f"{ce['world']} world]")
        if ce.get("group"):
            lines.append(f"    group: {ce['group']}")
        if ce.get("call_stack"):
            for frame in ce["call_stack"]:
                lines.append(f"    via {frame}")
        for label, chain_key, sched_key in (
                ("A", "branch_chain_a", "schedule_a"),
                ("B", "branch_chain_b", "schedule_b")):
            chain = ce.get(chain_key) or []
            lines.append(f"    rank set {label}: "
                         + (ce.get(f"rank_set_{label.lower()}")
                            or "all ranks"))
            for d in chain:
                lines.append(
                    f"      -> {d['file']}:{d['line']} {d['kind']} "
                    f"({d['condition']}) takes '{d['taken']}' "
                    f"[{d['flavor']}]")
            for s in ce.get(sched_key) or ["(no collectives)"]:
                lines.append(f"      dispatches {s}")
    n_err = sum(1 for f in result.findings if f.severity == "error")
    n_warn = len(result.findings) - n_err
    tail = (f"hvd_verify: {len(result.findings)} finding(s) "
            f"({n_err} error(s), {n_warn} warning(s))"
            if result.findings else "hvd_verify: OK — no findings")
    tail += (f"  [{result.entries} entr(ies), "
             f"{result.paths_explored} path(s)"
             + (", BOUNDED — raise HVD_VERIFY_MAX_PATHS for more"
                if result.truncated else "") + "]")
    lines.append(tail)
    return "\n".join(lines)


def render_result_json(result: CheckResult) -> str:
    import json

    return json.dumps({
        "findings": [f.as_dict() for f in result.findings],
        "count": len(result.findings),
        "entries": result.entries,
        "paths_explored": result.paths_explored,
        "truncated": result.truncated,
    }, indent=1)
