"""Schedule IR — what the model checker reasons about.

One :class:`FunctionInfo` per ``def`` (plus a pseudo-function for each
module's top-level body) holding an ordered event list: collectives with
their communication *group*, calls to other functions, branches tagged
with the taint flavor that decides whether ranks can take different arms,
and loops.  The extractor (extract.py) lowers Python ASTs into this IR;
the enumerator (paths.py) walks it to project per-rank collective
sequences; the checker (checker.py) compares those sequences pairwise
per group.

Communication groups are symbolic labels, not rank lists: a whole-world
collective is ``world``, an intra-host stage is ``local``, a cross-host
stage is ``cross``, a restricted communicator is ``process_set:<expr>``,
and a raw ``axis_index_groups=`` argument classifies by its source text.
Two collectives commute in the schedule iff their groups differ — that
is exactly the property the runtime sanitizer's vector clock enforces
(analysis/sanitizer.py), and what HVD011 checks statically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

#: group labels for the built-in hierarchies
GROUP_WORLD = "world"
GROUP_LOCAL = "local"
GROUP_CROSS = "cross"

#: branch flavors, by who can take different arms
FLAVOR_UNIFORM = "uniform"      # all ranks take the same arm (unknown which)
FLAVOR_RANK = "rank"            # condition is rank-tainted: arms differ by rank
FLAVOR_DATA = "data"            # per-rank data decides (inside traced code)
FLAVOR_EXCEPTION = "exception"  # exceptions strike per rank

#: flavors on which two ranks of ONE run may legitimately disagree
DIVERGENT_FLAVORS = frozenset({FLAVOR_RANK, FLAVOR_DATA, FLAVOR_EXCEPTION})


@dataclass(frozen=True)
class Site:
    file: str
    line: int
    col: int = 0

    def __str__(self) -> str:
        return f"{self.file}:{self.line}"


@dataclass
class Collective:
    """One collective dispatch: the unit the schedules are made of."""

    op: str                                  # tail name: "allreduce", "psum"…
    name: Optional[str]                      # constant name= kw, if any
    group: str                               # communication group label
    signature: Dict[str, str]                # normalized signature kwargs
    site: Site
    cleanup: str = ""                        # "" | "except" — abort-path flag

    def key(self) -> Tuple:
        """Schedule-equality key: two dispatches match iff these agree."""
        return (self.op, self.name, self.group,
                tuple(sorted(self.signature.items())))

    def describe(self) -> str:
        bits = [f"name={self.name!r}"] if self.name else []
        bits += [f"{k}={v}" for k, v in sorted(self.signature.items())]
        inner = ", ".join(bits)
        return f"{self.op}({inner})" if inner else f"{self.op}()"


@dataclass
class Call:
    """A call to a (possibly) user-defined function, inlined by the
    enumerator when the callgraph can resolve it."""

    target: str                              # tail name of the callee
    site: Site


@dataclass
class Branch:
    kind: str                                # "if" | "while" | "try"
    flavor: str                              # FLAVOR_*
    condition: str                           # source text of the test
    site: Site
    body: List["Event"] = field(default_factory=list)
    orelse: List["Event"] = field(default_factory=list)


@dataclass
class Loop:
    """A uniform loop (``for``, or ``while`` on an untainted condition):
    every rank runs the same (unknown) trip count, bounded-unrolled."""

    kind: str                                # "for" | "while"
    site: Site
    body: List["Event"] = field(default_factory=list)


@dataclass
class Return:
    site: Site


@dataclass
class Raise:
    site: Site


Event = Union[Collective, Call, Branch, Loop, Return, Raise]


@dataclass
class FunctionInfo:
    name: str                                # bare name ("<module>" for files)
    site: Site
    traced: bool                             # under spmd/jit
    body: List[Event] = field(default_factory=list)
    wrapped: bool = False                    # passed to spmd/jit/elastic.run
    elastic: bool = False                    # body of hvd.elastic.run(fn, …)

    @property
    def qualname(self) -> str:
        return f"{self.site.file}::{self.name}"


@dataclass
class Entry:
    """A model-checking entry point and why it was chosen."""

    fn: FunctionInfo
    kind: str            # "module" | "root" | "wrapped" | "elastic"

    @property
    def world(self) -> str:
        """Elastic bodies re-execute per membership epoch: their schedule
        is checked per-epoch world, which the reports call out."""
        return "elastic" if (self.kind == "elastic" or self.fn.elastic) \
            else "static"


def walk_events(events: List[Event]):
    """Yield every event in a body, recursing into branches and loops."""
    for ev in events:
        yield ev
        if isinstance(ev, Branch):
            yield from walk_events(ev.body)
            yield from walk_events(ev.orelse)
        elif isinstance(ev, Loop):
            yield from walk_events(ev.body)


def has_collective(events: List[Event]) -> bool:
    return any(isinstance(ev, Collective) for ev in walk_events(events))


def called_names(events: List[Event]) -> set:
    return {ev.target for ev in walk_events(events) if isinstance(ev, Call)}
