"""Schedule IR — what the model checker reasons about.

One :class:`FunctionInfo` per ``def`` (plus a pseudo-function for each
module's top-level body) holding an ordered event list: collectives with
their communication *group*, calls to other functions, branches tagged
with the taint flavor that decides whether ranks can take different arms,
and loops.  The extractor (extract.py) lowers Python ASTs into this IR;
the enumerator (paths.py) walks it to project per-rank collective
sequences; the checker (checker.py) compares those sequences pairwise
per group.

Communication groups are symbolic labels, not rank lists: a whole-world
collective is ``world``, an intra-host stage is ``local``, a cross-host
stage is ``cross``, a restricted communicator is ``process_set:<expr>``,
a named mesh axis is ``axis:<name>`` (the label a ``lax.psum(x, "pp")``
or any positional/``axis_name=`` mesh-axis argument lowers to — the same
vocabulary the future DP×TP×PP mesh dispatches under), and a raw
``axis_index_groups=`` argument classifies by its source text.  Two
collectives commute in the schedule iff their groups differ — that is
exactly the property the runtime sanitizer's vector clock enforces
(analysis/sanitizer.py), and what HVD011/HVD014 check statically.

Point-to-point schedules (``lax.ppermute`` / ``pshuffle``) lower to
:class:`SendRecv` — a :class:`Collective` subclass carrying the
permutation expression (symbolic in the stage count when the source
builds it that way) and, when the permutation is a literal pair list,
the concrete (source, destination) stage ranks.  The checker's HVD013
(pipeline deadlock) and HVD015 (axis-shape contract) reason over those.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

#: group labels for the built-in hierarchies
GROUP_WORLD = "world"
GROUP_LOCAL = "local"
GROUP_CROSS = "cross"

#: prefix of mesh-axis group labels: ``axis:<name>`` for a collective
#: over one named mesh axis (``lax.psum(x, "pp")`` → ``axis:pp``; a
#: symbolic axis argument keeps its source text, so two sites agree on
#: the group iff they spell the same axis expression)
GROUP_AXIS_PREFIX = "axis:"


def axis_group(name: str) -> str:
    """The ``axis:<name>`` group label for a named mesh axis."""
    return f"{GROUP_AXIS_PREFIX}{name}"


def is_axis_group(group: str) -> bool:
    return group.startswith(GROUP_AXIS_PREFIX)


def axis_name(group: str) -> str:
    """The axis name of an ``axis:<name>`` label (``""`` otherwise)."""
    return group[len(GROUP_AXIS_PREFIX):] if is_axis_group(group) else ""

#: branch flavors, by who can take different arms
FLAVOR_UNIFORM = "uniform"      # all ranks take the same arm (unknown which)
FLAVOR_RANK = "rank"            # condition is rank-tainted: arms differ by rank
FLAVOR_DATA = "data"            # per-rank data decides (inside traced code)
FLAVOR_EXCEPTION = "exception"  # exceptions strike per rank

#: flavors on which two ranks of ONE run may legitimately disagree
DIVERGENT_FLAVORS = frozenset({FLAVOR_RANK, FLAVOR_DATA, FLAVOR_EXCEPTION})


@dataclass(frozen=True)
class Site:
    file: str
    line: int
    col: int = 0

    def __str__(self) -> str:
        return f"{self.file}:{self.line}"


@dataclass
class Collective:
    """One collective dispatch: the unit the schedules are made of."""

    op: str                                  # tail name: "allreduce", "psum"…
    name: Optional[str]                      # constant name= kw, if any
    group: str                               # communication group label
    signature: Dict[str, str]                # normalized signature kwargs
    site: Site
    cleanup: str = ""                        # "" | "except" — abort-path flag
    #: literal axis-size assumption this dispatch encodes, if any (the
    #: leading split dimension of an all_to_all over an axis group);
    #: checked against mesh declarations by HVD015
    assumes_size: Optional[int] = None

    def key(self) -> Tuple:
        """Schedule-equality key: two dispatches match iff these agree."""
        return (self.op, self.name, self.group,
                tuple(sorted(self.signature.items())))

    def describe(self) -> str:
        bits = [f"name={self.name!r}"] if self.name else []
        bits += [f"{k}={v}" for k, v in sorted(self.signature.items())]
        inner = ", ".join(bits)
        return f"{self.op}({inner})" if inner else f"{self.op}()"


@dataclass
class SendRecv(Collective):
    """A point-to-point schedule event: one ``lax.ppermute``/``pshuffle``
    dispatch.  Still a collective at the XLA level — every member of the
    axis must enter the permute — but the checker additionally knows who
    sends to whom: ``perm`` keeps the permutation's source text (symbolic
    when built from stage arithmetic like ``[(i, (i + 1) % s) …]``) and
    ``pairs`` the concrete (source, destination) stage ranks when the
    permutation is a literal pair list."""

    perm: str = ""                           # permutation expression text
    pairs: Optional[Tuple[Tuple[int, int], ...]] = None

    def key(self) -> Tuple:
        # two permutes only pair up when their permutations agree — a
        # perm mismatch IS a schedule conflict (HVD013), so perm is part
        # of schedule equality
        return (self.op, self.name, self.group, self.perm,
                tuple(sorted(self.signature.items())))

    def describe(self) -> str:
        bits = [f"name={self.name!r}"] if self.name else []
        if self.perm:
            bits.append(f"perm={self.perm}")
        bits += [f"{k}={v}" for k, v in sorted(self.signature.items())]
        return f"{self.op}({', '.join(bits)})"


@dataclass
class Call:
    """A call to a (possibly) user-defined function, inlined by the
    enumerator when the callgraph can resolve it."""

    target: str                              # tail name of the callee
    site: Site


@dataclass
class Branch:
    kind: str                                # "if" | "while" | "try"
    flavor: str                              # FLAVOR_*
    condition: str                           # source text of the test
    site: Site
    body: List["Event"] = field(default_factory=list)
    orelse: List["Event"] = field(default_factory=list)


@dataclass
class Loop:
    """A uniform loop (``for``, a ``while`` on an untainted condition, or
    a ``lax.scan`` over a local body function — the pipeline micro-batch
    loop): every rank runs the same (unknown, symbolic-in-stage-count)
    trip count, bounded-unrolled to HVD_VERIFY_LOOP_BOUND."""

    kind: str                                # "for" | "while" | "scan"
    site: Site
    body: List["Event"] = field(default_factory=list)


@dataclass
class Return:
    site: Site


@dataclass
class Raise:
    site: Site


Event = Union[Collective, Call, Branch, Loop, Return, Raise]


@dataclass
class FunctionInfo:
    name: str                                # bare name ("<module>" for files)
    site: Site
    traced: bool                             # under spmd/jit
    body: List[Event] = field(default_factory=list)
    wrapped: bool = False                    # passed to spmd/jit/elastic.run
    elastic: bool = False                    # body of hvd.elastic.run(fn, …)

    @property
    def qualname(self) -> str:
        return f"{self.site.file}::{self.name}"


@dataclass
class Entry:
    """A model-checking entry point and why it was chosen."""

    fn: FunctionInfo
    kind: str            # "module" | "root" | "wrapped" | "elastic"

    @property
    def world(self) -> str:
        """Elastic bodies re-execute per membership epoch: their schedule
        is checked per-epoch world, which the reports call out."""
        return "elastic" if (self.kind == "elastic" or self.fn.elastic) \
            else "static"


def walk_events(events: List[Event]):
    """Yield every event in a body, recursing into branches and loops."""
    for ev in events:
        yield ev
        if isinstance(ev, Branch):
            yield from walk_events(ev.body)
            yield from walk_events(ev.orelse)
        elif isinstance(ev, Loop):
            yield from walk_events(ev.body)


def has_collective(events: List[Event]) -> bool:
    return any(isinstance(ev, Collective) for ev in walk_events(events))


def called_names(events: List[Event]) -> set:
    return {ev.target for ev in walk_events(events) if isinstance(ev, Call)}
