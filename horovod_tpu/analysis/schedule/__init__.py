"""Whole-program collective-schedule model checker (`hvd_verify`).

The static half of the correctness story the runtime sanitizer
(analysis/sanitizer.py) covers at dispatch time: build an
interprocedural call graph over the training program, enumerate the
execution paths each rank can take through rank-tainted control flow,
project every path's collective sequence *per communication group*
(flat world, intra-host local, cross-host, process sets, named mesh
axes as ``axis:<name>``, per-epoch elastic worlds), and prove the
sequences pairwise compatible — or emit a machine-checkable
counterexample naming the diverging rank set, the collective, and the
exact branch chain (file:line per decision).  Point-to-point schedules
(``lax.ppermute``) lower to SendRecv events so pipeline handoffs are
first-class.

Rules HVD009–HVD015 (SCHEDULE_RULES, docs/analysis.md):

* HVD009 — schedule divergence within one group;
* HVD010 — blocking collective reachable on a strict subset of ranks;
* HVD011 — cross-group ordering inversion (intra vs cross stages);
* HVD012 — collective on an abort/cleanup path that peers skip;
* HVD013 — unmatched/cyclic point-to-point schedule (pipeline deadlock);
* HVD014 — cross-axis ordering inversion (HVD011 over mesh axes);
* HVD015 — axis-shape contract violation (mesh declaration vs dispatch).

Entry points: ``scripts/hvd_verify.py`` and ``hvd_lint --model-check``.
Bounds: HVD_VERIFY_MAX_PATHS / HVD_VERIFY_LOOP_BOUND (utils/env.py);
every loop unrolled to the bound is surfaced in the report's
``loop_bounds`` field (entry, loop kind, file:line, bound).
"""

from .checker import (  # noqa: F401
    CheckResult,
    SCHEDULE_RULES,
    check_paths,
    check_sources,
    render_result_json,
    render_result_text,
)
from .ir import (  # noqa: F401
    Collective,
    Entry,
    FunctionInfo,
    SendRecv,
    axis_group,
    is_axis_group,
)
from .paths import Decision, Dispatch, Enumerator, Path  # noqa: F401
