"""Whole-program collective-schedule model checker (`hvd_verify`).

The static half of the correctness story the runtime sanitizer
(analysis/sanitizer.py) covers at dispatch time: build an
interprocedural call graph over the training program, enumerate the
execution paths each rank can take through rank-tainted control flow,
project every path's collective sequence *per communication group*
(flat world, intra-host local, cross-host, process sets, per-epoch
elastic worlds), and prove the sequences pairwise compatible — or emit
a machine-checkable counterexample naming the diverging rank set, the
collective, and the exact branch chain (file:line per decision).

Rules HVD009–HVD012 (SCHEDULE_RULES, docs/analysis.md):

* HVD009 — schedule divergence within one group;
* HVD010 — blocking collective reachable on a strict subset of ranks;
* HVD011 — cross-group ordering inversion (intra vs cross stages);
* HVD012 — collective on an abort/cleanup path that peers skip.

Entry points: ``scripts/hvd_verify.py`` and ``hvd_lint --model-check``.
Bounds: HVD_VERIFY_MAX_PATHS / HVD_VERIFY_LOOP_BOUND (utils/env.py).
"""

from .checker import (  # noqa: F401
    CheckResult,
    SCHEDULE_RULES,
    check_paths,
    check_sources,
    render_result_json,
    render_result_text,
)
from .ir import Collective, Entry, FunctionInfo  # noqa: F401
from .paths import Decision, Dispatch, Enumerator, Path  # noqa: F401
