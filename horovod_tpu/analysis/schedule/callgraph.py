"""Interprocedural call graph + entry-point selection.

Resolution is tail-name based, like the linter's collective matching
(collective_api.py): `self._flush(x)`, `module._flush(x)` and a bare
`_flush(x)` all resolve to a function *named* ``_flush``.  Ambiguity is
handled conservatively — a call site binds to a same-file definition
first, and to a cross-file definition only when exactly one file defines
the name; otherwise the call stays unresolved (no inlining, no false
interprocedural findings).

Entry points, in the order the ISSUE names them:

* **train-step seams** — functions wrapped by ``hvd.spmd``/``jax.jit``
  (``step = hvd.spmd(one_step)``) or decorated so;
* **elastic bodies** — functions passed to ``hvd.elastic.run(fn, state)``,
  checked as per-epoch worlds;
* **roots** — module top-level bodies and functions no analyzed code
  calls (the ``main()``s and library API surface a user script dispatches
  from).

Entries whose transitive closure dispatches no collective are pruned
before enumeration — most of a real repo never touches the wire.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from .ir import Entry, FunctionInfo, called_names, has_collective


class CallGraph:
    def __init__(self, functions: List[FunctionInfo]):
        self.functions = functions
        self._by_name: Dict[str, List[FunctionInfo]] = {}
        for fn in functions:
            self._by_name.setdefault(fn.name, []).append(fn)
        self._dispatches: Dict[str, bool] = {}

    # -- resolution ----------------------------------------------------------
    def resolve(self, target: str,
                from_file: Optional[str] = None) -> Optional[FunctionInfo]:
        """The definition a call to ``target`` binds to, or None when the
        name is unknown or ambiguous across files."""
        candidates = self._by_name.get(target)
        if not candidates:
            return None
        if from_file is not None:
            same = [fn for fn in candidates if fn.site.file == from_file]
            if len(same) == 1:
                return same[0]
            if len(same) > 1:
                return None  # same-file overloads (class methods) — skip
        return candidates[0] if len(candidates) == 1 else None

    # -- reachability --------------------------------------------------------
    def dispatches(self, fn: FunctionInfo,
                   _stack: Optional[Set[str]] = None) -> bool:
        """Whether ``fn`` (transitively) dispatches any collective."""
        key = fn.qualname
        if key in self._dispatches:
            return self._dispatches[key]
        stack = _stack or set()
        if key in stack:
            return False
        if has_collective(fn.body):
            self._dispatches[key] = True
            return True
        stack = stack | {key}
        out = False
        for name in called_names(fn.body):
            callee = self.resolve(name, from_file=fn.site.file)
            if callee is not None and self.dispatches(callee, stack):
                out = True
                break
        self._dispatches[key] = out
        return out

    # -- entry points --------------------------------------------------------
    def entries(self, explicit: Optional[List[str]] = None) -> List[Entry]:
        """Model-checking entry points.  ``explicit`` (function names or
        ``file::name`` qualnames) overrides auto-detection."""
        if explicit:
            out = []
            for spec in explicit:
                matched = [fn for fn in self.functions
                           if fn.name == spec or fn.qualname == spec
                           or fn.qualname.endswith(spec)]
                if not matched:
                    # a typo'd --entry must be a usage error, not a
                    # green "verified 0 entries" (rules.py applies the
                    # same rule to nonexistent paths)
                    raise ValueError(
                        f"--entry {spec!r} matches no function in the "
                        "checked files")
                out.extend(Entry(fn=fn, kind="root") for fn in matched)
            return out

        all_called: Set[str] = set()
        for fn in self.functions:
            all_called |= called_names(fn.body)
        out = []
        for fn in self.functions:
            if not self.dispatches(fn):
                continue
            if fn.name == "<module>":
                out.append(Entry(fn=fn, kind="module"))
            elif fn.elastic:
                out.append(Entry(fn=fn, kind="elastic"))
            elif fn.wrapped:
                out.append(Entry(fn=fn, kind="wrapped"))
            elif fn.name not in all_called:
                out.append(Entry(fn=fn, kind="root"))
        return out
