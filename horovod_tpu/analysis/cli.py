"""CLI driver for hvd_lint (scripts/hvd_lint.py is the entry point).

Exit codes: 0 clean, 1 findings, 2 usage error — the shape CI expects
from a linter.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from .findings import render_json, render_text
from .rules import RULES, lint_paths


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvd_lint",
        description="Collective-correctness linter for horovod_tpu "
                    "training code: rank-divergent collectives, "
                    "data-dependent collectives in traced regions, "
                    "signature mismatches, host I/O under jit, and "
                    "general hygiene.",
    )
    p.add_argument("paths", nargs="*", default=[],
                   help="files or directories to lint (default: cwd)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (default: text)")
    p.add_argument("--disable", default="",
                   help="comma-separated rule IDs to skip (also honours "
                        "the HVD_LINT_DISABLE env knob)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--warnings-ok", action="store_true",
                   help="exit 0 when only warning-severity findings remain")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in sorted(RULES):
            sev, summary = RULES[rule]
            print(f"{rule}  [{sev:7s}]  {summary}")
        return 0
    paths = args.paths or ["."]
    disable = {r.strip() for r in args.disable.split(",") if r.strip()}
    try:
        findings = lint_paths(paths, disable=disable)
    except OSError as e:
        print(f"hvd_lint: {e}", file=sys.stderr)
        return 2
    print(render_json(findings) if args.format == "json"
          else render_text(findings))
    if not findings:
        return 0
    if args.warnings_ok and all(f.severity == "warning" for f in findings):
        return 0
    return 1
