"""CLI drivers for hvd_lint and hvd_verify (scripts/hvd_lint.py and
scripts/hvd_verify.py are the entry points).

Exit codes: 0 clean, 1 findings, 2 usage error — the shape CI expects
from a linter.  ``hvd_lint --model-check`` runs the schedule model
checker (analysis/schedule/) in the same session and merges its
HVD009–HVD015 findings into the lint report.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from .findings import render_json, render_text
from .rules import RULES, lint_paths


def _all_rules() -> dict:
    from .schedule import SCHEDULE_RULES

    merged = dict(RULES)
    merged.update(SCHEDULE_RULES)
    return merged


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvd_lint",
        description="Collective-correctness linter for horovod_tpu "
                    "training code: rank-divergent collectives, "
                    "data-dependent collectives in traced regions, "
                    "signature mismatches, host I/O under jit, and "
                    "general hygiene.",
    )
    p.add_argument("paths", nargs="*", default=[],
                   help="files or directories to lint (default: cwd)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (default: text)")
    p.add_argument("--disable", default="",
                   help="comma-separated rule IDs to skip (also honours "
                        "the HVD_LINT_DISABLE env knob)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--warnings-ok", action="store_true",
                   help="exit 0 when only warning-severity findings remain")
    p.add_argument("--model-check", action="store_true",
                   help="also run the interprocedural schedule model "
                        "checker (HVD009-HVD015; scripts/hvd_verify.py is "
                        "the standalone driver)")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        rules = _all_rules()
        for rule in sorted(rules):
            sev, summary = rules[rule]
            print(f"{rule}  [{sev:7s}]  {summary}")
        return 0
    paths = args.paths or ["."]
    disable = {r.strip() for r in args.disable.split(",") if r.strip()}
    try:
        if args.model_check:
            # one walk + read of the tree feeds both analyzers
            from .findings import sort_findings
            from .rules import lint_sources, read_sources
            from .schedule import check_sources

            sources, unreadable = read_sources(paths)
            findings = sort_findings(
                unreadable + lint_sources(sources, disable=disable))
            # both analyzers report unparsable files as HVD000 — keep
            # one finding per site, not one per analyzer
            seen = {(f.rule, f.file, f.line, f.col) for f in findings}
            findings = sort_findings(findings + [
                f for f in check_sources(sources, disable=disable).findings
                if (f.rule, f.file, f.line, f.col) not in seen])
        else:
            findings = lint_paths(paths, disable=disable)
    except OSError as e:
        print(f"hvd_lint: {e}", file=sys.stderr)
        return 2
    print(render_json(findings) if args.format == "json"
          else render_text(findings))
    if not findings:
        return 0
    if args.warnings_ok and all(f.severity == "warning" for f in findings):
        return 0
    return 1


# ---------------------------------------------------------------------------
# hvd_verify
# ---------------------------------------------------------------------------
def build_verify_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvd_verify",
        description="Whole-program collective-schedule model checker: "
                    "enumerates per-rank execution paths through "
                    "rank-tainted control flow interprocedurally, "
                    "projects each rank's collective sequence per "
                    "communication group, and proves them pairwise "
                    "compatible — or prints a counterexample naming the "
                    "diverging rank set, the collective, and the exact "
                    "branch chain.",
    )
    p.add_argument("paths", nargs="*", default=[],
                   help="files or directories to verify (default: cwd)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (default: text)")
    p.add_argument("--json", dest="format", action="store_const",
                   const="json", help="shorthand for --format json")
    p.add_argument("--entry", action="append", default=None,
                   metavar="NAME",
                   help="check only this entry point (function name or "
                        "file.py::name; repeatable; default: auto-detect "
                        "train-step seams, elastic bodies, module bodies "
                        "and uncalled roots)")
    p.add_argument("--max-paths", type=int, default=None,
                   help="per-entry path budget (default: "
                        "HVD_VERIFY_MAX_PATHS or 64)")
    p.add_argument("--loop-bound", type=int, default=None,
                   help="loop unroll bound (default: "
                        "HVD_VERIFY_LOOP_BOUND or 2)")
    p.add_argument("--disable", default="",
                   help="comma-separated rule IDs to skip (also honours "
                        "the HVD_LINT_DISABLE env knob)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the schedule rule catalogue and exit")
    return p


def main_verify(argv: Optional[Sequence[str]] = None) -> int:
    from .schedule import (
        SCHEDULE_RULES,
        check_paths,
        render_result_json,
        render_result_text,
    )

    args = build_verify_parser().parse_args(argv)
    if args.list_rules:
        for rule in sorted(SCHEDULE_RULES):
            sev, summary = SCHEDULE_RULES[rule]
            print(f"{rule}  [{sev:7s}]  {summary}")
        return 0
    paths = args.paths or ["."]
    disable = {r.strip() for r in args.disable.split(",") if r.strip()}
    try:
        result = check_paths(paths, entries=args.entry,
                             max_paths=args.max_paths,
                             loop_bound=args.loop_bound, disable=disable)
    except (OSError, ValueError) as e:
        print(f"hvd_verify: {e}", file=sys.stderr)
        return 2
    print(render_result_json(result) if args.format == "json"
          else render_result_text(result))
    return 1 if result.findings else 0
