"""AST walk extracting collective-relevant facts from one source file.

One pass produces a :class:`FileFacts` bundle; the rules (rules.py) are
pure functions over it.  The walk tracks three kinds of context:

* **traced regions** — functions decorated with (or wrapped by) ``spmd`` /
  ``jit`` / ``shard_map`` & co., where Python control flow executes at
  trace time and host I/O is poison;
* **rank-divergent branches** — ``if``/``while`` keyed on ``rank()``-family
  calls (directly or through a tainted local like
  ``verbose = hvd.rank() == 0``), where a collective in one arm only is a
  deadlock;
* **data-dependent branches inside traced code** — conditions derived from
  the traced function's own parameters, where a guarded collective means
  ranks can trace different programs.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from . import collective_api as api


def _dotted(node) -> Tuple[str, ...]:
    """The attribute chain of a Name/Attribute expression, else ()."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def _tail(node) -> str:
    """Final attribute name of a call target (``hvd.allreduce`` →
    ``allreduce``); empty for computed targets."""
    if isinstance(node, ast.Call):
        node = node.func
    d = _dotted(node)
    return d[-1] if d else ""


def _sig_source(node) -> str:
    """Comparable text for a signature keyword value.  Dotted names
    normalize to their tail so ``op=hvd.Sum`` and ``op=Sum`` (the same
    symbol imported two ways) don't read as a cross-site mismatch."""
    d = _dotted(node)
    if d:
        return d[-1]
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — exotic node
        return "<expr>"


@dataclass
class CollectiveCall:
    tail: str
    line: int
    col: int
    traced: bool
    discarded: bool
    name_kw: Optional[str]           # constant name= value, if any
    signature: Dict[str, str]        # normalized SIGNATURE_KEYWORDS sources
    depth: int = 0                   # function-frame depth at the call
    claimed: bool = False            # already reported by an inner branch


@dataclass
class BranchInfo:
    """A rank-divergent ``if``/``while``."""

    line: int
    col: int
    kind: str                        # "if" | "while"
    body: List[CollectiveCall]
    orelse: List[CollectiveCall]


@dataclass
class DynamicBranch:
    """A data-dependent ``if``/``while`` inside a traced region."""

    line: int
    col: int
    kind: str
    collectives: List[CollectiveCall]


@dataclass
class IOCall:
    line: int
    col: int
    what: str


@dataclass
class PermCall:
    """A point-to-point collective (``lax.ppermute``) whose permutation
    argument is a literal pair list — checkable for bijectivity."""

    line: int
    col: int
    tail: str
    pairs: List[Tuple[int, int]]


@dataclass
class EnvRead:
    line: int
    col: int
    var: str


@dataclass
class FileFacts:
    path: str
    calls: List[CollectiveCall] = field(default_factory=list)
    rank_branches: List[BranchInfo] = field(default_factory=list)
    dynamic_branches: List[DynamicBranch] = field(default_factory=list)
    io_calls: List[IOCall] = field(default_factory=list)
    perm_calls: List[PermCall] = field(default_factory=list)
    env_reads: List[EnvRead] = field(default_factory=list)
    mutable_defaults: List[Tuple[int, int, str]] = field(default_factory=list)
    bare_excepts: List[Tuple[int, int]] = field(default_factory=list)
    #: statement line spans for suppression mapping (statement_spans)
    spans: List[Tuple[int, int]] = field(default_factory=list)


def statement_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    """(start, end) line spans per statement, for suppression mapping
    (findings.Suppressions.attach_spans): a simple statement spans its
    whole source extent — a suppression on the closing paren of a
    multi-line call attaches to the call's reported line — and a
    compound statement (def/class/if/for/try…) spans its *header* only,
    decorators included, so a suppression on a decorator line attaches
    to findings anchored in the signature without silencing the body."""
    spans: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        body = getattr(node, "body", None)
        if isinstance(body, list) and body \
                and isinstance(body[0], ast.stmt):
            start = node.lineno
            decorators = getattr(node, "decorator_list", None)
            if decorators:
                start = min([d.lineno for d in decorators] + [start])
            end = max(start, body[0].lineno - 1)
            spans.append((start, end))
        else:
            spans.append((node.lineno,
                          getattr(node, "end_lineno", None) or node.lineno))
    return spans


class _Frame:
    __slots__ = ("traced", "params", "rank_tainted", "data_tainted")

    def __init__(self, traced: bool, params: Set[str]):
        self.traced = traced
        self.params = params
        self.rank_tainted: Set[str] = set()
        self.data_tainted: Set[str] = set()


_ENV_GETTERS = frozenset({"get_str", "get_int", "get_bool", "get_float",
                          "getenv"})
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set)
_MUTABLE_CTORS = frozenset({"list", "dict", "set", "defaultdict",
                            "OrderedDict", "deque"})


def _perm_pairs(node) -> Optional[List[Tuple[int, int]]]:
    """Literal ``[(src, dst), …]`` pairs of a ppermute perm argument,
    else None — comprehensions and symbolic perms are out of scope here
    (the schedule model checker reasons about those)."""
    if not isinstance(node, (ast.List, ast.Tuple)):
        return None
    pairs: List[Tuple[int, int]] = []
    for elt in node.elts:
        if not isinstance(elt, (ast.Tuple, ast.List)) \
                or len(elt.elts) != 2:
            return None
        pair = []
        for e in elt.elts:
            if isinstance(e, ast.Constant) and type(e.value) is int:
                pair.append(e.value)
            else:
                return None
        pairs.append((pair[0], pair[1]))
    return pairs


def _wrapped_function_names(tree: ast.AST) -> Set[str]:
    """Functions put on the traced path by *call*, not decorator:
    ``step = hvd.spmd(one_step, ...)`` / ``jax.jit(fn)`` — the first
    positional bare-name argument of a trace-wrapper call."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and api.is_trace_wrapper(_tail(node.func)) \
                and node.args and isinstance(node.args[0], ast.Name):
            names.add(node.args[0].id)
    return names


def _decorator_traced(dec) -> bool:
    if api.is_trace_wrapper(_tail(dec)):
        return True
    if isinstance(dec, ast.Call):
        if api.is_trace_wrapper(_tail(dec.func)):
            return True
        if _tail(dec.func) == "partial" and dec.args \
                and api.is_trace_wrapper(_tail(dec.args[0])):
            return True
    return False


class FactVisitor(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.AST):
        self.facts = FileFacts(path=path)
        self._frames: List[_Frame] = [_Frame(False, set())]  # module frame
        self._wrapped = _wrapped_function_names(tree)
        # A file defining its own ``def broadcast_(...)`` (the torch/mxnet
        # in-place variants) shadows the API: bare calls to it aren't the
        # framework collective and must not be matched by name.
        self._local_defs = {
            n.name for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self._discard_expr: Optional[ast.AST] = None

    # -- context helpers -----------------------------------------------------
    @property
    def _frame(self) -> _Frame:
        return self._frames[-1]

    def _traced(self) -> bool:
        return self._frame.traced

    def _rank_dep(self, expr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and api.is_rank_call(_tail(node)):
                return True
            if isinstance(node, ast.Name) \
                    and any(node.id in f.rank_tainted for f in self._frames):
                return True
        return False

    def _data_dep(self, expr) -> bool:
        f = self._frame
        if not f.traced:
            return False
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) \
                    and (node.id in f.params or node.id in f.data_tainted):
                return True
        return False

    # -- functions -----------------------------------------------------------
    def _visit_func(self, node) -> None:
        a = node.args
        for default in list(a.defaults) + [d for d in a.kw_defaults if d]:
            if isinstance(default, _MUTABLE_LITERALS) or (
                isinstance(default, ast.Call)
                and _tail(default.func) in _MUTABLE_CTORS
            ):
                self.facts.mutable_defaults.append(
                    (default.lineno, default.col_offset, node.name)
                )
        traced = (
            self._frame.traced
            or node.name in self._wrapped
            or any(_decorator_traced(d) for d in node.decorator_list)
        )
        params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        if a.vararg:
            params.add(a.vararg.arg)
        if a.kwarg:
            params.add(a.kwarg.arg)
        self._frames.append(_Frame(traced, params))
        try:
            for stmt in node.body:
                self.visit(stmt)
        finally:
            self._frames.pop()

    def visit_FunctionDef(self, node):  # noqa: N802
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        self._visit_func(node)

    def visit_Lambda(self, node):  # noqa: N802
        # a lambda body executes later, like a nested def — own frame so
        # branch attribution (depth) and data-dep tracking see it right
        a = node.args
        params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        self._frames.append(_Frame(self._frame.traced, params))
        try:
            self.visit(node.body)
        finally:
            self._frames.pop()

    # -- taint tracking ------------------------------------------------------
    def _taint_targets(self, targets, value) -> None:
        rank = self._rank_dep(value)
        data = self._data_dep(value)
        if not (rank or data):
            return
        for t in targets:
            for node in ast.walk(t):
                if isinstance(node, ast.Name):
                    if rank:
                        self._frame.rank_tainted.add(node.id)
                    if data:
                        self._frame.data_tainted.add(node.id)

    def visit_Assign(self, node):  # noqa: N802
        self._taint_targets(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):  # noqa: N802
        if node.value is not None:
            self._taint_targets([node.target], node.value)
        self.generic_visit(node)

    def visit_NamedExpr(self, node):  # noqa: N802
        self._taint_targets([node.target], node.value)
        self.generic_visit(node)

    # -- branches ------------------------------------------------------------
    def _visit_arm(self, stmts) -> List[CollectiveCall]:
        start = len(self.facts.calls)
        for stmt in stmts:
            self.visit(stmt)
        return self.facts.calls[start:]

    def _visit_branch(self, node, kind: str) -> None:
        rank_dep = self._rank_dep(node.test)
        data_dep = self._data_dep(node.test)
        self.visit(node.test)
        depth = len(self._frames)

        def arm(stmts):
            # A collective inside a nested def/lambda merely *defined* in
            # the arm does not dispatch here (depth filter); one already
            # reported by an inner rank-branch isn't re-reported by the
            # enclosing one (claimed filter).
            return [c for c in self._visit_arm(stmts)
                    if c.depth == depth and not c.claimed]

        body = arm(node.body)
        orelse = arm(node.orelse)
        if rank_dep:
            for c in body + orelse:
                c.claimed = True
            self.facts.rank_branches.append(BranchInfo(
                node.lineno, node.col_offset, kind, body, orelse,
            ))
        elif data_dep and (body or orelse):
            for c in body + orelse:
                c.claimed = True
            self.facts.dynamic_branches.append(DynamicBranch(
                node.lineno, node.col_offset, kind, body + orelse,
            ))

    def visit_If(self, node):  # noqa: N802
        self._visit_branch(node, "if")

    def visit_While(self, node):  # noqa: N802
        self._visit_branch(node, "while")

    # -- statements ----------------------------------------------------------
    def visit_Expr(self, node):  # noqa: N802
        self._discard_expr = node.value
        try:
            self.generic_visit(node)
        finally:
            self._discard_expr = None

    def visit_ExceptHandler(self, node):  # noqa: N802
        if node.type is None:
            self.facts.bare_excepts.append((node.lineno, node.col_offset))
        self.generic_visit(node)

    def visit_Subscript(self, node):  # noqa: N802
        d = _dotted(node.value)
        # Load context only: an environ[...] *assignment* is a launcher
        # exporting a knob to children, not an undeclared read
        if d and d[-1] == "environ" and isinstance(node.ctx, ast.Load) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str) \
                and node.slice.value.startswith("HVD_"):
            self.facts.env_reads.append(EnvRead(
                node.lineno, node.col_offset, node.slice.value,
            ))
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------------
    def visit_Call(self, node):  # noqa: N802
        tail = _tail(node.func)
        shadowed = (isinstance(node.func, ast.Name)
                    and tail in self._local_defs)
        if api.is_collective_call(_dotted(node.func)) and not shadowed:
            sig: Dict[str, str] = {}
            name_kw = None
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    name_kw = kw.value.value
                elif kw.arg in api.SIGNATURE_KEYWORDS:
                    sig[kw.arg] = _sig_source(kw.value)
            self.facts.calls.append(CollectiveCall(
                tail=tail, line=node.lineno, col=node.col_offset,
                traced=self._traced(),
                discarded=node is self._discard_expr,
                name_kw=name_kw, signature=sig,
                depth=len(self._frames),
            ))
        if tail in api.P2P_COLLECTIVES and not shadowed:
            perm = None
            for kw in node.keywords:
                if kw.arg == "perm":
                    perm = kw.value
            if perm is None and len(node.args) >= 3:
                perm = node.args[2]
            pairs = _perm_pairs(perm) if perm is not None else None
            if pairs is not None:
                self.facts.perm_calls.append(PermCall(
                    node.lineno, node.col_offset, tail, pairs,
                ))
        self._check_blocking(node, tail)
        self._check_env_read(node, tail)
        self.generic_visit(node)

    def _check_blocking(self, node, tail: str) -> None:
        if not self._traced():
            return
        d = _dotted(node.func)
        if d and len(d) >= 2 and (d[-2], d[-1]) in api.TRACE_SAFE_DOTTED:
            return
        what = None
        if isinstance(node.func, ast.Name) \
                and tail in api.BLOCKING_BARE_CALLS:
            what = tail
        elif len(d) >= 2 and (d[-2], d[-1]) in api.BLOCKING_DOTTED_CALLS:
            what = ".".join(d[-2:])
        elif d and d[0] in api.BLOCKING_BASE_MODULES:
            what = ".".join(d)
        if what:
            self.facts.io_calls.append(
                IOCall(node.lineno, node.col_offset, what)
            )

    def _check_env_read(self, node, tail: str) -> None:
        d = _dotted(node.func)
        is_environ_get = (tail == "get" and len(d) >= 2
                          and d[-2] == "environ")
        if not (is_environ_get or tail in _ENV_GETTERS):
            return
        if not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith("HVD_"):
            self.facts.env_reads.append(
                EnvRead(node.lineno, node.col_offset, arg.value)
            )


def collect_facts(source: str, path: str) -> FileFacts:
    """Parse + walk one file.  Raises SyntaxError on unparsable input —
    the caller turns that into a finding."""
    tree = ast.parse(source, filename=path)
    v = FactVisitor(path, tree)
    v.visit(tree)
    v.facts.spans = statement_spans(tree)
    return v.facts
