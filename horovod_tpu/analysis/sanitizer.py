"""Cross-rank collective sanitizer — the runtime half of hvd_verify.

The failure mode the static checkers catch at review time (ranks
disagreeing on which collective runs next) is, at runtime, a silent
hang: every rank blocks in a different collective and only the stall
inspector's 60-second post-mortem names the op.  With ``HVD_SANITIZER=1``
each eager dispatch is fingerprinted *before* it runs and cross-checked
against its peers through the launcher's rendezvous KV store
(run/http_server.py), the same transport the metrics pusher already
rides.  A divergence raises :class:`CollectiveDivergenceError` on every
rank that can see it, naming the diverging rank and both call
signatures; a peer that never dispatches (the classic rank-guarded
collective) surfaces as a timeout diagnostic instead of an infinite
hang.

**Fingerprint v2 — group- and epoch-aware.**  A fingerprint is
``(group, epoch, seq, op, name, shape, dtype, clock)``:

* ``group`` names the communication group the dispatch reduces over —
  ``world`` for flat collectives, ``local:<node>`` / ``cross:<chunk>``
  for the two-level stages (parallel/hierarchical.py surfaces the stage
  plan to dispatch), ``process_set:…`` for restricted communicators, and
  ``axis:<name>:<instance>`` for one instance of a named mesh axis (the
  runtime spelling of the model checker's ``axis:<name>`` group labels —
  a 2×3 tp×pp mesh has three ``axis:tp:<k>`` groups and two
  ``axis:pp:<k>`` groups).  Sequence numbers count **per (group,
  epoch)** and checks compare only the group's members, so a two_level
  run no longer cross-matches its intra-host stage on one rank against
  the cross-host stage on another — the flat-world false mismatch this
  plane shipped with.  Point-to-point ops (``ppermute`` /
  ``all_to_all``) carry their permutation / axis identity in the
  fingerprint (``perm``), so two stage ranks dispatching the same op
  with different permutations is a signature divergence naming both
  permutations, not a silent data swap.
* ``epoch`` is the elastic membership epoch (elastic/membership.py).
  Under ``HVD_SANITIZER_EPOCH_STRICT`` (default) fingerprints only match
  within one epoch, so a rank still draining epoch N never pairs with a
  peer already rebuilt into N+1; set it to 0 to let checks span a
  rebuild window while debugging elastic jobs.
* ``clock`` is this rank's dispatch counter across *all* groups — a
  vector-clock component.  Each rank records the clocks at which it and
  each peer issued the shared (group, seq) dispatches; two shared
  dispatches issued in opposite clock order on two ranks is a
  **cross-group ordering inversion** (the runtime twin of hvd_verify's
  HVD011 — named as HVD014 when both streams are ``axis:`` groups, the
  mesh-shaped inversion) and raises instead of deadlocking with both
  ranks blocked in different groups' collectives.

This is a debug plane: every check is one KV PUT plus size-1 GET-polls
per group peer, so it multiplies eager-dispatch latency — leave it off
in production and flip it on to turn a reproducible hang into a
diagnosis.  The compiled hot path (hvd.spmd steps) is untouched: XLA's
static schedule already cannot reorder collectives per rank; divergence
enters through the eager control plane this guards.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional, Sequence, Tuple

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

# The KV scope fingerprints live under is owned by the server
# (run/http_server.py SANITIZER_SCOPE — the GET /sanitizer route and key
# parsing derive from it); imported lazily in check() like the client.

DEFAULT_TIMEOUT_SECONDS = 60.0

#: the flat-world group label (every rank participates)
WORLD_GROUP = "world"

#: runtime mesh-axis group labels: ``axis:<name>:<instance>`` — the
#: prefix matches the static checker's (schedule/ir.py
#: GROUP_AXIS_PREFIX); colons are KV-safe (keys split on ``.``)
AXIS_GROUP_PREFIX = "axis:"

#: how many verified sequence numbers each rank keeps published per
#: (group, epoch) before garbage-collecting its own old fingerprints.
#: Completing sequence N proves every group peer has *started* N (they
#: all published it), so no peer can still need keys below N; the window
#: keeps GET /sanitizer a useful recent view while bounding the
#: launcher's store at O(window x ranks x groups).
GC_WINDOW = 64

#: how many recent shared dispatches per peer the ordering index keeps
ORDER_WINDOW = 32

#: scope-cache size that triggers GC-window-based pruning (the bound is
#: soft: a 1k-rank world legitimately holds ranks x groups live streams,
#: and pruning must never evict a stream's newest fingerprint)
CACHE_SOFT_LIMIT = 16384


class CollectiveDivergenceError(RuntimeError):
    """Ranks disagreed on which collective to run next (or one rank never
    dispatched at all).  Raised instead of the hang the divergence would
    otherwise become."""


def group_key(group: str) -> str:
    """KV-safe group slug: the store key format is
    ``<group>.<epoch>.<seq>.<rank>``, so the group must not contain the
    separator."""
    return str(group).replace(".", "_").replace("/", "_")


def fingerprint(seq: int, *, op: str, name: str, shape: Sequence[int],
                dtype, group: str = WORLD_GROUP, epoch: int = 0,
                clock: int = 0, perm: Optional[str] = None) -> dict:
    return {
        "seq": int(seq),
        "op": str(op),
        "name": str(name),
        "shape": [int(d) for d in shape],
        "dtype": str(dtype),
        "group": str(group),
        "epoch": int(epoch),
        "clock": int(clock),
        "perm": str(perm) if perm is not None else "",
    }


def _sig(fp: dict) -> str:
    perm = fp.get("perm") or ""
    return (f"{fp['op']}(name={fp['name']!r}, shape={tuple(fp['shape'])}, "
            f"dtype={fp['dtype']}"
            + (f", perm={perm}" if perm else "") + ")")


def _cmp_view(fp: dict) -> dict:
    """The fields two peers' fingerprints must agree on.  ``perm``
    normalizes absent → "" so fingerprints published by an older build
    (no perm field) compare equal to a perm-less dispatch."""
    view = {k: fp.get(k) for k in ("op", "name", "shape", "dtype")}
    view["perm"] = fp.get("perm") or ""
    return view


class OrderIndex:
    """Happens-before index over one rank's view of its own and its
    peers' dispatch clocks.

    ``observe(peer, key, mine, theirs)`` records that the shared
    dispatch ``key`` (a ``(group, epoch, seq)`` triple) was issued at
    local clock ``mine`` and at ``peer``'s clock ``theirs``; it returns
    the earlier shared key that ``peer`` ordered the *other* way, if
    any.  Within one rank clocks are totally ordered, so for two shared
    dispatches a and b: a→b here and b→a there means each rank can block
    in a different group's collective — a deadlock no per-group sequence
    check can see.

    Comparisons never cross membership epochs: an elastic rebuild (or a
    peer relaunched into a new epoch) resets that peer's clock, so an
    epoch-N entry ordered against an epoch-N+1 entry would read as a
    spurious inversion."""

    def __init__(self, window: int = ORDER_WINDOW):
        self.window = int(window)
        self._mine: Dict[Tuple, int] = {}
        self._mine_order: list = []
        self._theirs: Dict[int, Dict[Tuple, int]] = {}
        self._recent: Dict[int, list] = {}

    def observe(self, peer: int, key: Tuple, mine: int,
                theirs: int) -> Optional[Tuple]:
        peer_clocks = self._theirs.setdefault(peer, {})
        recent = self._recent.setdefault(peer, [])
        inverted = None
        for prev in recent:
            if prev[1] != key[1]:
                continue  # a different epoch: clocks are not comparable
            pm, pt = self._mine.get(prev), peer_clocks.get(prev)
            if pm is None or pt is None:
                continue
            if (pm < mine) != (pt < theirs):
                inverted = prev
                break
        if key not in self._mine:
            self._mine_order.append(key)
        self._mine[key] = mine
        peer_clocks[key] = theirs
        recent.append(key)
        if len(recent) > self.window:
            dropped = recent.pop(0)
            peer_clocks.pop(dropped, None)
        # bound the local clock map too (a long run must not grow it
        # per dispatch forever): keep enough history to serve every
        # peer's window; an evicted key simply stops being comparable
        limit = self.window * (len(self._theirs) + 1) * 2
        while len(self._mine_order) > limit:
            self._mine.pop(self._mine_order.pop(0), None)
        return inverted


class Sanitizer:
    """One rank's sanitizer: publishes this rank's fingerprint for each
    collective sequence number and verifies every group peer published
    an identical one before the dispatch proceeds."""

    def __init__(self, rank: int, size: int, addr: str, port: int,
                 secret: Optional[bytes] = None,
                 timeout: float = DEFAULT_TIMEOUT_SECONDS,
                 epoch_fn=None, epoch_strict: Optional[bool] = None):
        self.rank = int(rank)
        self.size = int(size)
        self.addr = addr
        self.port = int(port)
        self.secret = secret
        self.timeout = float(timeout)
        self.epoch_fn = epoch_fn
        if epoch_strict is None:
            epoch_strict = env_util.get_bool(
                env_util.HVD_SANITIZER_EPOCH_STRICT, True)
        self.epoch_strict = bool(epoch_strict)
        self._seqs: Dict[Tuple[str, int], int] = {}
        self._last_epoch: Dict[str, int] = {}
        self._clock = 0
        self._order = OrderIndex()
        self._lock = threading.Lock()
        # batched peer reads (docs/control_plane.md): one GET
        # /scope/sanitizer?since=<cursor> per poll round replaces a GET
        # per peer; the cache holds every decoded fingerprint the
        # cursor has swept past, pruned by the peers' own GC deletes
        self._cursor: Optional[int] = None
        self._scope_server: Optional[str] = None
        self._scope_cache: Dict[str, dict] = {}

    # -- internals -----------------------------------------------------------
    def _epoch(self) -> int:
        if self.epoch_fn is not None:
            try:
                return int(self.epoch_fn())
            except Exception:  # noqa: BLE001 — a broken epoch source
                return 0       # must not fail the check
        return 0

    def _next(self, group: str, epoch: int) -> Tuple[int, int, Optional[int]]:
        """(seq, clock, retired_epoch): ``retired_epoch`` is the group's
        previous epoch the first time a new one is seen — the caller
        garbage-collects its stranded fingerprints (an elastic job must
        not leak a window of keys per epoch bump)."""
        with self._lock:
            seq = self._seqs.get((group, epoch), 0)
            self._seqs[(group, epoch)] = seq + 1
            self._clock += 1
            prev = self._last_epoch.get(group)
            self._last_epoch[group] = epoch
            retired = prev if prev is not None and prev != epoch else None
            return seq, self._clock, retired

    def _gc_epoch(self, group: str, epoch: int) -> None:
        """Best-effort delete of this rank's remaining fingerprints for a
        retired (group, epoch) — the keys the rolling per-seq GC never
        reaches once the epoch stops advancing."""
        try:
            from ..run.http_client import delete_kv
            from ..run.http_server import SANITIZER_SCOPE

            last = self._seqs.get((group, epoch), 0)
            for seq in range(max(0, last - GC_WINDOW), last):
                delete_kv(self.addr, self.port, SANITIZER_SCOPE,
                          self._kv_key(group, epoch, seq, self.rank),
                          self.secret)
        except Exception:  # noqa: BLE001 — GC must never fail a check
            pass

    @staticmethod
    def _kv_key(group: str, epoch: int, seq: int, rank: int) -> str:
        return f"{group_key(group)}.{epoch}.{seq}.{rank}"

    def _raise(self, msg: str) -> None:
        from .. import metrics

        metrics.SANITIZER_MISMATCHES.inc()
        raise CollectiveDivergenceError(msg)

    def _publish(self, key: str, fp: dict) -> None:
        """PUT this rank's fingerprint — through the host relay when
        one is discoverable (the storm batches into O(hosts) upstream
        requests), direct otherwise, with the shared pass-through
        fallback (run/relay.py control_put)."""
        from ..run import relay
        from ..run.http_server import SANITIZER_SCOPE

        relay.control_put(self.addr, self.port, SANITIZER_SCOPE, key,
                          json.dumps(fp).encode(), secret=self.secret)

    def _refresh_scope(self) -> None:
        """One batched scope read: advance the cursor, fold changed
        fingerprints into the cache, drop GC'd keys, and reset
        everything when the server incarnation changed (failover)."""
        from ..run.http_client import get_scope
        from ..run.http_server import SANITIZER_SCOPE

        resp = get_scope(self.addr, self.port, SANITIZER_SCOPE,
                         since=self._cursor, secret=self.secret)
        sid = resp.get("server_id")
        if resp.get("full") or sid != self._scope_server:
            self._scope_cache.clear()
            self._scope_server = sid
        self._cursor = resp.get("version")
        for key, raw in resp.get("entries", {}).items():
            try:
                self._scope_cache[key] = json.loads(raw)
            except (ValueError, TypeError):
                self._scope_cache[key] = {
                    "op": "<undecodable>", "name": "", "shape": [],
                    "dtype": "", "clock": 0}
        for key in resp.get("removed", ()):
            self._scope_cache.pop(key, None)
        if len(self._scope_cache) > CACHE_SOFT_LIMIT:
            self._prune_cache()

    def _prune_cache(self) -> None:
        """Bound the cache by each (group, epoch, rank)'s sequence
        window, mirroring the peers' own GC: entries more than
        GC_WINDOW behind that stream's newest seq can never be needed
        again.  Never evicts a stream's NEWEST fingerprint — dropping a
        peer's current entry would strand it past the cursor and turn a
        healthy peer into a false silent-peer divergence (keys are
        ``<group>.<epoch>.<seq>.<rank>``; group slugs are dot-free by
        :func:`group_key`)."""

        def parse(key):
            parts = key.rsplit(".", 3)
            if len(parts) != 4 or not parts[2].isdigit():
                return None
            return (parts[0], parts[1], parts[3]), int(parts[2])

        newest: Dict[Tuple, int] = {}
        for key in self._scope_cache:
            parsed = parse(key)
            if parsed is None:
                continue
            stream, seq = parsed
            newest[stream] = max(newest.get(stream, -1), seq)
        for key in list(self._scope_cache):
            parsed = parse(key)
            if parsed is None:
                self._scope_cache.pop(key, None)
                continue
            stream, seq = parsed
            if seq < newest[stream] - GC_WINDOW:
                self._scope_cache.pop(key, None)

    # -- the check -----------------------------------------------------------
    def check(self, *, op: str, name: str, shape: Sequence[int], dtype,
              group: str = WORLD_GROUP,
              peers: Optional[Sequence[int]] = None,
              epoch: Optional[int] = None,
              perm: Optional[str] = None) -> int:
        """Fingerprint + cross-check one collective dispatch within its
        communication group.  ``peers`` is the group's member ranks
        (default: all ranks — the flat world).  ``perm`` is the
        permutation / axis identity of a point-to-point dispatch
        (ppermute pair list, all_to_all split spec) — part of the
        compared signature, so stage ranks disagreeing on the
        permutation raise naming both.  Returns the per-(group, epoch)
        sequence number it verified; raises CollectiveDivergenceError
        on signature divergence, a silent peer, or a cross-group
        ordering inversion.

        The peer wait is batched (docs/control_plane.md): every poll
        round is ONE cursor-based scope read covering all peers of all
        groups, instead of a GET per peer — the O(ranks x groups) poll
        traffic this plane used to put on the rendezvous server."""
        import time as _time

        from .. import metrics

        if epoch is None:
            epoch = self._epoch()
        match_epoch = epoch if self.epoch_strict else 0
        members = sorted(int(p) for p in peers) if peers is not None \
            else list(range(self.size))
        if self.rank not in members:
            raise ValueError(
                f"rank {self.rank} dispatched a collective for group "
                f"'{group}' it is not a member of (members: {members})")
        seq, clock, retired_epoch = self._next(group, match_epoch)
        if retired_epoch is not None:
            self._gc_epoch(group, retired_epoch)
        mine = fingerprint(seq, op=op, name=name, shape=shape, dtype=dtype,
                           group=group, epoch=epoch, clock=clock, perm=perm)
        self._publish(self._kv_key(group, match_epoch, seq, self.rank),
                      mine)
        need = {peer: self._kv_key(group, match_epoch, seq, peer)
                for peer in members if peer != self.rank}
        deadline = _time.monotonic() + self.timeout
        delay = 0.01
        while need:
            self._refresh_scope()
            for peer in sorted(need):
                theirs = self._scope_cache.get(need[peer])
                if theirs is None:
                    continue
                if _cmp_view(theirs) != _cmp_view(mine):
                    self._raise(
                        f"collective sanitizer: divergence at sequence "
                        f"{seq} of group '{group}' (epoch {epoch}) — rank "
                        f"{self.rank} dispatched {_sig(mine)} but rank "
                        f"{peer} dispatched {_sig(theirs)}"
                    )
                inverted = self._order.observe(
                    peer, (group, match_epoch, seq), clock,
                    int(theirs.get("clock", 0)))
                if inverted is not None:
                    g2, _, s2 = inverted
                    both_axes = (str(g2).startswith(AXIS_GROUP_PREFIX)
                                 and str(group).startswith(
                                     AXIS_GROUP_PREFIX))
                    kind = ("cross-axis ordering inversion (runtime "
                            "HVD014)" if both_axes
                            else "cross-group ordering inversion")
                    self._raise(
                        f"collective sanitizer: {kind} — rank "
                        f"{self.rank} issued sequence "
                        f"{s2} of group '{g2}' before sequence {seq} of "
                        f"group '{group}' ({_sig(mine)}), but rank {peer} "
                        "issued them in the opposite order; each rank "
                        "blocks in a different "
                        + ("axis's" if both_axes else "group's")
                        + " collective"
                    )
                del need[peer]
            if not need:
                break
            if _time.monotonic() >= deadline:
                peer = min(need)
                self._raise(
                    f"collective sanitizer: rank {peer} published no "
                    f"fingerprint for sequence {seq} of group '{group}' "
                    f"(epoch {epoch}) within {self.timeout:.0f}s while "
                    f"rank {self.rank} dispatched {_sig(mine)} — rank "
                    f"{peer} is running a different collective schedule "
                    "(rank-guarded collective, early exit, a hang "
                    "upstream, or a different membership epoch under "
                    "HVD_SANITIZER_EPOCH_STRICT)"
                )
            _time.sleep(delay)
            delay = min(delay * 1.5, 0.25)
        metrics.SANITIZER_CHECKS.inc()
        if seq >= GC_WINDOW:
            # best-effort GC of this rank's own stale fingerprint — a
            # long job must not grow the launcher's store without bound
            try:
                from ..run.http_client import delete_kv
                from ..run.http_server import SANITIZER_SCOPE

                delete_kv(self.addr, self.port, SANITIZER_SCOPE,
                          self._kv_key(group, match_epoch,
                                       seq - GC_WINDOW, self.rank),
                          self.secret)
            except Exception:  # noqa: BLE001 — GC must never fail a check
                pass
        return seq


# ---------------------------------------------------------------------------
# process-wide wiring (hooked by eager._dispatch_guard)
# ---------------------------------------------------------------------------
_UNSET = object()
_instance = _UNSET
_instance_lock = threading.Lock()


def _build_from_env():
    """The process sanitizer, from launcher-provided env: enabled by
    HVD_SANITIZER, carried by the metrics rendezvous (addr/port/secret
    the launcher already exports for the pusher), epoch-fed by the
    elastic membership plane."""
    if not env_util.get_bool(env_util.HVD_SANITIZER, False):
        return None
    from .. import core

    size = core.process_size()
    if size <= 1:
        return None  # nothing to cross-check
    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port:
        log.warning(
            "HVD_SANITIZER=1 but no rendezvous address "
            "(HVD_METRICS_KV_ADDR/PORT unset) — sanitizer disabled"
        )
        return None
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    secret = bytes.fromhex(secret_hex) if secret_hex else None
    timeout = env_util.get_float(env_util.HVD_SANITIZER_TIMEOUT_SECONDS,
                                 DEFAULT_TIMEOUT_SECONDS)
    from ..elastic import membership

    s = Sanitizer(core.process_rank(), size, addr, port,
                  secret=secret, timeout=timeout,
                  epoch_fn=membership.current_epoch)
    log.info("collective sanitizer active: rank %d/%d via %s:%d "
             "(timeout %.0fs, epoch_strict=%s)", s.rank, s.size, addr,
             port, timeout, s.epoch_strict)
    return s


def instance() -> Optional[Sanitizer]:
    """The process sanitizer, built lazily on first dispatch (None when
    disabled — the common case costs one identity comparison)."""
    global _instance
    if _instance is _UNSET:
        with _instance_lock:
            if _instance is _UNSET:
                try:
                    _instance = _build_from_env()
                except Exception:  # noqa: BLE001 — a broken sanitizer
                    log.exception("sanitizer setup failed; disabled")
                    _instance = None
    return _instance


def reset() -> None:
    """Drop the cached process sanitizer (tests / re-init)."""
    global _instance
    with _instance_lock:
        _instance = _UNSET


def maybe_check(*, op: str, name: str, shape: Sequence[int], dtype,
                group: str = WORLD_GROUP,
                peers: Optional[Sequence[int]] = None,
                perm: Optional[str] = None) -> None:
    """The eager._dispatch_guard hook: no-op unless HVD_SANITIZER=1."""
    s = instance()
    if s is not None:
        s.check(op=op, name=name, shape=shape, dtype=dtype,
                group=group, peers=peers, perm=perm)
