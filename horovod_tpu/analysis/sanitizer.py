"""Cross-rank collective sanitizer — the runtime half of hvd_lint.

The failure mode the linter catches at review time (ranks disagreeing on
which collective runs next) is, at runtime, a silent hang: every rank
blocks in a different collective and only the stall inspector's 60-second
post-mortem names the op.  With ``HVD_SANITIZER=1`` each eager dispatch
is fingerprinted *before* it runs — (sequence number, op kind, tensor
name, shape, dtype) — and cross-checked against every peer through the
launcher's rendezvous KV store (run/http_server.py), the same transport
the metrics pusher already rides.  A divergence raises
:class:`CollectiveDivergenceError` on every rank that can see it, naming
the diverging rank and both call signatures; a peer that never dispatches
(the classic rank-guarded collective) surfaces as a timeout diagnostic
instead of an infinite hang.

This is a debug plane: every check is one KV PUT plus size-1 GET-polls
per peer, so it multiplies eager-dispatch latency — leave it off in
production and flip it on to turn a reproducible hang into a diagnosis.
The compiled hot path (hvd.spmd steps) is untouched: XLA's static
schedule already cannot reorder collectives per rank; divergence enters
through the eager control plane this guards.
"""

from __future__ import annotations

import json
import threading
from typing import Optional, Sequence

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

# The KV scope fingerprints live under is owned by the server
# (run/http_server.py SANITIZER_SCOPE — the GET /sanitizer route and key
# parsing derive from it); imported lazily in check() like the client.

DEFAULT_TIMEOUT_SECONDS = 60.0

#: how many verified sequence numbers each rank keeps published before
#: garbage-collecting its own old fingerprints.  Completing sequence N
#: proves every peer has *started* N (they all published it), so no peer
#: can still need keys below N; the window keeps GET /sanitizer a useful
#: recent view while bounding the launcher's store at O(window x ranks).
GC_WINDOW = 64


class CollectiveDivergenceError(RuntimeError):
    """Ranks disagreed on which collective to run next (or one rank never
    dispatched at all).  Raised instead of the hang the divergence would
    otherwise become."""


def fingerprint(seq: int, *, op: str, name: str, shape: Sequence[int],
                dtype) -> dict:
    return {
        "seq": int(seq),
        "op": str(op),
        "name": str(name),
        "shape": [int(d) for d in shape],
        "dtype": str(dtype),
    }


def _sig(fp: dict) -> str:
    return (f"{fp['op']}(name={fp['name']!r}, shape={tuple(fp['shape'])}, "
            f"dtype={fp['dtype']})")


class Sanitizer:
    """One rank's sanitizer: publishes this rank's fingerprint for each
    collective sequence number and verifies every peer published an
    identical one before the dispatch proceeds."""

    def __init__(self, rank: int, size: int, addr: str, port: int,
                 secret: Optional[bytes] = None,
                 timeout: float = DEFAULT_TIMEOUT_SECONDS):
        self.rank = int(rank)
        self.size = int(size)
        self.addr = addr
        self.port = int(port)
        self.secret = secret
        self.timeout = float(timeout)
        self._seq = 0
        self._lock = threading.Lock()

    def check(self, *, op: str, name: str, shape: Sequence[int],
              dtype) -> int:
        """Fingerprint + cross-check one collective dispatch.  Returns the
        sequence number it verified; raises CollectiveDivergenceError on
        signature divergence or a silent peer."""
        from ..run.http_client import get_kv, put_kv
        from ..run.http_server import SANITIZER_SCOPE

        from .. import metrics

        with self._lock:
            seq = self._seq
            self._seq += 1
        mine = fingerprint(seq, op=op, name=name, shape=shape, dtype=dtype)
        put_kv(self.addr, self.port, SANITIZER_SCOPE,
               f"{seq}.{self.rank}", json.dumps(mine).encode(), self.secret)
        for peer in range(self.size):
            if peer == self.rank:
                continue
            raw = get_kv(self.addr, self.port, SANITIZER_SCOPE,
                         f"{seq}.{peer}", self.secret,
                         wait=True, timeout=self.timeout)
            if raw is None:
                metrics.SANITIZER_MISMATCHES.inc()
                raise CollectiveDivergenceError(
                    f"collective sanitizer: rank {peer} published no "
                    f"fingerprint for sequence {seq} within "
                    f"{self.timeout:.0f}s while rank {self.rank} "
                    f"dispatched {_sig(mine)} — rank {peer} is running a "
                    "different collective schedule (rank-guarded "
                    "collective, early exit, or a hang upstream)"
                )
            theirs = json.loads(raw)
            if {k: theirs[k] for k in ("op", "name", "shape", "dtype")} != \
                    {k: mine[k] for k in ("op", "name", "shape", "dtype")}:
                metrics.SANITIZER_MISMATCHES.inc()
                raise CollectiveDivergenceError(
                    f"collective sanitizer: divergence at sequence {seq} — "
                    f"rank {self.rank} dispatched {_sig(mine)} but rank "
                    f"{peer} dispatched {_sig(theirs)}"
                )
        metrics.SANITIZER_CHECKS.inc()
        if seq >= GC_WINDOW:
            # best-effort GC of this rank's own stale fingerprint — a
            # long job must not grow the launcher's store without bound
            try:
                from ..run.http_client import delete_kv

                delete_kv(self.addr, self.port, SANITIZER_SCOPE,
                          f"{seq - GC_WINDOW}.{self.rank}", self.secret)
            except Exception:  # noqa: BLE001 — GC must never fail a check
                pass
        return seq


# ---------------------------------------------------------------------------
# process-wide wiring (hooked by eager._dispatch_guard)
# ---------------------------------------------------------------------------
_UNSET = object()
_instance = _UNSET
_instance_lock = threading.Lock()


def _build_from_env():
    """The process sanitizer, from launcher-provided env: enabled by
    HVD_SANITIZER, carried by the metrics rendezvous (addr/port/secret
    the launcher already exports for the pusher)."""
    if not env_util.get_bool(env_util.HVD_SANITIZER, False):
        return None
    from .. import core

    size = core.process_size()
    if size <= 1:
        return None  # nothing to cross-check
    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port:
        log.warning(
            "HVD_SANITIZER=1 but no rendezvous address "
            "(HVD_METRICS_KV_ADDR/PORT unset) — sanitizer disabled"
        )
        return None
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    secret = bytes.fromhex(secret_hex) if secret_hex else None
    timeout = env_util.get_float(env_util.HVD_SANITIZER_TIMEOUT_SECONDS,
                                 DEFAULT_TIMEOUT_SECONDS)
    s = Sanitizer(core.process_rank(), size, addr, port,
                  secret=secret, timeout=timeout)
    log.info("collective sanitizer active: rank %d/%d via %s:%d "
             "(timeout %.0fs)", s.rank, s.size, addr, port, timeout)
    return s


def instance() -> Optional[Sanitizer]:
    """The process sanitizer, built lazily on first dispatch (None when
    disabled — the common case costs one identity comparison)."""
    global _instance
    if _instance is _UNSET:
        with _instance_lock:
            if _instance is _UNSET:
                try:
                    _instance = _build_from_env()
                except Exception:  # noqa: BLE001 — a broken sanitizer
                    log.exception("sanitizer setup failed; disabled")
                    _instance = None
    return _instance


def reset() -> None:
    """Drop the cached process sanitizer (tests / re-init)."""
    global _instance
    with _instance_lock:
        _instance = _UNSET


def maybe_check(*, op: str, name: str, shape: Sequence[int], dtype) -> None:
    """The eager._dispatch_guard hook: no-op unless HVD_SANITIZER=1."""
    s = instance()
    if s is not None:
        s.check(op=op, name=name, shape=shape, dtype=dtype)
