"""Static + runtime collective-correctness analysis.

Two halves of one story — catching "ranks disagree on which collective
runs next" *before* it becomes a hang:

* **hvd_lint** (findings.py / collective_api.py / visitor.py / rules.py /
  cli.py): an AST pass over training code modelling the repo's collective
  API surface.  Rule catalogue in rules.RULES, user docs in
  docs/analysis.md, CLI at scripts/hvd_lint.py.
* **the collective sanitizer** (sanitizer.py): ``HVD_SANITIZER=1`` makes
  every eager dispatch fingerprint itself and cross-check against all
  peers through the rendezvous KV store, raising a diagnostic that names
  the diverging rank and both signatures instead of deadlocking.
"""

from .findings import (  # noqa: F401
    Finding,
    Suppressions,
    render_json,
    render_text,
)
from .rules import (  # noqa: F401
    RULES,
    declared_knobs,
    iter_python_files,
    lint_paths,
    lint_sources,
)
from .sanitizer import (  # noqa: F401
    CollectiveDivergenceError,
    Sanitizer,
)
