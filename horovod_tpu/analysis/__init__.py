"""Static + runtime collective-correctness analysis.

Three legs of one story — catching "ranks disagree on which collective
runs next" *before* it becomes a hang:

* **hvd_lint** (findings.py / collective_api.py / visitor.py / rules.py /
  cli.py): an AST pass over training code modelling the repo's collective
  API surface.  Rule catalogue in rules.RULES (HVD001–HVD008 plus the
  HVD016 ppermute-bijection check), user docs in docs/analysis.md, CLI
  at scripts/hvd_lint.py.
* **hvd_verify** (schedule/): the interprocedural schedule model checker
  — call graph + bounded per-rank path enumeration + pairwise per-group
  sequence compatibility over world/local/cross/process-set and
  ``axis:<name>`` mesh-axis groups, with point-to-point (ppermute)
  schedules first-class, emitting counterexample traces (HVD009–HVD015,
  schedule.SCHEDULE_RULES).  CLI at scripts/hvd_verify.py, also
  reachable as ``hvd_lint --model-check``.
* **the collective sanitizer** (sanitizer.py): ``HVD_SANITIZER=1`` makes
  every eager dispatch fingerprint itself — group- and membership-epoch-
  aware, vector-clock ordered, permutation identity included for
  point-to-point ops — and cross-check against its group peers through
  the rendezvous KV store, raising a diagnostic that names the
  diverging rank and both signatures instead of deadlocking.
"""

from .findings import (  # noqa: F401
    Finding,
    Suppressions,
    render_json,
    render_text,
)
from .rules import (  # noqa: F401
    RULES,
    declared_knobs,
    iter_python_files,
    lint_paths,
    lint_sources,
)
from .sanitizer import (  # noqa: F401
    CollectiveDivergenceError,
    OrderIndex,
    Sanitizer,
)
from .schedule import (  # noqa: F401
    SCHEDULE_RULES,
    check_paths,
    check_sources,
)

#: the full user-facing rule catalogue (linter + model checker)
ALL_RULES = {**RULES, **SCHEDULE_RULES}
