"""Peer-replicated state plane: async snapshots + restore-from-peers.

Every recovery before this module funneled through the synchronous
orbax-to-storage path in utils/checkpoint.py — correct, but at
thousand-rank worlds the cold-storage round trip is the availability
bottleneck (ROADMAP: "State plane at production scale").  This module
layers a **peer checkpoint tier** over that storage path so a failure
costs one async snapshot interval, not a storage restore:

* **Asynchronous snapshot** — ``snapshot(state, step)`` is the step-path
  call and costs microseconds: it parks a reference in a depth-one
  latest-wins slot (the trailing-fetch discipline of
  training.TrailingLossFetcher / data.loader.prefetch_to_device: the
  device→host copy happens N calls behind, never on the dispatch path).
  A daemon thread does the ``jax.device_get`` + pickle + sharding +
  CRC32 content checksums + peer upload.  The orbax storage save is
  demoted to a slower cadence (``HVD_SNAPSHOT_STORAGE_EVERY``) as the
  durable backstop — elastic/state.py owns that demotion.
* **K-peer replication** — each rank's shards are pushed to
  ``HVD_PEER_REPLICAS`` peer *hosts* (prefer cross-host, same-DCN-tier:
  placement rides the host labels the PR 13 relay tree publishes and
  the ``TopologySpec`` local/cross split).  Every worker runs a small
  shard server (a plain :class:`~horovod_tpu.run.http_server.
  RendezvousServer` — same HMAC surface, same retrying client) and
  registers its endpoint under ``peerstate/addr.<worker>`` on the
  central rendezvous.
* **Generations + commit markers** — a snapshot generation is its step
  number.  Each rank writes ``manifest.<gen>.<rank>`` (shard sizes,
  checksums, replica placement) and then — only after every shard is
  pushed — the PR 5-style commit marker ``commit.<gen>.<rank>``.  Both
  live in the journaled ``peerstate`` scope, so the PR 13 warm-standby
  / epoch-fencing machinery is the consistency story.  A generation is
  restorable iff every rank of its world committed; GC **clears the
  commit marker first**, then deletes shards — the cleared-before-
  overwrite invariant, kept on the peer tier.
* **Restore-from-peers** — :meth:`PeerSnapshotManager.restore` resolves
  the newest fully-committed generation, pulls this rank's shards from
  live peers over HTTP (retry/backoff from run/http_client), verifies
  checksums, and returns ``None`` when any shard is unrecoverable —
  the caller (ElasticState.resume) then falls back wholesale to the
  storage tier.  Fault seams: ``kind=corrupt`` at ``seam=peer_push``
  flips shard bytes in flight; ``seam=peer_pull`` models a peer dying
  mid-restore (elastic/faults.py).
* **Elastic redistribution** — a joining rank pulls its shards from
  peers through the same restore path (no file listing), and
  :meth:`reprotect` re-pushes shards whose replicas left the world so
  K-redundancy is restored at the next stable epoch
  (membership epoch hooks call :func:`on_epoch`).

Flight recorder: ``snapshot.begin`` / ``snapshot.commit`` and
``restore.source`` (payload ``source=peer|storage``) chain onto the
abort/epoch chain via the epoch record's embedded event ids.  Metrics:
the ``hvd_snapshot_*`` family.  Knobs: ``HVD_SNAPSHOT`` /
``HVD_SNAPSHOT_SHARDS`` / ``HVD_SNAPSHOT_KEEP`` /
``HVD_SNAPSHOT_STORAGE_EVERY`` / ``HVD_SNAPSHOT_TIMEOUT_SECONDS`` /
``HVD_SNAPSHOT_COPY`` / ``HVD_PEER_REPLICAS``
(docs/fault_tolerance.md#the-peer-state-plane).
"""

from __future__ import annotations

import json
import pickle
import socket
import threading
import time
import urllib.error
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils import env as env_util
from ..utils.logging import get_logger
from . import faults

log = get_logger(__name__)

#: shard keys on a peer's shard server: ``<gen>.<src_rank>.<idx>``
SHARD_SCOPE = "shard"


def enabled() -> bool:
    """True when the peer tier is on (``HVD_SNAPSHOT=1``) and at least
    one replica is asked for."""
    return env_util.get_bool(env_util.HVD_SNAPSHOT) and replicas() > 0


def replicas() -> int:
    return env_util.get_int(env_util.HVD_PEER_REPLICAS,
                            env_util.DEFAULT_PEER_REPLICAS)


def checksum(data: bytes) -> str:
    """Content checksum of one shard (CRC32 — integrity against torn or
    bit-flipped transfers, not an adversary; the HMAC transport covers
    tampering)."""
    return f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"


def shard_payload(payload: bytes, nshards: int) -> List[bytes]:
    """Split one serialized state blob into ``nshards`` contiguous
    pieces (the last carries the remainder; tiny states yield fewer,
    never empty, shards)."""
    nshards = max(int(nshards), 1)
    if not payload:
        return [b""]
    size = max((len(payload) + nshards - 1) // nshards, 1)
    return [payload[i:i + size] for i in range(0, len(payload), size)]


def _detach(state: Any, copy_arrays: bool) -> Any:
    """Detach an enqueued snapshot from later caller mutation.

    Containers (dict / list / tuple / namedtuple) are rebuilt, so an
    in-place container update (``state["step"] = ...``) between the
    enqueue and the background serialize cannot tear the parked
    snapshot or advance it past its generation label.  Leaves are
    shared by default: ``jax.Array`` leaves are immutable and host
    leaves ride the JAX functional-update contract (replace, don't
    mutate).  ``copy_arrays`` (``HVD_SNAPSHOT_COPY=1``) additionally
    copies numpy ndarray leaves — a bounded host memcpy per enqueue —
    for training loops that DO mutate arrays in place."""
    if isinstance(state, dict):
        return {k: _detach(v, copy_arrays) for k, v in state.items()}
    if isinstance(state, (list, tuple)):
        vals = [_detach(v, copy_arrays) for v in state]
        if isinstance(state, list):
            return vals
        if hasattr(state, "_fields"):  # namedtuple
            return type(state)(*vals)
        return tuple(vals)
    if copy_arrays:
        try:
            import numpy as np

            if isinstance(state, np.ndarray):
                return np.array(state, copy=True)
        except Exception:  # noqa: BLE001 — a leaf that cannot be
            pass           # copied is shared, same as the default
    return state


def choose_peers(me: str, addrs: Dict[str, dict], k: int,
                 local_size: Optional[int] = None) -> List[str]:
    """Pick ``k`` replica holders for ``me`` from the registered shard
    servers, topology-aware: cross-host peers first (a host loss must
    not take a shard and all its replicas), ring-offset within each
    preference class so placement is deterministic and spread.  When
    host labels cannot separate workers (single-host tests, or a
    ``local_size`` covering the world — one ICI domain, everything is
    the same DCN tier per ``TopologySpec``), any peer qualifies."""
    workers = sorted(w for w in addrs if w != me)
    if not workers or k <= 0:
        return []
    my_host = (addrs.get(me) or {}).get("host")
    ordered = sorted(addrs)
    base = ordered.index(me) if me in ordered else 0
    # ring order starting just past me, so consecutive ranks spread
    # their replicas instead of all hammering worker 0
    ring = sorted(workers,
                  key=lambda w: (ordered.index(w) - base) % len(ordered))
    ls = local_size if local_size is not None else env_util.get_int(
        env_util.HVD_LOCAL_SIZE, 1)
    one_domain = ls >= len(addrs)  # whole world shares one ICI domain
    cross = [w for w in ring
             if one_domain or my_host is None
             or (addrs.get(w) or {}).get("host") != my_host]
    same = [w for w in ring if w not in cross]
    return (cross + same)[:min(k, len(workers))]


def _flight_event(kind: str, payload: dict, severity: str = "info",
                  cause_id: Optional[str] = None,
                  correlation_id: Optional[str] = None) -> Optional[str]:
    """Best-effort flight-recorder emit — telemetry must never take
    down a snapshot or restore."""
    try:
        from ..observe import events as events_mod

        return events_mod.record_event(
            kind, severity=severity, payload=payload, cause_id=cause_id,
            correlation_id=correlation_id,
            rank=env_util.get_int(env_util.HVD_PROCESS_ID, 0))
    except Exception:  # noqa: BLE001
        return None


def _metric(name: str, *labels, n: float = 1, set_value: bool = False):
    try:
        from .. import metrics

        if not metrics.on():
            return
        fam = getattr(metrics, name)
        inst = fam.labels(*labels) if labels else fam
        if set_value:
            inst.set(n)
        else:
            inst.inc(n)
    except Exception:  # noqa: BLE001
        pass


def _epoch_chain() -> Tuple[Optional[str], Optional[str]]:
    """(cause_id, correlation_id) of the current membership epoch record
    so restore events chain onto the abort/epoch incident across
    processes (observe/events.py)."""
    try:
        from . import membership

        rec = membership.current_record()
        if rec:
            return rec.get("event_id"), rec.get("correlation_id")
    except Exception:  # noqa: BLE001
        pass
    return None, None


class PeerSnapshotManager:
    """One rank's half of the peer state plane: the shard server it
    donates to its peers, the background snapshotter, and the
    restore/reprotect logic.

    The manager is wired at the same rendezvous the membership plane
    uses (``HVD_METRICS_KV_ADDR``/``PORT``/``HVD_METRICS_SECRET``);
    tests pass ``addr``/``port``/``secret`` explicitly."""

    def __init__(self, *, replicas_k: Optional[int] = None,
                 nshards: Optional[int] = None,
                 keep: Optional[int] = None,
                 addr: Optional[str] = None, port: Optional[int] = None,
                 secret: Optional[bytes] = None,
                 worker: Optional[str] = None,
                 rank: Optional[int] = None):
        self.k = int(replicas_k if replicas_k is not None else replicas())
        self.nshards = int(nshards if nshards is not None else
                           env_util.get_int(env_util.HVD_SNAPSHOT_SHARDS,
                                            env_util.DEFAULT_SNAPSHOT_SHARDS))
        self.keep = max(int(keep if keep is not None else env_util.get_int(
            env_util.HVD_SNAPSHOT_KEEP, env_util.DEFAULT_SNAPSHOT_KEEP)), 1)
        self.timeout = env_util.get_float(
            env_util.HVD_SNAPSHOT_TIMEOUT_SECONDS,
            env_util.DEFAULT_SNAPSHOT_TIMEOUT_SECONDS)
        self.copy_arrays = env_util.get_bool(env_util.HVD_SNAPSHOT_COPY)
        if addr is None or port is None:
            from .abort import _rendezvous_from_env

            wired = _rendezvous_from_env()
            if wired is None:
                raise RuntimeError(
                    "peer state plane needs the launcher rendezvous wiring "
                    "(HVD_METRICS_KV_ADDR/PORT) or explicit addr/port")
            addr, port, secret = wired
        self.addr, self.port, self.secret = addr, int(port), secret
        if worker is None:
            from . import membership

            worker = membership.worker_id()
        self.worker = str(worker)
        self._rank = rank
        # own shard server (donated host memory peers replicate into)
        self.server = None
        self._server_port: Optional[int] = None
        # local shard cache: gen -> [(key, bytes)] — what reprotect
        # re-pushes without re-serializing (survivors only; a restarted
        # process has no cache and simply snapshots again)
        self._local: Dict[int, List[Tuple[str, bytes]]] = {}
        self._my_gens: List[int] = []   # own committed gens, oldest first
        # latest-wins snapshot slot + the daemon that drains it
        self._slot: Optional[Tuple[Any, int]] = None
        self._slot_lock = threading.Lock()
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_stall_us: float = 0.0
        self.last_failure: Optional[str] = None
        self.snapshots = 0
        self.failures = 0

    # -- rank / wiring -----------------------------------------------------
    @property
    def rank(self) -> int:
        if self._rank is not None:
            return self._rank
        return env_util.get_int(env_util.HVD_PROCESS_ID, 0)

    def start(self) -> int:
        """Start the shard server and register its endpoint under
        ``peerstate/addr.<worker>``.  Idempotent."""
        from ..run.http_client import put_kv
        from ..run.http_server import (PEER_ADDR_PREFIX, PEERSTATE_SCOPE,
                                       RendezvousServer)

        if self.server is None:
            self.server = RendezvousServer(secret=self.secret)
            self._server_port = self.server.start()
        record = {"worker": self.worker, "host": self._host_label(),
                  "addr": self._advertise_addr(),
                  "port": self._server_port, "time": time.time()}
        put_kv(self.addr, self.port, PEERSTATE_SCOPE,
               f"{PEER_ADDR_PREFIX}{self.worker}",
               json.dumps(record).encode(), secret=self.secret, retry=True)
        return self._server_port

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.server is not None:
            self.server.stop()
            self.server = None

    def _host_label(self) -> str:
        """The placement label peers are spread across — the relay
        tree's host slug, so the peer tier and the aggregation tree
        agree on what 'one host' means."""
        try:
            from ..run.relay import host_slug

            return host_slug()
        except Exception:  # noqa: BLE001
            return socket.gethostname() or "localhost"

    def _advertise_addr(self) -> str:
        """The address peers dial for this worker's shard server."""
        addr = env_util.get_str(env_util.HVD_RING_HOST)
        if addr:
            return addr
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((self.addr, self.port or 1))
                return s.getsockname()[0]
            finally:
                s.close()
        except OSError:
            return "127.0.0.1"

    def _addr_table(self) -> Dict[str, dict]:
        """Registered shard-server endpoints (``addr.<worker>``)."""
        from ..run.http_client import get_scope
        from ..run.http_server import PEER_ADDR_PREFIX, PEERSTATE_SCOPE

        out: Dict[str, dict] = {}
        try:
            res = get_scope(self.addr, self.port, PEERSTATE_SCOPE,
                            secret=self.secret)
        except (urllib.error.URLError, OSError) as e:
            log.debug("peerstate addr table read failed: %s", e)
            return out
        for key, raw in res.get("entries", {}).items():
            if not key.startswith(PEER_ADDR_PREFIX):
                continue
            try:
                out[key[len(PEER_ADDR_PREFIX):]] = json.loads(raw)
            except (ValueError, TypeError):
                continue
        return out

    def _live_world(self, addrs: Dict[str, dict]) -> Dict[str, dict]:
        """Peer candidates: registered endpoints restricted to the
        committed membership world when one exists (a removed worker's
        stale registration must not hold replicas)."""
        try:
            from . import membership

            rec = membership.current_record()
            if rec and rec.get("world"):
                world = set(rec["world"])
                world.add(self.worker)
                return {w: a for w, a in addrs.items() if w in world}
        except Exception:  # noqa: BLE001
            pass
        return addrs

    # -- the step-path call ------------------------------------------------
    def snapshot(self, state: Any, step: int) -> float:
        """Enqueue an async snapshot of ``state`` as generation
        ``step``.  This is the ONLY thing the step path pays: a
        container rebuild (so later in-place dict/list updates cannot
        reach the parked snapshot — see :func:`_detach`; numpy leaves
        are also copied under ``HVD_SNAPSHOT_COPY=1``) plus a slot
        write + event set (µs — pinned under 1% of a 1 ms step in
        tier-1).  Latest-wins: a slow upload skips intermediate
        generations rather than queueing them."""
        t0 = time.perf_counter()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._drain_loop, daemon=True, name="hvd-snapshot")
            self._thread.start()
        item = (_detach(state, self.copy_arrays), int(step))
        with self._slot_lock:
            # _idle transitions pair with the slot under one lock, so
            # the drain loop's idle re-check can never race a fresh
            # enqueue into a stale "drained" verdict
            self._slot = item
            self._idle.clear()
        self._wake.set()
        stall = time.perf_counter() - t0
        self.last_stall_us = stall * 1e6
        _metric("SNAPSHOT_STALL_US", n=self.last_stall_us, set_value=True)
        return stall

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the background snapshotter is idle (tests,
        bench, clean shutdown).  True when it drained in time."""
        return self._idle.wait(timeout)

    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait()
            if self._stop.is_set():
                return
            self._wake.clear()
            while True:
                with self._slot_lock:
                    item, self._slot = self._slot, None
                if item is None:
                    break
                state, step = item
                try:
                    self.snapshot_sync(state, step)
                except Exception as e:  # noqa: BLE001 — the snapshotter
                    # must never take down training; the storage tier
                    # remains the durable backstop
                    self.failures += 1
                    self.last_failure = f"{type(e).__name__}: {e}"
                    _metric("SNAPSHOT_FAILURES")
                    log.warning("async snapshot of step %s failed: %s",
                                step, self.last_failure)
            with self._slot_lock:
                if self._slot is None:
                    self._idle.set()

    # -- the snapshot body (also callable synchronously in tests) ----------
    def snapshot_sync(self, state: Any, step: int) -> dict:
        """Serialize ``state``, push shards to K peers, write manifest
        then commit marker for generation ``step``.  Returns the
        manifest."""
        from ..run.http_client import push_shard, put_kv
        from ..run.http_server import (PEERSTATE_SCOPE,
                                       SNAPSHOT_COMMIT_PREFIX,
                                       SNAPSHOT_MANIFEST_PREFIX)

        gen = int(step)
        begin_eid = _flight_event("snapshot.begin",
                                  {"gen": gen, "rank": self.rank,
                                   "worker": self.worker})
        t0 = time.perf_counter()
        try:
            import jax

            state = jax.device_get(state)
        except Exception:  # noqa: BLE001 — plain host pytrees (tests,
            pass           # bench fixtures) serialize as they are
        payload = pickle.dumps(state)
        shards = shard_payload(payload, self.nshards)
        addrs = self._live_world(self._addr_table())
        peers = choose_peers(self.worker, addrs, self.k)
        if not peers:
            raise RuntimeError(
                f"no peer shard servers registered (worker {self.worker}; "
                "did peers call PeerSnapshotManager.start()?)")
        manifest: dict = {"gen": gen, "step": gen, "rank": self.rank,
                          "worker": self.worker,
                          "world_size": self._world_size(addrs),
                          "shards": [], "time": time.time()}
        local: List[Tuple[str, bytes]] = []
        for idx, data in enumerate(shards):
            key = f"{gen}.{self.rank}.{idx}"
            crc = checksum(data)
            wire = faults.on_peer_push(data)  # kind=corrupt flips bytes
            for peer in peers:
                rec = addrs.get(peer) or {}
                push_shard(rec.get("addr", "127.0.0.1"),
                           int(rec.get("port", 0)), key, wire,
                           secret=self.secret, timeout=self.timeout)
            manifest["shards"].append({"idx": idx, "bytes": len(data),
                                       "crc": crc, "peers": list(peers)})
            local.append((key, data))
        put_kv(self.addr, self.port, PEERSTATE_SCOPE,
               f"{SNAPSHOT_MANIFEST_PREFIX}{gen}.{self.rank}",
               json.dumps(manifest).encode(), secret=self.secret, retry=True)
        # PR 5 commit semantics: the marker is written ONLY after every
        # shard landed — a rank that dies mid-upload leaves gen
        # uncommitted and restore skips it
        put_kv(self.addr, self.port, PEERSTATE_SCOPE,
               f"{SNAPSHOT_COMMIT_PREFIX}{gen}.{self.rank}",
               json.dumps({"gen": gen, "worker": self.worker,
                           "time": time.time()}).encode(),
               secret=self.secret, retry=True)
        self._local[gen] = local
        self._my_gens.append(gen)
        self.snapshots += 1
        self.last_failure = None
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        _metric("SNAPSHOTS_TOTAL")
        _metric("SNAPSHOT_BYTES", n=len(payload))
        _metric("SNAPSHOT_GEN", n=gen, set_value=True)
        _flight_event("snapshot.commit",
                      {"gen": gen, "rank": self.rank, "bytes": len(payload),
                       "shards": len(shards), "peers": peers,
                       "upload_ms": round(elapsed_ms, 3)},
                      cause_id=begin_eid)
        self._gc()
        return manifest

    def _world_size(self, addrs: Dict[str, dict]) -> int:
        try:
            from . import membership

            rec = membership.current_record()
            if rec and rec.get("world"):
                return len(rec["world"])
        except Exception:  # noqa: BLE001
            pass
        n = env_util.get_int(env_util.HVD_NUM_PROCESSES, 0)
        return n if n > 0 else max(len(addrs), 1)

    def _gc(self) -> None:
        """Retire own generations beyond ``keep``, cleared-before-
        overwrite: the commit marker goes FIRST (the generation stops
        being restorable), then the replicated shards, then the
        manifest — a crash mid-GC can never leave a committed
        generation with missing shards."""
        from ..run.http_client import delete_kv
        from ..run.http_server import (PEERSTATE_SCOPE,
                                       SNAPSHOT_COMMIT_PREFIX,
                                       SNAPSHOT_MANIFEST_PREFIX, SHARD_SCOPE
                                       as SERVER_SHARD_SCOPE)

        while len(self._my_gens) > self.keep:
            gen = self._my_gens.pop(0)
            try:
                delete_kv(self.addr, self.port, PEERSTATE_SCOPE,
                          f"{SNAPSHOT_COMMIT_PREFIX}{gen}.{self.rank}",
                          secret=self.secret)
                addrs = self._addr_table()
                for key, _ in self._local.get(gen, ()):  # then the shards
                    for peer, rec in addrs.items():
                        if peer == self.worker:
                            continue
                        try:
                            delete_kv(rec.get("addr", "127.0.0.1"),
                                      int(rec.get("port", 0)),
                                      SERVER_SHARD_SCOPE, key,
                                      secret=self.secret)
                        except (urllib.error.URLError, OSError):
                            pass  # a dead peer's copies die with it
                delete_kv(self.addr, self.port, PEERSTATE_SCOPE,
                          f"{SNAPSHOT_MANIFEST_PREFIX}{gen}.{self.rank}",
                          secret=self.secret)
            except (urllib.error.URLError, OSError) as e:
                log.debug("snapshot GC of gen %s failed: %s", gen, e)
            self._local.pop(gen, None)

    # -- restore -----------------------------------------------------------
    def _manifests(self) -> Dict[int, Dict[int, dict]]:
        """``{gen: {rank: manifest}}`` from the rendezvous, plus commit
        markers folded in as ``manifest['_committed']``."""
        from ..run.http_client import get_scope
        from ..run.http_server import (PEERSTATE_SCOPE,
                                       SNAPSHOT_COMMIT_PREFIX,
                                       SNAPSHOT_MANIFEST_PREFIX)

        res = get_scope(self.addr, self.port, PEERSTATE_SCOPE,
                        secret=self.secret)
        gens: Dict[int, Dict[int, dict]] = {}
        committed: set = set()
        for key, raw in res.get("entries", {}).items():
            if key.startswith(SNAPSHOT_MANIFEST_PREFIX):
                gen_s, _, rank_s = \
                    key[len(SNAPSHOT_MANIFEST_PREFIX):].partition(".")
                if not (gen_s.isdigit() and rank_s.isdigit()):
                    continue
                try:
                    gens.setdefault(int(gen_s), {})[int(rank_s)] = \
                        json.loads(raw)
                except (ValueError, TypeError):
                    continue
            elif key.startswith(SNAPSHOT_COMMIT_PREFIX):
                gen_s, _, rank_s = \
                    key[len(SNAPSHOT_COMMIT_PREFIX):].partition(".")
                if gen_s.isdigit() and rank_s.isdigit():
                    committed.add((int(gen_s), int(rank_s)))
        for gen, by_rank in gens.items():
            for rank, m in by_rank.items():
                m["_committed"] = (gen, rank) in committed
        return gens

    def resolve_committed(self) -> Optional[int]:
        """Newest generation whose EVERY rank wrote both manifest and
        commit marker — the only generations restore may target
        (uncommitted newest generations are skipped, the peer-tier
        analog of ``latest_step`` ignoring torn ``step_N`` dirs)."""
        try:
            gens = self._manifests()
        except (urllib.error.URLError, OSError) as e:
            self.last_failure = f"manifest read failed: {e}"
            return None
        for gen in sorted(gens, reverse=True):
            by_rank = gens[gen]
            if 0 not in by_rank:
                continue
            # the world this gen must cover is the LARGEST any of its
            # manifests recorded — rank 0's view alone can be stale
            # across a concurrent grow (ranks >= its world_size
            # committed the same gen with a larger world), and trusting
            # it would deem the gen whole with those ranks unchecked
            world = max((int(m.get("world_size") or 0)
                         for m in by_rank.values()), default=0) \
                or len(by_rank)
            if all(r in by_rank and by_rank[r].get("_committed")
                   for r in range(world)):
                return gen
        return None

    def restore(self, like: Any = None, *, gen: Optional[int] = None,
                rank: Optional[int] = None
                ) -> Optional[Tuple[Any, int]]:
        """Pull this rank's shards of the newest fully-committed
        generation from live peers, verify checksums, and return
        ``(state, step)`` — or ``None`` when no generation is
        restorable or any shard is unrecoverable (every replica dead or
        corrupt); the caller then falls back wholesale to the storage
        tier.  Per-shard, each replica is tried in manifest order
        before the shard is declared lost."""
        from ..run.http_client import pull_shard

        rank = self.rank if rank is None else int(rank)
        if gen is None:
            gen = self.resolve_committed()
        if gen is None:
            self.last_failure = self.last_failure or \
                "no fully-committed generation"
            return None
        try:
            manifest = self._manifests().get(gen, {}).get(rank)
        except (urllib.error.URLError, OSError) as e:
            self.last_failure = f"manifest read failed: {e}"
            return None
        if manifest is None:
            self.last_failure = (f"gen {gen} has no manifest for rank "
                                 f"{rank} (world resized?)")
            return None
        addrs = self._addr_table()
        pieces: List[bytes] = []
        for shard in manifest.get("shards", ()):
            key = f"{gen}.{rank}.{shard['idx']}"
            data = None
            for peer in shard.get("peers", ()):
                rec = addrs.get(peer)
                if rec is None:
                    continue
                try:
                    faults.on_peer_pull(key)  # peer-death-mid-restore seam
                    raw = pull_shard(rec.get("addr", "127.0.0.1"),
                                     int(rec.get("port", 0)), key,
                                     secret=self.secret,
                                     timeout=self.timeout)
                except (urllib.error.URLError, OSError) as e:
                    log.warning("shard %s pull from peer %s failed: %s",
                                key, peer, e)
                    continue
                if raw is None:
                    continue
                if checksum(raw) != shard.get("crc"):
                    log.warning("shard %s from peer %s failed its "
                                "checksum (corrupt replica)", key, peer)
                    continue
                data = raw
                break
            if data is None:
                self.last_failure = (f"shard {key}: no live peer holds an "
                                     "intact replica")
                log.warning("peer restore of gen %s abandoned: %s",
                            gen, self.last_failure)
                return None
            pieces.append(data)
        state = pickle.loads(b"".join(pieces))
        self.last_failure = None
        return state, int(manifest.get("step", gen))

    # -- elastic redistribution --------------------------------------------
    def reprotect(self) -> int:
        """Restore K-redundancy after a shrink: re-push shards of this
        rank's newest committed generation whose recorded replicas left
        the world, and rewrite the manifest.  Returns shards re-pushed
        (0 when redundancy is intact or there is no local cache — a
        restarted process simply snapshots again)."""
        from ..run.http_client import push_shard, put_kv
        from ..run.http_server import (PEERSTATE_SCOPE,
                                       SNAPSHOT_MANIFEST_PREFIX)

        if not self._my_gens:
            return 0
        gen = self._my_gens[-1]
        local = dict(self._local.get(gen, ()))
        if not local:
            return 0
        try:
            manifest = self._manifests().get(gen, {}).get(self.rank)
        except (urllib.error.URLError, OSError):
            return 0
        if manifest is None:
            return 0
        addrs = self._live_world(self._addr_table())
        live = set(addrs)
        repushed = 0
        changed = False
        short: List[str] = []
        for shard in manifest.get("shards", ()):
            holders = [p for p in shard.get("peers", ()) if p in live]
            if holders != list(shard.get("peers", ())):
                changed = True  # prune dead holders from the manifest
            lost = self.k - len(holders)
            if lost <= 0:
                shard["peers"] = holders
                continue
            key = f"{gen}.{self.rank}.{shard['idx']}"
            data = local.get(key)
            if data is None:
                shard["peers"] = holders
                short.append(key)
                continue
            # candidate pool: the live world with surviving holders
            # excluded UP FRONT — filtering choose_peers' ring prefix
            # after the fact can return fewer than `lost` fresh peers
            # when host labels shifted across the shrink
            pool = {w: a for w, a in addrs.items()
                    if w == self.worker or w not in holders}
            for peer in choose_peers(self.worker, pool, lost):
                rec = addrs.get(peer) or {}
                try:
                    push_shard(rec.get("addr", "127.0.0.1"),
                               int(rec.get("port", 0)), key, data,
                               secret=self.secret, timeout=self.timeout)
                except (urllib.error.URLError, OSError) as e:
                    log.warning("reprotect push of %s to %s failed: %s",
                                key, peer, e)
                    continue
                holders.append(peer)
                repushed += 1
                changed = True
            shard["peers"] = holders
            if len(holders) < self.k:
                short.append(key)
        if short:
            # partial reprotection is NOT silent: redundancy stays
            # below K until more peers join (the next epoch hook
            # retries) — the storage tier remains the durable backstop
            log.warning("reprotect of gen %s left %d shard(s) under-"
                        "replicated (< %d replicas): %s — not enough "
                        "live peers outside the surviving holders",
                        gen, len(short), self.k, ", ".join(short))
        if changed:
            put_kv(self.addr, self.port, PEERSTATE_SCOPE,
                   f"{SNAPSHOT_MANIFEST_PREFIX}{gen}.{self.rank}",
                   json.dumps({k: v for k, v in manifest.items()
                               if k != "_committed"}).encode(),
                   secret=self.secret, retry=True)
        if changed or short:
            _metric("SNAPSHOT_REPROTECTED", n=repushed)
            _flight_event("snapshot.reprotect",
                          {"gen": gen, "rank": self.rank,
                           "shards": repushed,
                           "under_replicated": len(short)},
                          severity="warning")
        return repushed

    def on_epoch(self, rec: Optional[dict] = None) -> None:
        """Membership epoch hook (membership.run / join_world): the
        world changed — re-register this worker's endpoint (the rank
        may have moved) and restore replica redundancy."""
        try:
            self.start()
            self.reprotect()
        except Exception as e:  # noqa: BLE001 — the hook must not fail
            log.warning("peerstate epoch hook failed: %s", e)  # a rebuild


# ---------------------------------------------------------------------------
# process-wide wiring (ElasticState + membership epoch hooks)
# ---------------------------------------------------------------------------
_instance: Optional[PeerSnapshotManager] = None
_lock = threading.Lock()


def manager(start: bool = True) -> PeerSnapshotManager:
    """The process-wide manager, built from env on first use (and its
    shard server started so this worker donates replica space even
    before its first snapshot)."""
    global _instance
    with _lock:
        if _instance is None:
            _instance = PeerSnapshotManager()
            if start:
                _instance.start()
        return _instance


def instance() -> Optional[PeerSnapshotManager]:
    return _instance


def on_epoch(rec: Optional[dict] = None) -> None:
    """Module-level epoch hook: no-op unless a manager exists."""
    m = _instance
    if m is not None:
        m.on_epoch(rec)


def reset() -> None:
    """Stop and drop the process manager (tests / shutdown)."""
    global _instance
    with _lock:
        if _instance is not None:
            _instance.stop()
            _instance = None
