"""Chaos campaign engine: scripted multi-fault scenarios, certified.

The fault seams grown across PRs 9-19 — `HVD_FAULT_SPEC` process
faults, lease-expiry detection, drain handshakes, relay failover, the
journaled warm-standby primary, the peer state plane — were each pinned
by unit tests that exercise ONE failure at a time.  This module turns
them into **scenarios**: timed, composed fault schedules executed
against a real elastic control plane (a live :class:`RendezvousServer`
plus a live :class:`ElasticDriver`, in process), with every recovery
promise machine-checked by the invariant monitors
(observe/invariants.py) over the flight-recorder event stream.

Three layers:

**Scenario DSL** — ``;``-separated timed entries, each
``:``-separated ``key=value`` fields (the `HVD_FAULT_SPEC` grammar,
plus a clock and control-plane targets)::

    at=250ms:rank=1:kind=crash; at=600ms:rank=2:kind=preempt=2s;
    at=900ms:target=primary:kind=kill=250ms

``target=worker`` (default) faults one worker: ``crash`` (process
exit, reaped like a child exit), ``hang`` (silent stop — lease expiry
must find it), ``partition`` (alive but unreachable), ``slow=<dur>``
(one step stretched), ``preempt[=<grace>]`` (a preemption notice the
driver must turn into a planned drain + snapshot, not a crash), and
``skew`` (test-only: corrupts the worker's restore bookkeeping so its
next lossy resume over-reports ``steps_lost`` — the deliberately
catchable violation the shrinker demos on).  ``target=primary:
kind=kill[=<outage>]`` kills the rendezvous primary and promotes a
journal-replay standby after the outage; ``target=relay:kind=kill``
kills the metrics relay (workers must fall back to the direct path
transparently).

**Campaign runner** — :func:`run_scenario` stands up the world,
injects the schedule from a side thread (so a fault can land while the
driver is blocked in a drain handshake), records outcomes, and hands
the evidence bundle to :func:`~..observe.invariants.check_all`.
:func:`generate_campaign` derives N scenarios from one integer seed
(``random.Random(seed)``, millisecond-rounded offsets) — the same seed
always renders the identical schedule, so every red campaign is
replayable.

**Shrinker** — :func:`ddmin` delta-debugs a failing scenario down to
the minimal fault subset that still trips an invariant; :func:`shrink`
wraps it with scenario re-execution and returns the minimal scenario
plus its violation report.

Modelling notes (why the runner is trustworthy evidence): workers are
threads speaking the real wire protocol — health leases, abort-flag
polling with the epoch filter, ready acks, the drain-request/ack
handshake — against the real server and driver, in lockstep (a soft
barrier over the current epoch roster, so a dead peer stalls survivors
exactly like a collective would until the abort propagates).  Aborts
are observed from a background tick independent of step latency,
mirroring the real heartbeat thread.  The snapshot plane commits every
``snapshot_every`` steps and pins one ``(gen, step)`` restore source
per epoch, so the steps-lost and source-agreement invariants check the
same arithmetic the peer state plane promises.  During a primary
outage the driver is not ticked and driver writes are assumed
retried — keep ``kill`` outages shorter than the drain budget (the
generator does).
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..observe import events as events_mod
from ..observe import invariants as invariants_mod
from ..run.http_server import (
    ABORT_KEY,
    ABORT_SCOPE,
    DRAIN_ACK_PREFIX,
    DRAIN_PREFIX,
    EPOCH_KEY,
    EVENTS_SCOPE,
    HEALTH_SCOPE,
    MEMBERSHIP_SCOPE,
    PREEMPT_PREFIX,
    READY_PREFIX,
    RendezvousServer,
)
from ..utils import env as env_util
from ..utils.logging import get_logger
from .driver import ElasticDriver
from .faults import FAULT_EXIT_CODE, parse_duration

log = get_logger(__name__)


class ChaosSpecError(ValueError):
    """Malformed scenario text (mirrors faults.FaultSpecError)."""


#: worker-targeted fault kinds (rank required)
WORKER_KINDS = ("crash", "hang", "partition", "slow", "preempt", "skew")
#: control-plane targets and their only kind
CONTROL_TARGETS = ("primary", "relay")
#: the reason suffix remove() appends on a completed drain handshake —
#: workers classify an epoch change as lossless by it
_DRAINED_MARK = "drained: in-flight work completed"


def _render_duration(seconds: float) -> str:
    ms = int(round(seconds * 1000))
    return f"{ms}ms"


@dataclass(frozen=True)
class ChaosEntry:
    """One timed fault: WHEN (``at``, seconds into the scenario), WHAT
    (``kind`` + optional ``duration`` argument), WHERE (``target`` and,
    for worker faults, the initial ``rank``)."""

    at: float
    kind: str
    target: str = "worker"
    rank: Optional[int] = None
    duration: float = 0.0

    def render(self) -> str:
        parts = [f"at={_render_duration(self.at)}"]
        if self.target != "worker":
            parts.append(f"target={self.target}")
        if self.rank is not None:
            parts.append(f"rank={self.rank}")
        kind = self.kind
        if self.duration:
            kind = f"{kind}={_render_duration(self.duration)}"
        parts.append(f"kind={kind}")
        return ":".join(parts)


@dataclass(frozen=True)
class Scenario:
    """A named, ordered fault schedule."""

    name: str
    entries: Tuple[ChaosEntry, ...]

    def render(self) -> str:
        """Canonical text — byte-identical across runs of the same
        seed (the determinism contract tests pin)."""
        ordered = sorted(self.entries,
                         key=lambda e: (e.at, e.target, e.kind,
                                        -1 if e.rank is None else e.rank))
        return "; ".join(e.render() for e in ordered)


def _parse_entry(text: str) -> ChaosEntry:
    at: Optional[float] = None
    kind: Optional[str] = None
    target = "worker"
    rank: Optional[int] = None
    duration = 0.0
    for part in text.strip().split(":"):
        if not part:
            continue
        key, sep, value = part.partition("=")
        key = key.strip()
        if not sep:
            raise ChaosSpecError(f"bad field {part!r} in {text!r} "
                                 "(want key=value)")
        if key == "at":
            at = parse_duration(value)
        elif key == "target":
            target = value.strip()
        elif key == "rank":
            try:
                rank = int(value)
            except ValueError:
                raise ChaosSpecError(f"bad rank {value!r} in {text!r}")
        elif key == "kind":
            kind, _, arg = value.partition("=")
            kind = kind.strip()
            if arg:
                duration = parse_duration(arg)
        else:
            raise ChaosSpecError(f"unknown field {key!r} in {text!r}")
    if at is None:
        raise ChaosSpecError(f"entry {text!r} has no at=<time>")
    if kind is None:
        raise ChaosSpecError(f"entry {text!r} has no kind=")
    if target == "worker":
        if kind not in WORKER_KINDS:
            raise ChaosSpecError(
                f"unknown worker fault kind {kind!r} in {text!r} "
                f"(want one of {', '.join(WORKER_KINDS)})")
        if rank is None:
            raise ChaosSpecError(f"worker fault {text!r} needs rank=")
        if kind == "slow" and duration <= 0:
            raise ChaosSpecError(f"slow fault {text!r} needs a "
                                 "duration (kind=slow=150ms)")
    elif target in CONTROL_TARGETS:
        if kind != "kill":
            raise ChaosSpecError(
                f"target={target} supports only kind=kill, got {kind!r}")
        if rank is not None:
            raise ChaosSpecError(
                f"target={target} entry {text!r} must not set rank=")
    else:
        raise ChaosSpecError(f"unknown target {target!r} in {text!r}")
    return ChaosEntry(at=at, kind=kind, target=target, rank=rank,
                      duration=duration)


def parse_scenario(text: str, name: str = "scenario") -> Scenario:
    """Parse the DSL text into a :class:`Scenario`; raises
    :class:`ChaosSpecError` with the offending entry on any error."""
    entries = []
    for chunk in text.split(";"):
        if chunk.strip():
            entries.append(_parse_entry(chunk))
    if not entries:
        raise ChaosSpecError("empty scenario")
    return Scenario(name=name, entries=tuple(
        sorted(entries, key=lambda e: e.at)))


# ---------------------------------------------------------------------------
# seeded campaign generation
# ---------------------------------------------------------------------------

def generate_campaign(seed: int, count: int = 8, world_size: int = 3,
                      min_np: int = 1) -> List[Scenario]:
    """Derive ``count`` scenarios from one integer seed.  Every draw
    comes from one ``random.Random(seed)`` in a fixed order and every
    offset is millisecond-rounded, so the same seed always renders the
    identical campaign (replay contract).  Coverage guarantees: every
    scenario composes >= 2 fault kinds, the campaign includes a
    ``preempt`` and a primary kill, and destructive faults never
    outnumber ``world_size - min_np`` in one scenario."""
    if world_size - min_np < 1:
        raise ChaosSpecError("campaign needs world_size - min_np >= 1 "
                             "(destructive faults must leave a quorum)")
    rng = random.Random(int(seed))
    destructive = ("crash", "hang", "partition", "preempt")
    scenarios: List[Scenario] = []
    for i in range(count):
        kinds: List[str] = []
        budget = world_size - min_np
        # rotate the special coverage through the campaign so every
        # 8-scenario window exercises preempt + both control-plane kills
        if i % 8 == 0:
            kinds.append("preempt")
            budget -= 1
        elif i % 8 == 1:
            kinds.append("primary-kill")
        elif i % 8 == 2:
            kinds.append("relay-kill")
        want = 2 + (1 if rng.random() < 0.4 else 0)
        pool = list(destructive) + ["slow"]
        guard = 0
        while len(kinds) < want and guard < 32:
            guard += 1
            k = pool[rng.randrange(len(pool))]
            if k in destructive and budget <= 0:
                k = "slow"
            if k in kinds and k != "slow":
                continue
            if k in destructive:
                budget -= 1
            kinds.append(k)
        if len(set(kinds)) < 2:  # e.g. slow+slow — force a composition
            kinds[-1] = "crash" if "crash" not in kinds else "partition"
        # distinct initial ranks for every worker fault
        avail = list(range(world_size))
        rng.shuffle(avail)
        t = round(0.15 + 0.2 * rng.random(), 3)
        entries: List[ChaosEntry] = []
        # primary kill goes first with a settle gap after the outage so
        # abort propagation is never measured across a dead primary
        ordered = sorted(kinds, key=lambda k: k != "primary-kill")
        for k in ordered:
            if k == "primary-kill":
                entries.append(ChaosEntry(at=t, kind="kill",
                                          target="primary", duration=0.25))
                t = round(t + 0.25 + 0.8, 3)
            elif k == "relay-kill":
                entries.append(ChaosEntry(at=t, kind="kill",
                                          target="relay"))
                t = round(t + 0.15, 3)
            elif k == "slow":
                entries.append(ChaosEntry(
                    at=t, kind="slow", rank=avail.pop(),
                    duration=round(0.05 + 0.1 * rng.random(), 3)))
                t = round(t + 0.1, 3)
            else:
                grace = 1.0 if (k == "preempt"
                                and rng.random() < 0.5) else 0.0
                entries.append(ChaosEntry(at=t, kind=k, rank=avail.pop(),
                                          duration=grace))
                t = round(t + 0.45 + round(0.3 * rng.random(), 3), 3)
        scenarios.append(Scenario(name=f"s{seed}-{i:02d}",
                                  entries=tuple(entries)))
    return scenarios


# ---------------------------------------------------------------------------
# the in-process world
# ---------------------------------------------------------------------------

class _SnapshotPlane:
    """The runner's stand-in for the peer state plane: one committed
    ``(gen, step)`` the fleet advances every ``snapshot_every`` steps,
    plus one pinned restore source per epoch (the collective-agreement
    rule from PR 19: first restorer pins, the rest reuse)."""

    def __init__(self, every: int):
        self.every = max(int(every), 1)
        self.gen = 0
        self.step = 0
        self._pins: Dict[int, Tuple[int, int]] = {}
        self._lock = threading.Lock()

    def commit(self, step: int, rank: Optional[int] = None,
               forced: bool = False) -> bool:
        with self._lock:
            if step > self.step or (forced and step >= self.step):
                self.gen += 1
                self.step = step
                events_mod.record_event(
                    "snapshot.commit",
                    payload={"gen": self.gen, "step": step,
                             "forced": forced},
                    rank=rank)
                return True
            return False

    def pin_restore(self, epoch: int) -> Tuple[int, int]:
        with self._lock:
            if epoch not in self._pins:
                self._pins[epoch] = (self.gen, self.step)
            return self._pins[epoch]


class _World:
    """Shared scenario state: the (swappable) primary server, the step
    counters the lockstep barrier reads, and the global stop flag."""

    def __init__(self, worker_ids: Sequence[str], *, hb_interval: float,
                 step_seconds: float, snapshot_every: int,
                 journal_path: Optional[str]):
        self.worker_ids = list(worker_ids)
        self.hb_interval = hb_interval
        self.step_seconds = step_seconds
        self.snapshot_every = snapshot_every
        self.journal_path = journal_path
        self.plane = _SnapshotPlane(snapshot_every)
        self.steps: Dict[str, int] = {w: 0 for w in self.worker_ids}
        self.stop = False
        self.relay_dead = False
        self.primary: Optional[RendezvousServer] = None
        self._plock = threading.Lock()

    # tolerant KV access: a dead primary reads as unreachable, not as a
    # crash of the caller (workers retry on their next tick)
    def kv_put(self, scope: str, key: str, obj: dict) -> bool:
        with self._plock:
            server = self.primary
        if server is None:
            return False
        try:
            server.put(scope, key, json.dumps(obj).encode())
            return True
        except Exception:  # noqa: BLE001
            return False

    def kv_get_json(self, scope: str, key: str) -> Optional[dict]:
        with self._plock:
            server = self.primary
        if server is None:
            return None
        try:
            raw = server.get(scope, key)
            return json.loads(raw) if raw is not None else None
        except Exception:  # noqa: BLE001
            return None


class _ChaosWorker(threading.Thread):
    """One roster member as a thread speaking the real wire protocol:
    health leases, abort-flag polling (epoch filter + event-id dedupe),
    ready acks, the drain handshake, lockstep stepping."""

    def __init__(self, world: _World, wid: str):
        super().__init__(daemon=True, name=f"chaos-worker-{wid}")
        self.world = world
        self.wid = wid
        self.rank: int = -1
        self.epoch: int = -1
        self.members: List[str] = []
        self.step = 0
        self.status = "running"
        # injection surface (written by the injector thread)
        self.fault: Optional[str] = None       # crash | hang | partition
        self.slow_pending = 0.0
        self.skewed = False
        self.preempt_expected = False
        self.draining = False
        self._relay_fallback = False
        self._last_hb = 0.0
        self._hb_count = 0
        self._seen_abort: set = set()
        self._pending_abort: Optional[dict] = None

    # -- wire protocol ----------------------------------------------------
    def _put(self, scope: str, key: str, obj: dict) -> bool:
        w = self.world
        if w.relay_dead and not self._relay_fallback:
            # the push in flight with the relay is lost exactly once;
            # the worker falls back to the direct path for good
            # (elastic/relay.py mark_relay_failed semantics)
            self._relay_fallback = True
            return False
        return w.kv_put(scope, key, obj)

    def _tick_background(self) -> None:
        """Lease renewal + abort observation — runs from every sleep
        chunk, independent of step latency, like the real heartbeat
        thread (a slow step must not delay abort observation)."""
        w = self.world
        now = time.monotonic()
        if now - self._last_hb >= w.hb_interval and self.rank >= 0:
            ok = self._put(HEALTH_SCOPE, str(self.rank),
                           {"rank": self.rank, "count": self._hb_count,
                            "interval": w.hb_interval, "pid": os.getpid()})
            if ok:
                self._last_hb = now
                self._hb_count += 1
        flag = w.kv_get_json(ABORT_SCOPE, ABORT_KEY)
        if flag:
            eid = flag.get("event_id") or f"t{flag.get('time')}"
            flag_epoch = flag.get("epoch")
            if eid not in self._seen_abort and (
                    flag_epoch is None or flag_epoch >= self.epoch):
                self._seen_abort.add(eid)
                events_mod.record_event(
                    "abort.observe", severity="warning",
                    payload={"epoch": flag_epoch, "worker": self.wid,
                             "reason": flag.get("reason")},
                    cause_id=flag.get("event_id"),
                    correlation_id=flag.get("correlation_id"),
                    rank=self.rank)
                self._pending_abort = flag

    def _observant_sleep(self, duration: float) -> None:
        end = time.monotonic() + duration
        while True:
            self._tick_background()
            rem = end - time.monotonic()
            if rem <= 0 or self.world.stop or self.fault is not None:
                return
            time.sleep(min(0.02, rem))

    def _ack_ready(self) -> None:
        self._put(MEMBERSHIP_SCOPE,
                  f"{READY_PREFIX}{self.epoch}.{self.wid}",
                  {"worker": self.wid, "time": time.time()})
        self._last_hb = 0.0  # re-establish the lease the commit cleared

    def _wait_epoch(self, after: int,
                    timeout: float = 3.0) -> Optional[dict]:
        w = self.world
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and not w.stop:
            rec = w.kv_get_json(MEMBERSHIP_SCOPE, EPOCH_KEY)
            if rec is not None and int(rec.get("epoch", -1)) > after:
                return rec
            time.sleep(0.008)
        return None

    def _check_drain(self) -> None:
        w = self.world
        req = w.kv_get_json(MEMBERSHIP_SCOPE, f"{DRAIN_PREFIX}{self.wid}")
        if req is None:
            return
        self.draining = True
        # the planned departure snapshot: nothing this worker computed
        # is lost (the preempt -> 0 steps promise)
        w.plane.commit(self.step, rank=self.rank, forced=True)
        self._put(MEMBERSHIP_SCOPE, f"{DRAIN_ACK_PREFIX}{self.wid}",
                  {"worker": self.wid, "epoch": req.get("epoch"),
                   "step": self.step, "time": time.time()})

    def _rebuild(self, flag: dict) -> bool:
        """React to an observed abort: wait for the next epoch, roll
        back to the pinned snapshot if the change was lossy, resume (or
        exit, if this worker is no longer in the world)."""
        w = self.world
        if flag.get("epoch") is None:
            self.status = "aborted"  # job-level give-up, no next epoch
            return False
        rec = self._wait_epoch(self.epoch)
        if rec is None:
            if not w.stop:
                self.status = "stuck"
                return False
            return True
        if self.wid not in rec.get("world", []):
            self.status = "removed"
            return False
        removed = rec.get("removed") or []
        reason = rec.get("reason") or ""
        lossy = bool(removed) and _DRAINED_MARK not in reason
        self.epoch = int(rec["epoch"])
        self.members = list(rec["world"])
        self.rank = self.members.index(self.wid)
        lost = 0
        if lossy:
            gen, rstep = w.plane.pin_restore(self.epoch)
            lost = max(0, self.step - rstep)
            if self.step > rstep:
                self.step = rstep
                w.steps[self.wid] = self.step
            events_mod.record_event(
                "restore.source",
                payload={"epoch": self.epoch, "gen": gen, "step": rstep,
                         "worker": self.wid, "source": "peer"},
                cause_id=rec.get("event_id"),
                correlation_id=rec.get("correlation_id"), rank=self.rank)
            if self.skewed:
                # the injected bookkeeping corruption (kind=skew): the
                # reported loss no longer matches the snapshot cadence
                lost += w.snapshot_every * 3
        events_mod.record_event(
            "restart.resume",
            payload={"epoch": self.epoch, "steps_lost": lost,
                     "worker": self.wid},
            cause_id=rec.get("event_id"),
            correlation_id=rec.get("correlation_id"), rank=self.rank)
        self._ack_ready()
        return True

    def _can_step(self) -> bool:
        w = self.world
        return all(w.steps.get(m, 0) >= self.step
                   for m in self.members if m != self.wid)

    # -- the life of a worker ---------------------------------------------
    def run(self) -> None:  # noqa: D102
        try:
            self._run()
        except Exception:  # noqa: BLE001
            log.exception("chaos worker %s died unexpectedly", self.wid)
            self.status = "error"

    def _run(self) -> None:
        w = self.world
        rec = self._wait_epoch(-1)
        if rec is None:
            self.status = "stuck"
            return
        self.epoch = int(rec["epoch"])
        self.members = list(rec.get("world", []))
        if self.wid not in self.members:
            self.status = "removed"
            return
        self.rank = self.members.index(self.wid)
        self._ack_ready()
        while True:
            if w.stop:
                if self.status == "running":
                    self.status = "finished"
                return
            if self.fault in ("crash", "hang"):
                # the thread just stops: a crash is reaped by the
                # runner's child-exit emulation, a hang only by the
                # lease; either way no more heartbeats from here
                self.status = "crashed" if self.fault == "crash" \
                    else "hung"
                return
            if self.fault == "partition":
                # alive but unreachable: no comm, no steps, no exit
                self.status = "partitioned"
                time.sleep(0.01)
                continue
            self._tick_background()
            if self._pending_abort is not None and not self.draining:
                flag, self._pending_abort = self._pending_abort, None
                if not self._rebuild(flag):
                    return
                continue
            if self.draining:
                rec = w.kv_get_json(MEMBERSHIP_SCOPE, EPOCH_KEY)
                if rec is not None \
                        and int(rec.get("epoch", -1)) > self.epoch \
                        and self.wid not in rec.get("world", []):
                    self.status = ("preempted" if self.preempt_expected
                                   else "drained")
                    return
                time.sleep(0.01)
                continue
            self._check_drain()
            if self.draining:
                continue
            if self._can_step():
                self._observant_sleep(w.step_seconds + self.slow_pending)
                self.slow_pending = 0.0
                self.step += 1
                w.steps[self.wid] = self.step
                if self.step % w.snapshot_every == 0:
                    w.plane.commit(self.step, rank=self.rank)
            else:
                self._observant_sleep(0.005)


class _Injector(threading.Thread):
    """Fires the schedule from outside the supervision loop — so a
    primary kill can land while the driver is blocked in a drain
    handshake, which is exactly the composition the journaled-standby
    design must survive."""

    def __init__(self, world: _World, driver: ElasticDriver,
                 workers: Dict[str, _ChaosWorker],
                 entries: Sequence[ChaosEntry], t0: float):
        super().__init__(daemon=True, name="chaos-injector")
        self.world = world
        self.driver = driver
        self.workers = workers
        self.pending = sorted(entries, key=lambda e: e.at)
        self.t0 = t0
        self.outage_until: Optional[float] = None
        self.resume_poll_at = 0.0   # runner: no driver ticks before this
        self.done = False
        self.fired: List[ChaosEntry] = []

    def run(self) -> None:  # noqa: D102
        while not self.world.stop:
            now = time.monotonic() - self.t0
            if self.outage_until is not None and now >= self.outage_until:
                self._takeover()
            if self.pending and self.pending[0].at <= now:
                entry = self.pending.pop(0)
                try:
                    self._fire(entry)
                except Exception:  # noqa: BLE001
                    log.exception("chaos injection failed: %s",
                                  entry.render())
                continue
            self.done = not self.pending and self.outage_until is None
            time.sleep(0.005)
        if self.outage_until is not None:
            # never leave the scenario headless: the evidence (events
            # scope) must be collectable after the horizon
            self._takeover()

    def _fire(self, entry: ChaosEntry) -> None:
        events_mod.record_event(
            "chaos.inject", severity="warning",
            payload={"kind": entry.kind, "target": entry.target,
                     "rank": entry.rank, "at": entry.at,
                     "duration": entry.duration})
        self.fired.append(entry)
        if entry.target == "primary":
            self._kill_primary(entry)
            return
        if entry.target == "relay":
            self.world.relay_dead = True
            return
        wid = self.world.worker_ids[entry.rank]
        worker = self.workers[wid]
        if entry.kind in ("crash", "hang", "partition"):
            worker.fault = entry.kind
        elif entry.kind == "slow":
            worker.slow_pending += entry.duration or 0.1
        elif entry.kind == "skew":
            worker.skewed = True
        elif entry.kind == "preempt":
            worker.preempt_expected = True
            self.world.kv_put(
                MEMBERSHIP_SCOPE, f"{PREEMPT_PREFIX}{wid}",
                {"worker": wid, "grace": entry.duration or None,
                 "pid": os.getpid(), "time": time.time()})

    def _kill_primary(self, entry: ChaosEntry) -> None:
        w = self.world
        events_mod.flush()
        events_mod.attach_server(None)  # ring-buffer until takeover
        with w._plock:
            old, w.primary = w.primary, None
        self.outage_until = ((time.monotonic() - self.t0)
                             + (entry.duration or 0.25))
        try:
            old.stop()
        except Exception:  # noqa: BLE001
            pass

    def _takeover(self) -> None:
        w = self.world
        new = RendezvousServer(port=0, journal_path=w.journal_path)
        new.start()
        with w._plock:
            w.primary = new
        self.driver.server = new
        events_mod.attach_server(new)
        events_mod.record_event(
            "primary.takeover", severity="warning",
            payload={"port": new.port,
                     "journal": bool(w.journal_path)})
        self.outage_until = None
        # let workers re-establish leases on the standby before the
        # driver's lease/silent sweeps may run again
        self.resume_poll_at = time.monotonic() + 2 * w.hb_interval


# ---------------------------------------------------------------------------
# scenario execution
# ---------------------------------------------------------------------------

@dataclass
class ScenarioResult:
    """One scenario's evidence bundle and verdict."""

    scenario: Scenario
    ok: bool
    violations: List[invariants_mod.Violation]
    events: List[dict]
    workers: Dict[str, dict]
    final_world: List[str]
    final_epoch: int
    failed_reason: Optional[str]
    recoveries: List[dict]
    duration_s: float
    skipped_entries: List[ChaosEntry] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "scenario": self.scenario.name,
            "schedule": self.scenario.render(),
            "ok": self.ok,
            "violations": [v.to_dict() for v in self.violations],
            "workers": self.workers,
            "final_world": self.final_world,
            "final_epoch": self.final_epoch,
            "failed_reason": self.failed_reason,
            "recoveries": self.recoveries,
            "events_recorded": len(self.events),
            "duration_s": round(self.duration_s, 3),
            "skipped": [e.render() for e in self.skipped_entries],
        }


@contextlib.contextmanager
def _scoped_env(overrides: Dict[str, str]):
    old = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _needs_journal(scenario: Scenario) -> bool:
    return any(e.target == "primary" for e in scenario.entries)


def run_scenario(scenario: Scenario, *, world_size: Optional[int] = None,
                 min_np: int = 1, hb_interval: float = 0.06,
                 step_seconds: Optional[float] = None,
                 snapshot_every: Optional[int] = None,
                 settle_seconds: Optional[float] = None,
                 timeout: Optional[float] = None,
                 drain_timeout: float = 1.2) -> ScenarioResult:
    """Execute one scenario against a live control plane and check
    every invariant over the recorded evidence.  Self-contained: stands
    up (and tears down) its own server, driver, and worker threads;
    resets the process flight recorder on entry and exit."""
    world_size = int(world_size if world_size is not None else
                     env_util.get_int(env_util.HVD_CHAOS_WORLD,
                                      env_util.DEFAULT_CHAOS_WORLD))
    step_seconds = float(
        step_seconds if step_seconds is not None else
        env_util.get_float(env_util.HVD_CHAOS_STEP_SECONDS,
                           env_util.DEFAULT_CHAOS_STEP_SECONDS))
    snapshot_every = int(
        snapshot_every if snapshot_every is not None else
        env_util.get_int(env_util.HVD_CHAOS_SNAPSHOT_EVERY,
                         env_util.DEFAULT_CHAOS_SNAPSHOT_EVERY))
    timeout = float(timeout if timeout is not None else env_util.get_float(
        env_util.HVD_CHAOS_TIMEOUT_SECONDS,
        env_util.DEFAULT_CHAOS_TIMEOUT_SECONDS))
    for e in scenario.entries:
        if e.target == "worker" and not 0 <= e.rank < world_size:
            raise ChaosSpecError(
                f"entry {e.render()!r} targets rank {e.rank} outside "
                f"the world of {world_size}")
    journal = None
    if _needs_journal(scenario):
        fd, journal = tempfile.mkstemp(prefix="hvd-chaos-journal-",
                                       suffix=".jsonl")
        os.close(fd)
        os.unlink(journal)  # the server creates it; replay needs absence
    events_mod._reset_for_tests()
    world = _World([str(i) for i in range(world_size)],
                   hb_interval=hb_interval, step_seconds=step_seconds,
                   snapshot_every=snapshot_every, journal_path=journal)
    server = RendezvousServer(port=0, journal_path=journal)
    server.start()
    world.primary = server
    events_mod.attach_server(server)
    overrides = {
        env_util.HVD_HEARTBEAT_INTERVAL_SECONDS: str(hb_interval),
        env_util.HVD_ELASTIC_TIMEOUT_SECONDS: "1.2",
        env_util.HVD_ELASTIC_SILENT_GRACE_SECONDS: "0.5",
        env_util.HVD_EVENTS: "1",
        # never let a worker-side flusher spin up against unrelated
        # rendezvous wiring from an enclosing test/launcher
        env_util.HVD_METRICS_KV_ADDR: "",
    }
    t_start = time.monotonic()
    workers: Dict[str, _ChaosWorker] = {}
    injector: Optional[_Injector] = None
    driver: Optional[ElasticDriver] = None
    try:
        with _scoped_env(overrides):
            driver = ElasticDriver(server, world.worker_ids,
                                   min_np=min_np,
                                   drain_timeout=drain_timeout)
            for wid in world.worker_ids:
                workers[wid] = _ChaosWorker(world, wid)
                workers[wid].start()
            injector = _Injector(world, driver, workers,
                                 scenario.entries, t_start)
            injector.start()
            destructive_silent = any(
                e.kind in ("hang", "partition") for e in scenario.entries)
            last_at = max((e.at for e in scenario.entries), default=0.0)
            settle = float(settle_seconds if settle_seconds is not None
                           else (3.2 if destructive_silent else 2.2))
            horizon = min(last_at + settle, timeout)
            reaped: set = set()
            quiet_since: Optional[float] = None
            while time.monotonic() - t_start < horizon:
                now = time.monotonic()
                if world.primary is not None \
                        and now >= injector.resume_poll_at \
                        and injector.outage_until is None:
                    for wkr in workers.values():
                        if wkr.status == "crashed" \
                                and wkr.wid not in reaped:
                            # supervise()'s child-exit reaping: the
                            # fault exit code names the cause
                            reaped.add(wkr.wid)
                            if wkr.wid in driver.world:
                                driver.remove(
                                    wkr.wid,
                                    f"worker {wkr.wid} exited with code "
                                    f"{FAULT_EXIT_CODE}")
                    try:
                        driver.poll()
                    except Exception:  # noqa: BLE001
                        log.exception("driver poll failed mid-scenario")
                if driver.failed_reason is not None:
                    break
                disrupted_clear = all(
                    wkr.wid not in driver.world
                    for wkr in workers.values()
                    if wkr.status in ("crashed", "hung", "partitioned")
                    or wkr.fault is not None or wkr.draining)
                quiesced = (injector.done and disrupted_clear
                            and driver._stable
                            and world.primary is not None
                            and set(driver.world)
                            <= driver._ready_workers(driver.epoch))
                if quiesced:
                    if quiet_since is None:
                        quiet_since = now
                    elif now - quiet_since > 0.35:
                        break
                else:
                    quiet_since = None
                time.sleep(0.01)
    finally:
        world.stop = True
        for wkr in workers.values():
            wkr.join(timeout=2.0)
        if injector is not None:
            injector.join(timeout=3.0)
    events_mod.flush()
    evs: List[dict] = []
    if world.primary is not None:
        for raw in world.primary.scope_items(EVENTS_SCOPE).values():
            try:
                evs.append(json.loads(raw))
            except (ValueError, TypeError):
                continue
    evs.sort(key=lambda e: (e.get("ts") or 0.0, str(e.get("id"))))
    evidence = {wid: {"status": wkr.status, "step": wkr.step,
                      "epoch": wkr.epoch}
                for wid, wkr in workers.items()}
    final_world = list(driver.world) if driver is not None else []
    violations = invariants_mod.check_all(
        evs, hb_interval=hb_interval, snapshot_every=snapshot_every,
        workers=evidence, final_world=final_world)
    recoveries = measure_recoveries(evs)
    result = ScenarioResult(
        scenario=scenario,
        ok=(not violations
            and (driver is None or driver.failed_reason is None)),
        violations=violations, events=evs, workers=evidence,
        final_world=final_world,
        final_epoch=driver.epoch if driver is not None else -1,
        failed_reason=driver.failed_reason if driver is not None else None,
        recoveries=recoveries,
        duration_s=time.monotonic() - t_start,
        skipped_entries=list(injector.pending) if injector else [])
    try:
        if driver is not None:
            driver.shutdown()
        if world.primary is not None:
            world.primary.stop()
    except Exception:  # noqa: BLE001
        pass
    if journal is not None:
        try:
            os.unlink(journal)
        except OSError:
            pass
    events_mod._reset_for_tests()
    return result


def measure_recoveries(events: List[dict]) -> List[dict]:
    """Per removal commit: time from the triggering evidence (lease
    expiry, preemption notice, or the remove decision) to the LAST
    survivor resume of that epoch — the MTTR the bench distils to
    p50/p99 — plus the per-rank steps lost."""
    evs = sorted((e for e in events if isinstance(e, dict)),
                 key=lambda e: (e.get("ts") or 0.0, str(e.get("id"))))
    out: List[dict] = []
    for c in evs:
        if c.get("kind") != "epoch.commit":
            continue
        payload = c.get("payload") or {}
        if not payload.get("removed"):
            continue
        epoch = payload.get("epoch")
        chain = events_mod.extract_chain(evs, c.get("id"))
        trigger = next(
            (e for e in chain if e.get("kind") in
             ("lease.expired", "preempt.notice", "epoch.remove")), c)
        resumes = [e for e in chain
                   if e.get("kind") == "restart.resume"
                   and (e.get("payload") or {}).get("epoch") == epoch]
        rec = {
            "epoch": epoch,
            "removed": payload.get("removed"),
            "drained": _DRAINED_MARK in (payload.get("reason") or ""),
            "trigger": trigger.get("kind"),
            "steps_lost": [
                (e.get("payload") or {}).get("steps_lost", 0)
                for e in resumes],
            "mttr_ms": None,
        }
        if resumes:
            rec["mttr_ms"] = round(
                (max(e.get("ts") or 0.0 for e in resumes)
                 - (trigger.get("ts") or 0.0)) * 1000, 1)
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# campaigns
# ---------------------------------------------------------------------------

@dataclass
class CampaignResult:
    """One campaign run: every scenario's verdict plus the shrink
    output for whatever failed (when shrinking was requested)."""

    seed: Optional[int]
    results: List[ScenarioResult]
    shrunk: Dict[str, "ShrinkResult"] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "ok": self.ok,
            "scenarios": [r.to_dict() for r in self.results],
            "shrunk": {k: s.to_dict() for k, s in self.shrunk.items()},
        }


def run_campaign(scenarios: Sequence[Scenario],
                 seed: Optional[int] = None,
                 shrink_failures: bool = False,
                 **run_kwargs) -> CampaignResult:
    """Run every scenario in order; optionally ddmin-shrink each red
    one to its minimal failing fault subset."""
    results = []
    for s in scenarios:
        log.info("chaos scenario %s: %s", s.name, s.render())
        results.append(run_scenario(s, **run_kwargs))
        log.info("chaos scenario %s: %s", s.name,
                 "OK" if results[-1].ok else
                 f"{len(results[-1].violations)} violation(s)")
    campaign = CampaignResult(seed=seed, results=results)
    if shrink_failures:
        for r in results:
            if not r.ok:
                campaign.shrunk[r.scenario.name] = shrink(
                    r.scenario, **run_kwargs)
    return campaign


# ---------------------------------------------------------------------------
# delta-debugging shrink
# ---------------------------------------------------------------------------

def ddmin(items: Sequence, failing: Callable[[List], bool]) -> List:
    """Zeller's ddmin over ``items``: the smallest subset for which
    ``failing`` still returns True (1-minimal — dropping any single
    remaining item makes the failure vanish).  Results are memoised so
    re-tested subsets cost nothing."""
    idx = list(range(len(items)))
    cache: Dict[Tuple[int, ...], bool] = {}

    def fails(sub: List[int]) -> bool:
        key = tuple(sub)
        if key not in cache:
            cache[key] = bool(failing([items[i] for i in sub]))
        return cache[key]

    if not fails(idx):
        raise ChaosSpecError(
            "the full scenario does not fail; nothing to shrink")
    n = 2
    while len(idx) >= 2:
        chunks = [idx[i * len(idx) // n:(i + 1) * len(idx) // n]
                  for i in range(n)]
        reduced = False
        for chunk in chunks:
            if not chunk or len(chunk) == len(idx):
                continue
            if fails(chunk):
                idx, n, reduced = chunk, 2, True
                break
            complement = [i for i in idx if i not in chunk]
            if complement and fails(complement):
                idx, n, reduced = complement, max(n - 1, 2), True
                break
        if not reduced:
            if n >= len(idx):
                break
            n = min(len(idx), n * 2)
    return [items[i] for i in idx]


@dataclass
class ShrinkResult:
    """The minimal failing scenario and the evidence it still trips."""

    minimal: Scenario
    result: ScenarioResult
    runs: int

    def to_dict(self) -> dict:
        return {
            "minimal": self.minimal.render(),
            "entries": len(self.minimal.entries),
            "runs": self.runs,
            "violations": [v.to_dict()
                           for v in self.result.violations],
        }


def shrink(scenario: Scenario, **run_kwargs) -> ShrinkResult:
    """Delta-debug ``scenario`` to the minimal fault subset that still
    violates an invariant, then re-run the minimal scenario to capture
    its violation report (with causal chains) as the verdict."""
    runs = [0]

    def failing(entries: List[ChaosEntry]) -> bool:
        if not entries:
            return False
        runs[0] += 1
        sub = Scenario(name=f"{scenario.name}#shrink{runs[0]}",
                       entries=tuple(entries))
        return not run_scenario(sub, **run_kwargs).ok

    minimal_entries = ddmin(list(scenario.entries), failing)
    minimal = Scenario(
        name=f"{scenario.name}#minimal",
        entries=tuple(sorted(minimal_entries, key=lambda e: e.at)))
    final = run_scenario(minimal, **run_kwargs)
    runs[0] += 1
    return ShrinkResult(minimal=minimal, result=final, runs=runs[0])
