"""Elastic membership: the worker side of shrink/grow worlds.

PR 4's failure-domain runtime is fail-stop: a dead rank aborts the whole
job and ``tpurun --restarts`` relaunches everything — correct, but every
failure costs a full teardown, JIT re-compile on all ranks, and up to one
checkpoint interval of work on every survivor.  This module is the
in-process alternative, the TPU-native form of the reference's elastic
runtime (``hvd.elastic``: discovery-driven worker sets,
``@hvd.elastic.run`` state restore — reference
horovod/run/elastic/driver.py, horovod/common/elastic.py):

* The **elastic driver** (elastic/driver.py, hosted by the ``tpurun
  --elastic`` supervisor) owns the world.  Membership is versioned by an
  **epoch counter**: each committed epoch is a JSON record at the
  rendezvous key ``/membership/epoch`` —
  ``{"epoch": N, "world": [worker ids in rank order], "controller_addr",
  "removed", "admitted", "reason"}``.  Worker identity
  (``HVD_ELASTIC_WORKER_ID``) is stable across epochs; *ranks* are
  re-assigned densely from the roster order each epoch.
* On a failure the driver revokes the dead rank's lease, publishes the
  coordinated-abort flag stamped with the dying epoch, and commits epoch
  N+1 with the survivor roster.  Survivors raise
  :class:`~horovod_tpu.elastic.abort.HorovodAbortError` at the next
  dispatch/step seam; the :func:`run` wrapper catches it, waits for the
  new epoch, **rebuilds in process** (:func:`apply_epoch` →
  ``core.reinit()``), re-syncs :class:`~horovod_tpu.elastic.state.
  ElasticState` through a rank-0 in-memory broadcast (no disk round
  trip), and retries the training function.
* Rejoin is the same path in reverse: a restarted or spare host calls
  :func:`join_world`, which announces it at the rendezvous; the driver
  admits it at the next epoch boundary, and the newcomer receives the
  live state from the same rank-0 broadcast (checkpoint restore is only
  the fallback when no broadcast arrives).

Wire layout under the ``membership`` scope (run/http_server.py;
``GET /membership`` renders it all):

====================  =====================================================
key                   value
====================  =====================================================
``epoch``             the committed epoch record (single writer: the driver)
``announce.<worker>`` a rejoin candidacy ``{worker, host, pid, time}``
``ready.<N>.<worker>``worker's ack that it rebuilt into epoch N
``state.<N>``         rank 0's pickled ``{state, step}`` broadcast for N
``blocklist``         worker ids barred from rejoining (flapping hosts)
====================  =====================================================
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import time
import urllib.error
from typing import Any, Callable, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger
from .abort import HorovodAbortError, _rendezvous_from_env

log = get_logger(__name__)

# module state: the epoch this process last applied, and its identity.
_epoch: int = 0
_record: Optional[dict] = None
_worker_id: Optional[str] = None


class RemovedFromWorldError(HorovodAbortError):
    """This worker is not part of the committed epoch (it was removed or
    blocklisted by the elastic driver) — there is nothing to rebuild
    into; the process must exit."""


def enabled() -> bool:
    """True when an elastic driver supervises this job (HVD_ELASTIC=1)."""
    return env_util.get_bool(env_util.HVD_ELASTIC)


def worker_id() -> str:
    """This process's stable identity across epochs: the launcher exports
    ``HVD_ELASTIC_WORKER_ID``; spare hosts set their own; the initial
    process id is the fallback."""
    global _worker_id
    if _worker_id is None:
        _worker_id = env_util.get_str(env_util.HVD_ELASTIC_WORKER_ID) \
            or str(env_util.get_int(env_util.HVD_PROCESS_ID, 0))
    return _worker_id


def current_epoch() -> int:
    return _epoch


def current_record() -> Optional[dict]:
    return _record


def world_size() -> int:
    """Size of the committed world this process last applied (falls back
    to the launcher-exported process count before any epoch is seen)."""
    if _record is not None:
        return len(_record.get("world", ()))
    return env_util.get_int(env_util.HVD_NUM_PROCESSES, 1)


def elastic_timeout() -> float:
    return env_util.get_float(env_util.HVD_ELASTIC_TIMEOUT_SECONDS,
                              env_util.DEFAULT_ELASTIC_TIMEOUT_SECONDS)


def _wiring():
    wired = _rendezvous_from_env()
    if wired is None:
        raise RuntimeError(
            "elastic membership needs the launcher rendezvous wiring "
            "(HVD_METRICS_KV_ADDR/PORT); was this process started under "
            "tpurun --elastic or pointed at its server?"
        )
    return wired


def get_epoch_record(*, timeout: float = 0.0) -> Optional[dict]:
    """The committed epoch record from the rendezvous server (None when
    nothing is committed yet; ``timeout`` waits for the first commit)."""
    from ..run.http_client import get_kv
    from ..run.http_server import EPOCH_KEY, MEMBERSHIP_SCOPE

    addr, port, secret = _wiring()
    raw = get_kv(addr, port, MEMBERSHIP_SCOPE, EPOCH_KEY, secret=secret,
                 wait=timeout > 0, timeout=timeout)
    if raw is None:
        return None
    return json.loads(raw)


def wait_for_epoch(min_epoch: int,
                   timeout: Optional[float] = None) -> Optional[dict]:
    """Poll the rendezvous until an epoch ``>= min_epoch`` is committed;
    returns the record, or None when ``timeout`` (default
    ``HVD_ELASTIC_TIMEOUT_SECONDS``) expires — the caller then treats the
    job as dead rather than waiting forever on a driver that gave up.
    Transient rendezvous errors are absorbed until the deadline."""
    timeout = elastic_timeout() if timeout is None else timeout
    deadline = time.monotonic() + timeout
    delay = 0.05
    while True:
        try:
            rec = get_epoch_record()
            if rec is not None and int(rec.get("epoch", -1)) >= min_epoch:
                return rec
        except (urllib.error.URLError, OSError, ValueError) as e:
            log.debug("membership poll failed: %s", e)
        if time.monotonic() >= deadline:
            return None
        time.sleep(delay)
        delay = min(delay * 1.5, 0.5)


def ack(epoch: int) -> None:
    """Publish this worker's ready ack for ``epoch`` — the driver's
    rebuild barrier (it clears the abort flag and admits pending joins
    once every roster member has acked)."""
    from ..run.http_client import put_kv
    from ..run.http_server import MEMBERSHIP_SCOPE, READY_PREFIX

    addr, port, secret = _wiring()
    put_kv(addr, port, MEMBERSHIP_SCOPE,
           f"{READY_PREFIX}{int(epoch)}.{worker_id()}",
           json.dumps({"worker": worker_id(), "pid": os.getpid(),
                       "time": time.time()}).encode(),
           secret=secret, retry=True)


def announce() -> None:
    """Publish this worker's rejoin candidacy; the driver admits it at
    the next epoch boundary (unless blocklisted) — or holds it as a
    spare when a serving autoscaler owns admissions."""
    from ..run.http_client import put_kv
    from ..run.http_server import ANNOUNCE_PREFIX, MEMBERSHIP_SCOPE

    addr, port, secret = _wiring()
    put_kv(addr, port, MEMBERSHIP_SCOPE, f"{ANNOUNCE_PREFIX}{worker_id()}",
           json.dumps({"worker": worker_id(), "host": socket.gethostname(),
                       "pid": os.getpid(), "time": time.time()}).encode(),
           secret=secret, retry=True)


def drain_requested() -> Optional[dict]:
    """The driver's pending drain request for THIS worker (None when
    there is none): the first half of the lossless scale-down
    handshake — on a request, stop taking new work, finish in flight,
    then :func:`ack_drain` (docs/inference.md, docs/fault_tolerance.md
    "Drain handshake").  Never raises: a rendezvous blip reads as "no
    request" and the driver's timeout covers the lossy fallback."""
    from ..run.http_client import get_kv
    from ..run.http_server import DRAIN_PREFIX, MEMBERSHIP_SCOPE

    try:
        addr, port, secret = _wiring()
        raw = get_kv(addr, port, MEMBERSHIP_SCOPE,
                     f"{DRAIN_PREFIX}{worker_id()}", secret=secret)
    except (RuntimeError, urllib.error.URLError, OSError) as e:
        log.debug("drain poll failed: %s", e)
        return None
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return {"worker": worker_id()}


def ack_drain() -> None:
    """The second half of the drain handshake: this worker has stopped
    pulling and completed everything in flight — the driver may now
    commit the shrink epoch."""
    from ..run.http_client import put_kv
    from ..run.http_server import DRAIN_ACK_PREFIX, MEMBERSHIP_SCOPE

    addr, port, secret = _wiring()
    put_kv(addr, port, MEMBERSHIP_SCOPE,
           f"{DRAIN_ACK_PREFIX}{worker_id()}",
           json.dumps({"worker": worker_id(), "pid": os.getpid(),
                       "time": time.time()}).encode(),
           secret=secret, retry=True)


def notify_preemption(grace: Optional[float] = None) -> None:
    """Publish a preemption notice for THIS worker (cloud maintenance
    signal, or a ``kind=preempt`` fault): ``preempt.<worker>`` under the
    membership scope.  The elastic driver's poll picks it up and runs a
    planned drain+snapshot inside the ``grace`` window
    (elastic/driver.preempt) — the worker keeps working until the drain
    request arrives, so preemption never reads as a crash."""
    from ..run.http_client import put_kv
    from ..run.http_server import MEMBERSHIP_SCOPE, PREEMPT_PREFIX

    addr, port, secret = _wiring()
    put_kv(addr, port, MEMBERSHIP_SCOPE,
           f"{PREEMPT_PREFIX}{worker_id()}",
           json.dumps({"worker": worker_id(), "grace": grace,
                       "pid": os.getpid(), "time": time.time()}).encode(),
           secret=secret, retry=True)


def _apply_env(rec: dict) -> int:
    """Adopt the committed record: re-assign this worker's dense rank
    from the roster and rewrite the topology env the runtime reads.
    Raises :class:`RemovedFromWorldError` when this worker is not in the
    roster.  Returns the new rank."""
    global _epoch, _record
    world = list(rec.get("world", ()))
    me = worker_id()
    if me not in world:
        raise RemovedFromWorldError(
            f"worker {me} is not in the epoch-{rec.get('epoch')} world "
            f"{world} (removed or blocklisted by the elastic driver)"
        )
    new_rank = world.index(me)
    n = len(world)
    os.environ[env_util.HVD_PROCESS_ID] = str(new_rank)
    os.environ[env_util.HVD_RANK] = str(new_rank)
    os.environ[env_util.HVD_NUM_PROCESSES] = str(n)
    os.environ[env_util.HVD_SIZE] = str(n)
    ctrl = rec.get("controller_addr")
    if ctrl:
        os.environ[env_util.HVD_CONTROLLER_ADDR] = ctrl
    else:
        os.environ.pop(env_util.HVD_CONTROLLER_ADDR, None)
    _record = rec
    _epoch = int(rec.get("epoch", 0))
    return new_rank


def _env_matches(rec: dict) -> bool:
    """Does this process's env already reflect ``rec``'s assignment?"""
    world = list(rec.get("world", ()))
    me = worker_id()
    if me not in world:
        return False
    ctrl = rec.get("controller_addr")
    return (env_util.get_int(env_util.HVD_PROCESS_ID, 0) == world.index(me)
            and env_util.get_int(env_util.HVD_NUM_PROCESSES, 1) == len(world)
            and (not ctrl
                 or env_util.get_str(env_util.HVD_CONTROLLER_ADDR) == ctrl))


def attach(timeout: float = 5.0) -> Optional[dict]:
    """Join the membership protocol at process start: read the committed
    epoch record, adopt it, and ack it (the driver's start barrier).
    When the world already moved between this worker's spawn and its
    attach (a shrink raced the interpreter start-up), the committed
    assignment is APPLIED — env rewritten, a stale heartbeat restarted —
    not merely acked; acking a world this process does not actually run
    in would satisfy the driver's stability barrier with a lie.  No-op
    outside elastic jobs; called by ``core.init()`` (before it reads the
    process identity) and by :func:`run`, idempotent."""
    global _epoch, _record
    if not enabled():
        return None
    try:
        rec = get_epoch_record(timeout=timeout)
    except (RuntimeError, urllib.error.URLError, OSError) as e:
        log.warning("membership attach failed: %s", e)
        return None
    if rec is None:
        return None
    if worker_id() not in rec.get("world", ()):
        # A spare (join_world announces later) or an evicted worker: no
        # ack for a roster we are not part of, and the epoch floor stays
        # one BEHIND the record — an evicted-at-startup worker must still
        # honor the abort flag stamped with the epoch it was removed
        # from, reach the seam, and die with RemovedFromWorldError
        # (adopting the new epoch would make its heartbeat discard that
        # flag as stale and leave a zombie training against a world it
        # left).
        _record = rec
        _epoch = max(int(rec.get("epoch", 0)) - 1, 0)
        return rec
    if not _env_matches(rec):
        log.warning("membership moved before attach: adopting epoch %s "
                    "assignment", rec.get("epoch"))
        _apply_env(rec)
        from . import heartbeat

        hb = heartbeat.instance()
        if hb is not None and hb.epoch != _epoch:
            heartbeat.stop()
            heartbeat.start_from_env()
    else:
        _record = rec
        _epoch = int(rec.get("epoch", 0))
    try:
        ack(_epoch)
    except (urllib.error.URLError, OSError) as e:
        log.warning("membership ack failed: %s", e)
    return rec


def apply_epoch(rec: dict) -> int:
    """Rebuild this process into the committed epoch ``rec``: re-assign
    the dense rank from the roster (:func:`_apply_env`), and
    re-initialize in process — ``core.reinit()`` tears down and
    re-creates the mesh/controller client against the epoch's
    ``ControllerServer`` and restarts the heartbeat under the new epoch;
    processes that never called ``core.init()`` (light harness workers)
    restart the heartbeat alone.  Returns the new rank."""
    new_rank = _apply_env(rec)
    from .. import core

    if core.is_initialized():
        core.reinit()
    else:
        from . import heartbeat

        heartbeat.stop()
        heartbeat.start_from_env()
    log.info("membership epoch %d applied: rank %d/%d (worker %s, "
             "controller %s)", _epoch, new_rank, len(rec.get("world", ())),
             worker_id(), rec.get("controller_addr") or "none")
    return new_rank


def renew_spare_lease() -> None:
    """Announce-keyed liveness for a worker the driver may be *holding*
    as a spare (``--min-np`` satisfied): one lease PUT at
    ``health/spare.<worker>`` — non-numeric key, so the driver's
    rank-lease expiry loop ignores it, but the server's STALE/DEAD
    verdicts apply and :meth:`~horovod_tpu.elastic.driver.ElasticDriver.
    _purge_dead_spares` drops a dead-while-held spare before trying to
    admit it.  Best-effort: a failed renewal just ages the lease."""
    from ..run.http_client import put_kv
    from ..run.http_server import HEALTH_SCOPE, SPARE_PREFIX

    addr, port, secret = _wiring()
    interval = env_util.get_float(env_util.HVD_HEARTBEAT_INTERVAL_SECONDS,
                                  env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS)
    body = json.dumps({"worker": worker_id(), "interval": interval,
                       "spare": True, "pid": os.getpid()}).encode()
    try:
        put_kv(addr, port, HEALTH_SCOPE, f"{SPARE_PREFIX}{worker_id()}",
               body, secret=secret)
    except (urllib.error.URLError, OSError) as e:
        log.debug("spare lease renewal failed: %s", e)


def clear_spare_lease() -> None:
    """Retire the spare lease on admission (the worker now renews a
    rank-keyed heartbeat lease instead)."""
    from ..run.http_client import delete_kv
    from ..run.http_server import HEALTH_SCOPE, SPARE_PREFIX

    addr, port, secret = _wiring()
    try:
        delete_kv(addr, port, HEALTH_SCOPE, f"{SPARE_PREFIX}{worker_id()}",
                  secret=secret)
    except (urllib.error.URLError, OSError):
        pass


def join_world(state: Any = None,
               timeout: Optional[float] = None) -> dict:
    """Spare-host entry: announce this worker at the rendezvous, wait for
    the driver to admit it into a committed epoch, rebuild into that
    epoch, and (when ``state`` is an ElasticState) receive the live
    training state from rank 0's in-memory broadcast.  Returns the epoch
    record; raises TimeoutError when no admitting epoch arrives.

    The wait is chunked at the heartbeat interval so the worker renews
    its **spare lease** (:func:`renew_spare_lease`) the whole time it
    may be sitting in ``driver.spares`` — a spare that dies while held
    stops renewing and is purged instead of being admitted into an
    epoch it can never ack."""
    timeout = elastic_timeout() if timeout is None else timeout
    announce()
    me = worker_id()
    deadline = time.monotonic() + timeout
    floor = -1
    interval = env_util.get_float(env_util.HVD_HEARTBEAT_INTERVAL_SECONDS,
                                  env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS)
    while True:
        renew_spare_lease()
        rec = wait_for_epoch(
            floor + 1,
            timeout=min(interval, max(deadline - time.monotonic(), 0.0)))
        if rec is None:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"worker {me} announced itself but no epoch admitted "
                    f"it within {timeout:.0f}s (blocklisted, or the driver "
                    "is not elastic)"
                )
            continue  # chunk elapsed: renew the lease, keep waiting
        floor = int(rec.get("epoch", 0))
        if me in rec.get("world", ()):
            break
    clear_spare_lease()
    apply_epoch(rec)
    if state is not None and hasattr(state, "sync"):
        state.sync(int(rec["epoch"]))
    ack(int(rec["epoch"]))
    from . import peerstate

    peerstate.on_epoch(rec)  # re-register + reprotect (no-op when off)
    log.info("worker %s joined the world at epoch %s", me, rec.get("epoch"))
    return rec


def publish_state_blob(epoch: int, payload: dict) -> None:
    """Rank 0's half of the in-memory state broadcast (ElasticState.sync):
    one pickled ``{state, step}`` blob per epoch on the rendezvous."""
    from ..run.http_client import put_kv
    from ..run.http_server import MEMBERSHIP_SCOPE, STATE_PREFIX

    addr, port, secret = _wiring()
    put_kv(addr, port, MEMBERSHIP_SCOPE, f"{STATE_PREFIX}{int(epoch)}",
           pickle.dumps(payload), secret=secret, retry=True)


def fetch_state_blob(epoch: int,
                     timeout: Optional[float] = None) -> Optional[dict]:
    """The non-root half: wait for rank 0's broadcast of ``epoch`` (None
    on timeout — the caller falls back to checkpoint restore)."""
    from ..run.http_client import get_kv
    from ..run.http_server import MEMBERSHIP_SCOPE, STATE_PREFIX

    addr, port, secret = _wiring()
    timeout = elastic_timeout() if timeout is None else timeout
    raw = get_kv(addr, port, MEMBERSHIP_SCOPE, f"{STATE_PREFIX}{int(epoch)}",
                 secret=secret, wait=True, timeout=timeout)
    if raw is None:
        return None
    return pickle.loads(raw)


def check_fence() -> None:
    """Split-brain fence for rank-0-gated side effects (checkpoint
    writes): a partitioned rank that cannot reach the rendezvous — or
    whose epoch has been superseded — must not act as rank 0.  Raises
    :class:`HorovodAbortError`; no-op outside elastic jobs."""
    if not enabled():
        return
    try:
        rec = get_epoch_record()
    except (RuntimeError, urllib.error.URLError, OSError) as e:
        raise HorovodAbortError(
            f"fencing: rendezvous unreachable from worker {worker_id()} "
            f"({e}); refusing rank-0 side effects in a possible partition"
        )
    if rec is not None and int(rec.get("epoch", 0)) != _epoch:
        raise HorovodAbortError(
            f"fencing: membership moved to epoch {rec.get('epoch')} while "
            f"this worker is still in epoch {_epoch}; refusing rank-0 "
            "side effects"
        )


def run(fn: Callable, state: Any = None, *args: Any,
        on_world_change: Optional[Callable] = None, **kwargs: Any):
    """Execute ``fn(state, *args, **kwargs)`` under elastic supervision —
    the TPU-native analog of ``@hvd.elastic.run`` (reference
    horovod/common/elastic.py run_fn).

    When a membership change interrupts training (the driver publishes
    the coordinated-abort flag and the next dispatch/step raises
    :class:`HorovodAbortError`), the wrapper waits for the new epoch,
    rebuilds in process (:func:`apply_epoch`), re-syncs ``state`` from
    rank 0's in-memory broadcast (when it is an
    :class:`~horovod_tpu.elastic.state.ElasticState`), invokes
    ``on_world_change(state, old_size, new_size)`` — the batch/LR rescale
    hook — and calls ``fn`` again.  ``fn`` must therefore resume from
    ``state`` (e.g. iterate ``range(state.step, total_steps)``).

    Outside elastic jobs, or when no new epoch is committed within
    ``HVD_ELASTIC_TIMEOUT_SECONDS`` (the job is actually dead), the
    original :class:`HorovodAbortError` propagates — fail-stop semantics
    are the fallback, not replaced.
    """
    attach()
    while True:
        try:
            return fn(state, *args, **kwargs)
        except RemovedFromWorldError:
            raise
        except HorovodAbortError as e:
            if not enabled():
                raise
            log.warning("elastic: training interrupted (%s); waiting for "
                        "epoch >= %d", e, _epoch + 1)
            rec = wait_for_epoch(_epoch + 1)
            if rec is None:
                log.error("elastic: no new epoch within %.0fs; the job is "
                          "dead", elastic_timeout())
                raise
            old_size = world_size()
            apply_epoch(rec)  # raises RemovedFromWorldError when evicted
            old_step = getattr(state, "step", None)
            if state is not None and hasattr(state, "sync"):
                state.sync(int(rec["epoch"]))
            ack(int(rec["epoch"]))
            from . import peerstate

            # shrink re-replication: shards whose replicas left the
            # world are re-pushed at the epoch boundary (no-op when the
            # peer state plane is off)
            peerstate.on_epoch(rec)
            new_size = len(rec.get("world", ()))
            if on_world_change is not None:
                on_world_change(state, old_size, new_size)
            # flight recorder: the resume closes the incident chain the
            # epoch record carries across processes (observe/events.py)
            try:
                from ..observe import events as events_mod

                new_step = getattr(state, "step", None)
                steps_lost = max(int(old_step) - int(new_step), 0) \
                    if old_step is not None and new_step is not None \
                    else None
                events_mod.record_event(
                    "restart.resume", severity="info",
                    payload={"epoch": int(rec.get("epoch", 0)),
                             "old_size": old_size, "new_size": new_size,
                             "step": new_step, "steps_lost": steps_lost},
                    cause_id=rec.get("event_id"),
                    correlation_id=rec.get("correlation_id"),
                    rank=env_util.get_int(env_util.HVD_PROCESS_ID, 0))
            except Exception:  # noqa: BLE001 — recording is best-effort
                pass
            log.info("elastic: resuming in epoch %d (world %d -> %d)",
                     _epoch, old_size, new_size)


def _reset_for_tests() -> None:
    """Drop the module's epoch/identity state (test isolation)."""
    global _epoch, _record, _worker_id
    _epoch = 0
    _record = None
    _worker_id = None
