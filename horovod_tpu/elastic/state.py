"""ElasticState: the auto-resume half of supervised restart.

The supervisor (run/run.py ``tpurun --restarts N``) relaunches a failed
job with ``HVD_RESTART_COUNT`` exported; this module is what the training
script pairs with it so a relaunch *continues* instead of starting over::

    state = {"params": params, "opt_state": opt_state}
    es = hvd.elastic.ElasticState("gs://ckpts/run1", state)
    state, start_step = es.resume()      # no-op on a fresh run
    for step in range(start_step, total_steps):
        state = train_step(state, ...)
        if step % ckpt_every == 0:
            es.state = state
            es.save(step + 1)            # rank 0 writes step_{N}

On restart every rank restores the newest ``step_N`` checkpoint through
``utils/checkpoint.py`` (rank-consistent step choice + root-broadcast
restore), so the job loses at most one checkpoint interval — the
reference's broadcast-on-start resume contract (SURVEY §5), now driven
automatically by the failure-domain runtime.

With the peer state plane on (``HVD_SNAPSHOT=1``,
elastic/peerstate.py) the tiers invert: every ``save(step)`` becomes a
microsecond async snapshot to K peer hosts, the orbax storage save is
demoted to every ``HVD_SNAPSHOT_STORAGE_EVERY``-th call as the durable
backstop, and ``resume()`` pulls from live peers first — checksum-
verified, falling back wholesale to the storage tier when peers are
dead or corrupt.  Either way the flight recorder logs which tier won
(``restore.source`` — docs/fault_tolerance.md#the-peer-state-plane).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from .. import core
from ..utils import env as env_util
from ..utils.checkpoint import latest_step, restore_checkpoint, save_checkpoint
from ..utils.logging import get_logger

log = get_logger(__name__)


class ElasticState:
    """A checkpoint directory paired with the live training state."""

    def __init__(self, path: str, state: Any,
                 peer: Optional[bool] = None):
        self.path = path
        self.state = state
        self.step = 0
        self._saves = 0
        self._peer = None
        if peer is None:
            from . import peerstate

            peer = peerstate.enabled()
        if peer:
            from . import peerstate

            try:
                self._peer = peerstate.manager()
            except Exception as e:  # noqa: BLE001 — a broken peer tier
                # degrades to the storage-only contract, never to a
                # training job that cannot start
                log.warning("peer state plane unavailable (%s); falling "
                            "back to storage-tier checkpoints only", e)

    @property
    def restart_count(self) -> int:
        """Which incarnation this is (0 = first launch); set by the
        supervisor on every relaunch."""
        return env_util.get_int(env_util.HVD_RESTART_COUNT, 0)

    def save(self, step: int) -> Optional[str]:
        """Checkpoint the current state as ``step_{step}`` (rank 0 writes;
        returns the written path there, None elsewhere).

        Elastic jobs fence first: a partitioned ex-rank-0 that cannot
        reach the rendezvous — or whose membership epoch was superseded —
        must not keep writing checkpoints into the same directory as the
        re-assigned rank 0 (split-brain double-writer).

        Peer tier on: EVERY call is an async peer snapshot (µs of stall
        — the upload happens off the step path), and only every
        ``HVD_SNAPSHOT_STORAGE_EVERY``-th call still pays the
        synchronous orbax storage save, the durable backstop."""
        if env_util.get_bool(env_util.HVD_ELASTIC) \
                and env_util.get_int(env_util.HVD_PROCESS_ID, 0) == 0:
            from . import membership

            membership.check_fence()
        out = None
        if self._peer is not None:
            self._peer.snapshot(self.state, step)
            every = max(env_util.get_int(
                env_util.HVD_SNAPSHOT_STORAGE_EVERY,
                env_util.DEFAULT_SNAPSHOT_STORAGE_EVERY), 1)
            if self._saves % every == 0:
                out = save_checkpoint(self.path, self.state, step=step)
        else:
            out = save_checkpoint(self.path, self.state, step=step)
        self._saves += 1
        self.step = int(step)
        return out

    def sync(self, epoch: Optional[int] = None) -> Tuple[Any, int]:
        """Re-sync the live state across a membership epoch — the
        shrink/grow path that loses ZERO committed steps: rank 0 (of the
        NEW dense assignment) broadcasts its in-memory ``{state, step}``
        through the rendezvous, everyone else (survivors and newcomers
        alike) adopts it; no disk round trip.  Falls back to
        :meth:`resume` (checkpoint restore) when no broadcast arrives —
        e.g. a world where every member is new.  Returns
        ``(state, step)``."""
        from . import membership

        if epoch is None:
            epoch = membership.current_epoch()
        rank = env_util.get_int(env_util.HVD_PROCESS_ID, 0)
        if rank == 0:
            membership.publish_state_blob(
                epoch, {"state": self.state, "step": self.step})
            log.info("elastic sync: rank 0 broadcast step %d for epoch %d",
                     self.step, epoch)
            return self.state, self.step
        payload = membership.fetch_state_blob(epoch)
        if payload is None:
            log.warning("elastic sync: no rank-0 broadcast for epoch %d; "
                        "falling back to checkpoint restore", epoch)
            return self.resume()
        self.state = payload["state"]
        self.step = int(payload["step"])
        log.info("elastic sync: adopted rank 0's step %d for epoch %d",
                 self.step, epoch)
        return self.state, self.step

    def resume(self) -> Tuple[Any, int]:
        """Restore the newest checkpoint and return ``(state, step)``;
        a fresh run returns the initial state and 0.

        Peer tier on: the newest fully-committed peer generation is
        tried first — shards pulled from live peers, checksum-verified
        (sub-second, no storage round trip) — and the storage tier is
        the wholesale fallback when no peer generation is restorable.
        Which tier won is recorded as a ``restore.source`` flight event
        chained onto the abort/epoch incident.

        Multi-process, BOTH tiers are collective decisions.  The peer
        path broadcasts rank 0's resolved generation so every rank
        targets the same snapshot, then all-gathers per-rank success
        before committing it — if ANY rank cannot restore that
        generation, every rank falls back wholesale to the storage
        tier (see :meth:`_restore_from_peers`).  The storage path
        broadcasts the step choice from rank 0 so every rank restores
        the same checkpoint even when only root can list the
        directory; the restore itself rides ``restore_checkpoint``'s
        agreement round (root failures surface on every rank)."""
        fallback_reason = None
        if self._peer is not None:
            got, fallback_reason = self._restore_from_peers()
            if got is not None:
                self.state, self.step = got[0], int(got[1])
                self._record_restore("peer", {"gen": self.step})
                try:
                    from ..observe import events as events_mod

                    events_mod.record_event(
                        "restart.resume", severity="info",
                        payload={"step": self.step, "source": "peer",
                                 "incarnation": self.restart_count},
                        rank=env_util.get_int(env_util.HVD_PROCESS_ID, 0))
                except Exception:  # noqa: BLE001
                    pass
                log.info("elastic resume: restored step %d from peers "
                         "(incarnation %d)", self.step, self.restart_count)
                return self.state, self.step
            log.warning("elastic resume: peer tier unrestorable (%s); "
                        "falling back to storage", fallback_reason)
        step = latest_step(self.path)
        if core.is_initialized() and core.process_size() > 1:
            from .. import eager

            step = eager.broadcast_object(step)
        if step is None:
            log.info("elastic resume: no checkpoint under %s (incarnation "
                     "%d starts fresh)", self.path, self.restart_count)
            self.step = 0
            return self.state, 0
        self.state = restore_checkpoint(self.path, self.state, step=step)
        self.step = int(step)
        if self._peer is not None:
            self._record_restore("storage", {"path": self.path,
                                             "reason": fallback_reason})
        try:
            from ..observe import events as events_mod

            events_mod.record_event(
                "restart.resume", severity="info",
                payload={"step": self.step,
                         "incarnation": self.restart_count,
                         "path": self.path},
                rank=env_util.get_int(env_util.HVD_PROCESS_ID, 0))
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass
        log.info("elastic resume: restored step %d from %s (incarnation %d)",
                 self.step, self.path, self.restart_count)
        return self.state, self.step

    def _restore_from_peers(self) -> Tuple[Optional[Tuple[Any, int]],
                                           Optional[str]]:
        """Peer-tier restore with cross-rank agreement; returns
        ``(result, fallback_reason)``.

        Multi-process, the peer-vs-storage decision must be collective:
        rank 0's resolved generation is broadcast so every rank targets
        the SAME snapshot, and an agreement round (allgather of
        per-rank success) gates the result — if ANY rank cannot restore
        that generation (a transient manifest read, dead replicas, a
        corrupt shard), EVERY rank discards its peer result and the
        world falls back wholesale to the storage tier, whose step
        choice rank 0 already broadcasts.  Without the agreement round,
        one rank's private fallback to the storage checkpoint (step M)
        while the others restore a newer peer generation (step N > M)
        would silently diverge state/step across the world."""
        multi = core.is_initialized() and core.process_size() > 1
        gen = None
        if multi:
            from .. import eager

            if core.process_rank() == 0:
                try:
                    gen = self._peer.resolve_committed()
                except Exception as e:  # noqa: BLE001
                    self._peer.last_failure = f"{type(e).__name__}: {e}"
            gen = eager.broadcast_object(gen)
            if gen is None:
                return None, (self._peer.last_failure
                              or "no fully-committed generation")
        got = None
        try:
            got = self._peer.restore(self.state, gen=gen)
        except Exception as e:  # noqa: BLE001 — peer restore must
            # degrade to storage, never strand the relaunch
            self._peer.last_failure = f"{type(e).__name__}: {e}"
        if multi:
            from .. import eager

            oks = eager.allgather_object(got is not None)
            if not all(oks):
                bad = [r for r, ok in enumerate(oks) if not ok]
                reason = (self._peer.last_failure if got is None
                          else f"rank(s) {bad} could not restore peer "
                               f"gen {gen}")
                return None, reason or f"rank(s) {bad} failed peer restore"
        if got is None:
            return None, self._peer.last_failure or "peer tier empty"
        return got, None

    def _record_restore(self, source: str, extra: dict) -> None:
        """Emit ``restore.source`` (flight recorder) + the
        ``hvd_restores_total`` tick — chained onto the current epoch
        record's event ids so the restore shows up inside the
        abort→epoch incident it resolves (observe/events.py)."""
        from . import peerstate

        payload = {"source": source, "step": self.step,
                   "incarnation": self.restart_count}
        payload.update({k: v for k, v in extra.items() if v is not None})
        cause_id, correlation_id = peerstate._epoch_chain()
        try:
            from ..observe import events as events_mod

            events_mod.record_event(
                "restore.source", severity="info", payload=payload,
                cause_id=cause_id, correlation_id=correlation_id,
                rank=env_util.get_int(env_util.HVD_PROCESS_ID, 0))
        except Exception:  # noqa: BLE001
            pass
        try:
            from .. import metrics

            if metrics.on():
                metrics.RESTORES.labels(source).inc()
        except Exception:  # noqa: BLE001
            pass
