"""ElasticState: the auto-resume half of supervised restart.

The supervisor (run/run.py ``tpurun --restarts N``) relaunches a failed
job with ``HVD_RESTART_COUNT`` exported; this module is what the training
script pairs with it so a relaunch *continues* instead of starting over::

    state = {"params": params, "opt_state": opt_state}
    es = hvd.elastic.ElasticState("gs://ckpts/run1", state)
    state, start_step = es.resume()      # no-op on a fresh run
    for step in range(start_step, total_steps):
        state = train_step(state, ...)
        if step % ckpt_every == 0:
            es.state = state
            es.save(step + 1)            # rank 0 writes step_{N}

On restart every rank restores the newest ``step_N`` checkpoint through
``utils/checkpoint.py`` (rank-consistent step choice + root-broadcast
restore), so the job loses at most one checkpoint interval — the
reference's broadcast-on-start resume contract (SURVEY §5), now driven
automatically by the failure-domain runtime.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from .. import core
from ..utils import env as env_util
from ..utils.checkpoint import latest_step, restore_checkpoint, save_checkpoint
from ..utils.logging import get_logger

log = get_logger(__name__)


class ElasticState:
    """A checkpoint directory paired with the live training state."""

    def __init__(self, path: str, state: Any):
        self.path = path
        self.state = state
        self.step = 0

    @property
    def restart_count(self) -> int:
        """Which incarnation this is (0 = first launch); set by the
        supervisor on every relaunch."""
        return env_util.get_int(env_util.HVD_RESTART_COUNT, 0)

    def save(self, step: int) -> Optional[str]:
        """Checkpoint the current state as ``step_{step}`` (rank 0 writes;
        returns the written path there, None elsewhere).

        Elastic jobs fence first: a partitioned ex-rank-0 that cannot
        reach the rendezvous — or whose membership epoch was superseded —
        must not keep writing checkpoints into the same directory as the
        re-assigned rank 0 (split-brain double-writer)."""
        if env_util.get_bool(env_util.HVD_ELASTIC) \
                and env_util.get_int(env_util.HVD_PROCESS_ID, 0) == 0:
            from . import membership

            membership.check_fence()
        out = save_checkpoint(self.path, self.state, step=step)
        self.step = int(step)
        return out

    def sync(self, epoch: Optional[int] = None) -> Tuple[Any, int]:
        """Re-sync the live state across a membership epoch — the
        shrink/grow path that loses ZERO committed steps: rank 0 (of the
        NEW dense assignment) broadcasts its in-memory ``{state, step}``
        through the rendezvous, everyone else (survivors and newcomers
        alike) adopts it; no disk round trip.  Falls back to
        :meth:`resume` (checkpoint restore) when no broadcast arrives —
        e.g. a world where every member is new.  Returns
        ``(state, step)``."""
        from . import membership

        if epoch is None:
            epoch = membership.current_epoch()
        rank = env_util.get_int(env_util.HVD_PROCESS_ID, 0)
        if rank == 0:
            membership.publish_state_blob(
                epoch, {"state": self.state, "step": self.step})
            log.info("elastic sync: rank 0 broadcast step %d for epoch %d",
                     self.step, epoch)
            return self.state, self.step
        payload = membership.fetch_state_blob(epoch)
        if payload is None:
            log.warning("elastic sync: no rank-0 broadcast for epoch %d; "
                        "falling back to checkpoint restore", epoch)
            return self.resume()
        self.state = payload["state"]
        self.step = int(payload["step"])
        log.info("elastic sync: adopted rank 0's step %d for epoch %d",
                 self.step, epoch)
        return self.state, self.step

    def resume(self) -> Tuple[Any, int]:
        """Restore the newest checkpoint under ``path`` and return
        ``(state, step)``; a fresh run returns the initial state and 0.

        Multi-process: the step choice is broadcast from rank 0 so every
        rank restores the same checkpoint even when only root can list
        the directory; the restore itself rides ``restore_checkpoint``'s
        agreement round (root failures surface on every rank)."""
        step = latest_step(self.path)
        if core.is_initialized() and core.process_size() > 1:
            from .. import eager

            step = eager.broadcast_object(step)
        if step is None:
            log.info("elastic resume: no checkpoint under %s (incarnation "
                     "%d starts fresh)", self.path, self.restart_count)
            self.step = 0
            return self.state, 0
        self.state = restore_checkpoint(self.path, self.state, step=step)
        self.step = int(step)
        try:
            from ..observe import events as events_mod

            events_mod.record_event(
                "restart.resume", severity="info",
                payload={"step": self.step,
                         "incarnation": self.restart_count,
                         "path": self.path},
                rank=env_util.get_int(env_util.HVD_PROCESS_ID, 0))
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass
        log.info("elastic resume: restored step %d from %s (incarnation %d)",
                 self.step, self.path, self.restart_count)
        return self.state, self.step
