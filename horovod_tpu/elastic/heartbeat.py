"""Heartbeat leases: liveness that is observable *before* a collective
times out.

Each rank runs one daemon thread that, every
``HVD_HEARTBEAT_INTERVAL_SECONDS`` (default 2):

* renews this rank's lease — a signed PUT of ``{rank, count, interval,
  pid}`` into the rendezvous server's ``health`` scope (the server stamps
  the receipt on *its* clock, so lease age needs no cross-host clock
  agreement; ``GET /health`` renders per-rank age and a
  live/stale/dead verdict, run/http_server.py);
* polls the job-wide abort flag (elastic/abort.py).  When set, the next
  eager dispatch (eager._dispatch_guard) or train step (training.py)
  raises :class:`~horovod_tpu.elastic.abort.HorovodAbortError` naming the
  failing rank and reason — surviving ranks exit in seconds with a root
  cause instead of hanging until a transport timeout.

Wiring mirrors the metrics pusher and sanitizer: the launcher exports
``HVD_METRICS_KV_ADDR``/``PORT``/``HVD_METRICS_SECRET`` and
``core.init()`` calls :func:`start_from_env`; ``HVD_HEARTBEAT_DISABLE=1``
turns the plane off.  Lease loss is tolerated (the next interval renews);
the thread never raises into the training process.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from ..run.http_server import (  # noqa: F401 — wire constants live with
    ABORT_KEY,                   # the server; HEALTH_SCOPE re-exported
    ABORT_SCOPE,                 # for the runtime side
    HEALTH_SCOPE,
)
from ..utils import env as env_util
from ..utils.logging import get_logger
from .abort import HorovodAbortError, format_abort

log = get_logger(__name__)


class HeartbeatThread(threading.Thread):
    """One rank's lease renewer + abort poller."""

    def __init__(self, rank: int, size: int, addr: str, port: int,
                 secret: Optional[bytes] = None,
                 interval: Optional[float] = None, epoch: int = 0,
                 renew: bool = True):
        super().__init__(daemon=True, name="hvd-heartbeat")
        self.rank = int(rank)
        self.size = int(size)
        self.addr = addr
        self.port = int(port)
        self.secret = secret
        self.interval = float(
            interval if interval is not None
            else env_util.get_float(
                env_util.HVD_HEARTBEAT_INTERVAL_SECONDS,
                env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS,
            )
        )
        # The membership epoch this lease belongs to: abort flags stamped
        # with an OLDER epoch are stale (the elastic driver aborts epoch N
        # to commit N+1; a rank already rebuilt into N+1 must not re-abort
        # on the flag's way out) — see elastic/membership.py.
        self.epoch = int(epoch)
        # renew=False: abort-flag polling only.  A worker that is NOT in
        # the committed world (evicted while booting, or a spare awaiting
        # admission) must still observe the abort seam, but its rank key
        # may now belong to a DIFFERENT worker — renewing it would keep
        # the successor's lease alive and mask that worker's death.
        self.renew = bool(renew)
        self.abort_info: Optional[dict] = None
        self.beats = 0
        # NOT named _stop: threading.Thread has an internal _stop()
        # method, and shadowing it with an Event makes is_alive()/join()
        # on a finished thread raise TypeError
        self._stop_event = threading.Event()

    def run(self) -> None:
        self.beat()  # publish the first lease before any wait
        while not self._stop_event.wait(self.interval):
            self.beat()

    def beat(self) -> None:
        """One tick: renew the lease AND learn the abort verdict in the
        same round trip — the renewal's reply carries the flag
        (run/http_server.py ``_apply_one``; through a per-host relay the
        reply serves the relay's flush-refreshed cache).  Never raises —
        a flaky rendezvous link must not take the rank down; the
        retrying HTTP client (HVD_HTTP_RETRIES) absorbs transients."""
        from ..run import relay
        from ..run.http_client import get_kv

        lease = {
            "rank": self.rank,
            "count": self.beats,
            "interval": self.interval,
            "pid": os.getpid(),
        }
        reply = None
        try:
            if self.renew:
                # through the host relay when one is resolved, with the
                # shared permanent fallback to the direct path
                reply = relay.control_put(
                    self.addr, self.port, HEALTH_SCOPE, str(self.rank),
                    json.dumps(lease).encode(), secret=self.secret,
                    want_reply=True)
            self.beats += 1
            from .. import metrics

            if metrics.on():
                metrics.HEARTBEATS.inc()
        except Exception as e:  # noqa: BLE001
            log.debug("heartbeat lease renewal failed: %s", e)
        if reply is not None and "abort" in reply:
            info = reply.get("abort")
            if isinstance(info, dict):
                self._observe_abort(info)
            return
        # abort-poll-only mode (renew=False), a failed renewal, or a
        # reply without the piggyback: fall back to the explicit GET
        try:
            raw = get_kv(self.addr, self.port, ABORT_SCOPE, ABORT_KEY,
                         secret=self.secret)
        except Exception as e:  # noqa: BLE001
            log.debug("heartbeat abort poll failed: %s", e)
            return
        if raw is not None:
            try:
                info = json.loads(raw)
            except (ValueError, TypeError):
                info = {"reason": "<undecodable abort flag>",
                        "source": "unknown"}
            if not isinstance(info, dict):
                info = {"reason": repr(info), "source": "unknown"}
            self._observe_abort(info)

    def _observe_abort(self, info: dict) -> None:
        """Record an observed abort flag (once), honoring the epoch
        filter: flags stamped with an OLDER epoch are stale."""
        if self.abort_info is not None:
            return
        flag_epoch = info.get("epoch")
        try:
            flag_epoch = int(flag_epoch) if flag_epoch is not None \
                else None
        except (TypeError, ValueError):
            flag_epoch = None  # malformed epoch: honor like epoch-less
        if flag_epoch is not None and flag_epoch < self.epoch:
            log.debug("ignoring stale abort flag for epoch %s "
                      "(this rank is in epoch %d)", flag_epoch, self.epoch)
            return
        self.abort_info = info
        log.error("heartbeat observed %s", format_abort(self.abort_info))
        from .. import metrics

        if metrics.on():
            metrics.ABORTS.labels("observed").inc()
        # flight-recorder: chain this rank's observation onto the
        # publisher's event — the flag carries the publish event's id
        # across processes (observe/events.py)
        try:
            from ..observe import events as events_mod

            events_mod.record_event(
                "abort.observe", severity="warning",
                payload={"reason": info.get("reason"),
                         "source": info.get("source"),
                         "failed_rank": info.get("rank")},
                cause_id=info.get("event_id"),
                correlation_id=info.get("correlation_id"),
                rank=self.rank)
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass
        # Keep renewing the lease: an elastic survivor lives on and
        # rebuilds, and the gap until it reaches the abort seam can
        # be a whole step or checkpoint save — letting the lease die
        # here reads as a SECOND failure to the driver.  Fail-stop
        # jobs exit moments later and server-side expiry reaps them.

    def stop(self) -> None:
        self._stop_event.set()


# ---------------------------------------------------------------------------
# process-wide wiring (core.init / the train-step and dispatch seams)
# ---------------------------------------------------------------------------
_instance: Optional[HeartbeatThread] = None
_lock = threading.Lock()


def start(rank: int, size: int, addr: str, port: int,
          secret: Optional[bytes] = None,
          interval: Optional[float] = None, epoch: int = 0,
          renew: bool = True) -> HeartbeatThread:
    """Start (or replace) the process-wide heartbeat thread."""
    global _instance
    with _lock:
        if _instance is not None:
            _instance.stop()
        _instance = HeartbeatThread(rank, size, addr, port,
                                    secret=secret, interval=interval,
                                    epoch=epoch, renew=renew)
        _instance.start()
        log.info("heartbeat active: rank %d/%d via %s:%d every %.1fs "
                 "(epoch %d%s)", _instance.rank, _instance.size, addr, port,
                 _instance.interval, _instance.epoch,
                 "" if renew else ", abort-poll only")
        return _instance


def start_from_env() -> Optional[HeartbeatThread]:
    """Launcher-driven activation: no-op unless this is a multi-process
    job with rendezvous wiring (tpurun / run() export it) and
    ``HVD_HEARTBEAT_DISABLE`` is unset.  Elastic jobs (HVD_ELASTIC=1)
    keep the heartbeat even at world size 1 — it is the channel through
    which a later grow epoch interrupts the lone rank."""
    if env_util.get_bool(env_util.HVD_HEARTBEAT_DISABLE):
        return None
    size = env_util.get_int(env_util.HVD_NUM_PROCESSES, 1)
    elastic = env_util.get_bool(env_util.HVD_ELASTIC)
    if size <= 1 and not elastic:
        return None  # a single process has no peers to outlive it
    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port:
        return None
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    secret = bytes.fromhex(secret_hex) if secret_hex else None
    rank = env_util.get_int(env_util.HVD_PROCESS_ID, 0)
    epoch = 0
    renew = True
    if elastic:
        from . import membership

        epoch = membership.current_epoch()
        rec = membership.current_record()
        if rec is not None \
                and membership.worker_id() not in rec.get("world", ()):
            # not a member of the committed world (evicted while
            # booting, or a spare awaiting admission): poll the abort
            # flag so the seam can kill/redirect us, but do NOT renew a
            # rank-keyed lease that may belong to a successor worker
            renew = False
    return start(rank, size, addr, port, secret=secret, epoch=epoch,
                 renew=renew)


def instance() -> Optional[HeartbeatThread]:
    return _instance


def stop() -> None:
    """Stop and drop the process heartbeat (core.shutdown / tests)."""
    global _instance
    with _lock:
        if _instance is not None:
            _instance.stop()
            _instance = None


def maybe_raise_abort() -> None:
    """The dispatch/train-step seam: raise if the heartbeat observed the
    job-wide abort flag.  One attribute read when nothing is wrong."""
    hb = _instance
    if hb is not None and hb.abort_info is not None:
        raise HorovodAbortError(format_abort(hb.abort_info))
