"""Elastic driver: the launcher side of shrink/grow worlds.

Owned by the ``tpurun --elastic`` supervisor (run/run.py) — the analog of
the reference's ElasticDriver + host discovery loop (reference
horovod/run/elastic/driver.py: worker state machine, host blacklisting,
rank re-assignment), re-based on the rendezvous server this repo already
runs for metrics/heartbeats:

* the driver **commits membership epochs** (elastic/membership.py wire
  layout) instead of killing the job on the first failure;
* worker death is detected two ways — child-process exit (the supervise
  loop polls every worker, whichever rank dies first) and **heartbeat
  lease expiry** on the server's own clock (which also catches network
  partitions: a ``kind=partition`` rank is alive but cannot renew);
* each epoch gets a **fresh ControllerServer** sized to the new world,
  so the native negotiation plane can never mix epochs;
* a worker removed ``HVD_ELASTIC_MAX_FLAPS`` times is **blocklisted**
  and its rejoin announcements are ignored (flapping hosts must not
  thrash the job with rebuild churn);
* rejoin announcements are admitted at the next epoch boundary, once
  the current epoch is stable (every member acked its rebuild).

The driver never relaunches processes itself — that remains ``tpurun
--restarts``'s job, and the two compose: the driver shrinks past
failures while ``len(world) >= min_np``, and only when the floor is
violated does it give up, letting the restart loop do a full relaunch.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

from ..run.http_server import (
    ABORT_KEY,
    ABORT_SCOPE,
    ANNOUNCE_PREFIX,
    BLOCKLIST_KEY,
    DRAIN_ACK_PREFIX,
    DRAIN_PREFIX,
    EPOCH_KEY,
    HEALTH_SCOPE,
    MEMBERSHIP_SCOPE,
    PREEMPT_PREFIX,
    READY_PREFIX,
    SPARE_PREFIX,
    STATE_PREFIX,
)
from ..utils import env as env_util
from ..utils.logging import get_logger
from .abort import make_flag

log = get_logger(__name__)


class ElasticDriver:
    """Membership authority for one job incarnation.

    ``rdv_server``: the launcher's RendezvousServer (direct in-process
    access — the driver is its single membership writer).
    ``worker_ids``: the initial roster, in rank order.
    ``controller``: "native" stands up a per-epoch ControllerServer and
    publishes its address in each epoch record; anything else leaves the
    eager plane controller-less (compiled-schedule-only jobs, tests).
    """

    def __init__(self, rdv_server, worker_ids: Sequence[str], *,
                 min_np: int = 1, controller: str = "xla",
                 controller_host: str = "127.0.0.1",
                 max_flaps: Optional[int] = None,
                 drain_timeout: Optional[float] = None):
        self.server = rdv_server
        self.min_np = max(int(min_np), 1)
        self.controller = controller
        self.controller_host = controller_host
        self.max_flaps = int(
            max_flaps if max_flaps is not None
            else env_util.get_int(env_util.HVD_ELASTIC_MAX_FLAPS,
                                  env_util.DEFAULT_ELASTIC_MAX_FLAPS))
        self.epoch = -1
        self.initial = set(str(w) for w in worker_ids)
        self.world: List[str] = []
        self.flaps: Dict[str, int] = {}
        self.blocklist: set = set()
        self.finished: set = set()   # members that exited 0 (end of training)
        self.failed_reason: Optional[str] = None  # set when below min_np
        self.ctrl_server = None
        self.controller_addr: Optional[str] = None
        self._commit_time = 0.0
        self._stable = False
        self._hb_interval = env_util.get_float(
            env_util.HVD_HEARTBEAT_INTERVAL_SECONDS,
            env_util.DEFAULT_HEARTBEAT_INTERVAL_SECONDS)
        self._timeout = env_util.get_float(
            env_util.HVD_ELASTIC_TIMEOUT_SECONDS,
            env_util.DEFAULT_ELASTIC_TIMEOUT_SECONDS)
        self._drain_timeout = float(
            drain_timeout if drain_timeout is not None
            else env_util.get_float(
                env_util.HVD_SERVE_DRAIN_TIMEOUT_SECONDS, self._timeout))
        # chaos-found liveness gap: a member that stops renewing right
        # before an unrelated commit clears the health scope never gets
        # a dead verdict (its lease entry is simply gone).  With the
        # grace > 0, a stable-epoch member with NO re-established lease
        # that long past stability is removed as dead.
        self._silent_grace = env_util.get_float(
            env_util.HVD_ELASTIC_SILENT_GRACE_SECONDS,
            env_util.DEFAULT_ELASTIC_SILENT_GRACE_SECONDS)
        self._stable_time = 0.0
        # serving-plane hooks (serving/autoscaler.py): an attached
        # autoscaler ticks from poll() on stable epochs, and announced
        # workers are HELD as spares for it instead of auto-admitted
        self.autoscaler = None
        self.hold_admissions = False
        self.spares: List[str] = []
        # called as on_remove(worker, drained) after every removal
        # commit: the serving plane hooks it to requeue a lossily-
        # removed replica's in-flight requests (broker.requeue)
        self.on_remove = None
        self.commit(list(worker_ids), reason="initial world")

    # -- flight recorder (observe/events.py) ---------------------------------
    def _event(self, kind: str, severity: str = "info",
               payload: Optional[dict] = None,
               cause_id: Optional[str] = None,
               rank: Optional[int] = None) -> Optional[str]:
        """Record one flight-recorder event; never raises (the recorder
        must not fail a membership change)."""
        try:
            from ..observe import events as events_mod

            return events_mod.record_event(kind, severity=severity,
                                           payload=payload,
                                           cause_id=cause_id, rank=rank)
        except Exception:  # noqa: BLE001
            return None

    # -- epoch commits -------------------------------------------------------
    def commit(self, world: List[str], *, removed: Sequence[str] = (),
               admitted: Sequence[str] = (), reason: str = "",
               cause_id: Optional[str] = None) -> dict:
        """Commit the next membership epoch: rebuild the per-epoch
        controller server, publish the record, and reset the stability
        barrier.  Single writer — only the driver calls this."""
        self.epoch += 1
        self.world = list(world)
        if self.controller == "native":
            old = self.ctrl_server
            from ..runtime.controller import ControllerServer

            self.ctrl_server = ControllerServer(len(world), port=0)
            self.controller_addr = (
                f"{self.controller_host}:{self.ctrl_server.port}")
            if old is not None:
                # survivors' clients reconnect during reinit; the dead
                # epoch's server holds half-negotiated state and must go
                old.stop()
        rec = {
            "epoch": self.epoch,
            "world": self.world,
            "size": len(self.world),
            "removed": list(removed),
            "admitted": list(admitted),
            "controller_addr": self.controller_addr,
            "reason": reason,
            "time": time.time(),
        }
        # the commit event rides the epoch record itself, so workers
        # that observe the new epoch can chain their restart/resume
        # events onto it across processes
        eid = self._event(
            "epoch.commit",
            severity="warning" if (removed or admitted) else "info",
            payload={"epoch": self.epoch, "size": len(self.world),
                     "removed": list(removed), "admitted": list(admitted),
                     "reason": reason},
            cause_id=cause_id)
        if eid:
            rec["event_id"] = eid
            try:
                from ..observe import events as events_mod

                corr = events_mod.correlation_of(eid)
                if corr:
                    rec["correlation_id"] = corr
            except Exception:  # noqa: BLE001
                pass
        # health first: stale leases keyed by the OLD ranks must not read
        # as deaths in the new epoch (new heartbeats re-populate on ack)
        self.server.clear_scope(HEALTH_SCOPE)
        self.server.put(MEMBERSHIP_SCOPE, EPOCH_KEY,
                        json.dumps(rec).encode())
        self.server.put(MEMBERSHIP_SCOPE, BLOCKLIST_KEY,
                        json.dumps(sorted(self.blocklist)).encode())
        self._commit_time = time.monotonic()
        self._stable = False
        from .. import metrics

        if metrics.on():
            metrics.MEMBERSHIP_EPOCHS.inc()
            if removed:
                metrics.RANKS_REMOVED.inc(len(removed))
            if admitted:
                metrics.RANKS_ADMITTED.inc(len(admitted))
        log.warning("membership epoch %d committed: world=%s removed=%s "
                    "admitted=%s (%s)", self.epoch, self.world,
                    list(removed), list(admitted), reason)
        return rec

    # -- membership changes --------------------------------------------------
    def remove(self, worker: str, reason: str, *,
               drain: bool = False,
               cause_id: Optional[str] = None) -> bool:
        """Shrink the world past ``worker``.  Workers that already
        finished cleanly are drained from the roster in the same commit
        (they will never ack or heartbeat again — leaving them in would
        hang the stability barrier and hand rank 0 to an exited
        process).  Returns False (and records ``failed_reason``) when
        the LIVE remainder would violate ``min_np`` — the caller must
        then fail the job the fail-stop way.

        ``drain=True`` is the **lossless** scale-down path (serving
        autoscaler, planned maintenance): before anything is revoked or
        committed, the departing worker is asked to stop pulling new
        work, finish what it has in flight, and ack — the drain
        handshake (``drain.<worker>`` → ``drain_ack.<worker>`` under
        the membership scope).  Only after the ack (or the
        ``HVD_SERVE_DRAIN_TIMEOUT_SECONDS`` budget, in which case the
        removal degrades to the lossy path with a warning) is the
        shrink epoch committed, so a drained shrink loses zero
        requests/steps.  Voluntary drains do not count toward the
        flapping blocklist — a worker scaled down N times is not a
        flaky host."""
        if worker not in self.world:
            return True
        finished = [w for w in self.world
                    if w != worker and w in self.finished]
        survivors = [w for w in self.world
                     if w != worker and w not in self.finished]
        if len(survivors) < self.min_np:
            self.failed_reason = (
                f"{reason}; world would shrink to {len(survivors)} < "
                f"min_np {self.min_np}")
            return False
        old_rank = self.world.index(worker)
        remove_eid = self._event(
            "epoch.remove", severity="warning",
            payload={"worker": worker, "rank": old_rank, "reason": reason,
                     "drain": bool(drain)},
            cause_id=cause_id, rank=old_rank)
        drained_ok = False
        if drain:
            drained_ok = self._drain(worker, cause_id=remove_eid)
            if not drained_ok:
                log.warning(
                    "drain handshake with worker %s timed out after "
                    "%.1fs; removing it the lossy way", worker,
                    self._drain_timeout)
        if not drain:
            self.flaps[worker] = self.flaps.get(worker, 0) + 1
            if self.flaps[worker] >= self.max_flaps:
                self.blocklist.add(worker)
                self._event("epoch.blocklist", severity="critical",
                            payload={"worker": worker,
                                     "flaps": self.flaps[worker]},
                            cause_id=remove_eid)
                log.warning("worker %s blocklisted after %d removals",
                            worker, self.flaps[worker])
        # the lease itself is revoked by commit()'s HEALTH-scope reset
        self._publish_abort(reason, rank=old_rank, cause_id=remove_eid)
        if finished:
            reason = f"{reason} (drained finished worker(s) {finished})"
        if drained_ok:
            reason = f"{reason} (drained: in-flight work completed)"
        self.commit(survivors, removed=[worker], reason=reason,
                    cause_id=remove_eid)
        if self.on_remove is not None:
            try:
                self.on_remove(worker, drained_ok)
            except Exception:  # noqa: BLE001 — a hook bug must not
                log.exception("on_remove hook failed for worker %s",
                              worker)  # fail the membership change
        return True

    def _drain(self, worker: str,
               cause_id: Optional[str] = None) -> bool:
        """Run the drain handshake with ``worker``: publish the request
        key, wait for the ack, clean both keys up.  True iff the worker
        acked inside the budget.

        The wait is synchronous — supervision (lease expiry, child-exit
        reaping) pauses for up to ``HVD_SERVE_DRAIN_TIMEOUT_SECONDS``
        while a drain is in flight.  Drains are rare, operator/
        autoscaler-paced events; tune the budget down if concurrent
        failure reaction matters more than drain patience."""
        req_key = f"{DRAIN_PREFIX}{worker}"
        ack_key = f"{DRAIN_ACK_PREFIX}{worker}"
        drain_eid = self._event("epoch.drain",
                                payload={"worker": worker,
                                         "epoch": self.epoch,
                                         "timeout": self._drain_timeout},
                                cause_id=cause_id)
        # a stale ack from a previous timed-out handshake (acked just
        # past the deadline) must not read as an instant lossless drain
        self.server.delete(MEMBERSHIP_SCOPE, ack_key)
        self.server.put(MEMBERSHIP_SCOPE, req_key, json.dumps({
            "worker": worker, "epoch": self.epoch, "time": time.time(),
        }).encode())
        deadline = time.monotonic() + self._drain_timeout
        acked = False
        while time.monotonic() < deadline:
            if self.server.get(MEMBERSHIP_SCOPE, ack_key) is not None:
                acked = True
                break
            time.sleep(0.02)
        self.server.delete(MEMBERSHIP_SCOPE, req_key)
        self.server.delete(MEMBERSHIP_SCOPE, ack_key)
        self._event("epoch.drain_ack",
                    severity="info" if acked else "warning",
                    payload={"worker": worker, "acked": acked},
                    cause_id=drain_eid)
        if acked:
            from .. import metrics

            if metrics.on():
                metrics.SERVE_DRAINS.inc()
        return acked

    def admit(self, workers: Sequence[str],
              reason: str = "rejoin",
              cause_id: Optional[str] = None) -> Optional[dict]:
        """Grow the world by ``workers`` at this epoch boundary (the
        running members are interrupted through the same abort seam a
        shrink uses — rejoin is the shrink path in reverse)."""
        workers = [w for w in workers
                   if w not in self.blocklist and w not in self.world]
        if not workers:
            return None
        admit_eid = self._event("epoch.admit",
                                payload={"workers": list(workers),
                                         "epoch": self.epoch + 1,
                                         "reason": reason},
                                cause_id=cause_id)
        self._publish_abort(
            f"admitting worker(s) {workers} into epoch {self.epoch + 1}",
            rank=None, cause_id=admit_eid)
        return self.commit(self.world + list(workers), admitted=workers,
                           reason=reason, cause_id=admit_eid)

    def preempt(self, worker: str, grace: Optional[float] = None,
                cause_id: Optional[str] = None) -> bool:
        """Handle a preemption notice for ``worker`` (cloud maintenance
        signal, ``kind=preempt`` fault) as a **planned drain+snapshot**,
        not a crash: the worker is asked to finish in flight, snapshot,
        and ack inside the ``grace`` window (capped at the drain
        budget); only then is the shrink committed.  Voluntary, so it
        never counts toward the flapping blocklist.  Returns False when
        the shrink would violate ``min_np`` (same contract as
        :meth:`remove`)."""
        if worker not in self.world or worker in self.finished:
            return True
        eid = self._event(
            "preempt.notice", severity="warning",
            payload={"worker": worker, "grace": grace,
                     "epoch": self.epoch},
            cause_id=cause_id, rank=self.world.index(worker))
        old = self._drain_timeout
        if grace:
            self._drain_timeout = min(old, float(grace))
        try:
            return self.remove(
                worker,
                f"preemption notice for worker {worker} "
                f"(grace {self._drain_timeout:.1f}s)",
                drain=True, cause_id=eid)
        finally:
            self._drain_timeout = old

    # -- serving-plane hooks (serving/autoscaler.py) -------------------------
    def attach_autoscaler(self, autoscaler, *,
                          hold_admissions: bool = True) -> None:
        """Give load, not failures, control of the world: ``autoscaler
        .tick()`` runs from every stable-epoch poll, and (by default)
        announced workers are held in ``self.spares`` for it to admit
        instead of being auto-admitted at the next boundary."""
        self.autoscaler = autoscaler
        self.hold_admissions = hold_admissions

    def admit_spare(self, reason: str = "autoscale grow"
                    ) -> Optional[str]:
        """Admit the longest-held spare (FIFO) into the next epoch;
        returns its worker id, or None when no spare is available.

        Held spares DO carry a liveness signal: ``join_world`` renews an
        announce-keyed lease at ``health/spare.<worker>`` the whole time
        the worker waits, and :meth:`_purge_dead_spares` runs before
        each admission attempt — a spare that died while held is purged
        here (and from the stable-epoch poll) instead of being admitted,
        stalling the stability barrier for an elastic timeout, and only
        then being removed by rank-lease expiry."""
        self._purge_dead_spares()
        while self.spares:
            w = self.spares.pop(0)
            if w in self.blocklist or w in self.world:
                continue
            if self.admit([w], reason=reason) is not None:
                return w
        return None

    def _purge_dead_spares(self) -> None:
        """Drop held spares whose ``spare.<worker>`` lease went dead
        (elastic/membership.renew_spare_lease).  A spare with NO lease
        entry is left alone — its key may simply have been wiped by the
        last epoch commit's health-scope clear and not yet re-renewed;
        the dead verdict is the only affirmative death signal."""
        if not self.spares:
            return
        ranks = self.server.health_report().get("ranks", {})
        for w in list(self.spares):
            info = ranks.get(f"{SPARE_PREFIX}{w}")
            if info is None or info.get("verdict") != "dead":
                continue
            self.spares.remove(w)
            self.server.delete(HEALTH_SCOPE, f"{SPARE_PREFIX}{w}")
            self._event("spare.purged", severity="warning",
                        payload={"worker": w,
                                 "age_seconds": info.get("age_seconds"),
                                 "held": len(self.spares)})
            log.warning("purged dead spare %s (lease age %.1fs); %d "
                        "spare(s) still held", w,
                        info.get("age_seconds") or -1.0, len(self.spares))

    def _publish_abort(self, reason: str, rank: Optional[int],
                       cause_id: Optional[str] = None) -> None:
        """Stamp the flag with the epoch being aborted so survivors that
        already rebuilt ignore it (elastic/heartbeat.py epoch filter)."""
        flag = make_flag(reason, rank=rank, source="elastic_driver",
                         epoch=self.epoch)
        eid = self._event("abort.publish", severity="critical",
                          payload={"reason": reason, "epoch": self.epoch,
                                   "source": "elastic_driver"},
                          cause_id=cause_id, rank=rank)
        if eid:
            flag["event_id"] = eid
            try:
                from ..observe import events as events_mod

                corr = events_mod.correlation_of(eid)
                if corr:
                    flag["correlation_id"] = corr
            except Exception:  # noqa: BLE001
                pass
        self.server.put(ABORT_SCOPE, ABORT_KEY, json.dumps(flag).encode())

    # -- the periodic poll ---------------------------------------------------
    def _ready_workers(self, epoch: int) -> set:
        prefix = f"{READY_PREFIX}{epoch}."
        return {k[len(prefix):]
                for k in self.server.scope_items(MEMBERSHIP_SCOPE)
                if k.startswith(prefix)}

    def _announced(self) -> set:
        return {k[len(ANNOUNCE_PREFIX):]
                for k in self.server.scope_items(MEMBERSHIP_SCOPE)
                if k.startswith(ANNOUNCE_PREFIX)}

    def _gc(self) -> None:
        """Drop rebuild artifacts of finished epochs (state blobs and
        ready acks below the current epoch) so a long-lived job's store
        stays bounded."""
        for key in list(self.server.scope_items(MEMBERSHIP_SCOPE)):
            for prefix in (STATE_PREFIX, READY_PREFIX):
                if key.startswith(prefix):
                    epoch_s = key[len(prefix):].split(".", 1)[0]
                    if epoch_s.isdigit() and int(epoch_s) < self.epoch:
                        self.server.delete(MEMBERSHIP_SCOPE, key)

    def poll(self) -> None:
        """One supervision tick: advance the stability barrier, remove
        lease-dead members, admit pending announcements."""
        now = time.monotonic()
        if not self._stable:
            acked = self._ready_workers(self.epoch)
            if set(self.world) <= acked:
                self._stable = True
            elif now - self._commit_time > self._timeout:
                log.warning(
                    "epoch %d stability timeout: %s never acked; "
                    "proceeding without the barrier", self.epoch,
                    sorted(set(self.world) - acked))
                self._stable = True
            if self._stable:
                # the aborted epoch is fully drained: the flag and the
                # old rebuild artifacts can go
                self._stable_time = now
                self.server.clear_scope(ABORT_SCOPE)
                self._gc()
        # lease expiry (partitions, silent deaths of external members):
        # enforced only on a STABLE epoch — mid-rebuild, a survivor may
        # legitimately be silent for a whole step/save between observing
        # the abort and restarting its heartbeat, and that silence must
        # not read as a second failure
        if self._stable and now - self._commit_time > 2.0 * self._hb_interval:
            report = self.server.health_report()
            # rank keys in the report refer to THIS roster; a mid-loop
            # remove() re-assigns ranks densely, so indexing self.world
            # with later keys would name the wrong (live) worker
            roster = list(self.world)
            for rank_s, info in report.get("ranks", {}).items():
                if info.get("verdict") != "dead":
                    continue
                if not rank_s.isdigit() or int(rank_s) >= len(roster):
                    continue  # a stale key from a previous epoch
                worker = roster[int(rank_s)]
                if worker in self.finished or worker not in self.world:
                    continue  # exited 0 / already removed this pass
                lease_eid = self._event(
                    "lease.expired", severity="critical",
                    payload={"rank": int(rank_s), "worker": worker,
                             "age_seconds": info.get("age_seconds"),
                             "interval": info.get("interval")},
                    rank=int(rank_s))
                self.remove(worker, f"rank {rank_s} (worker {worker}) "
                            "heartbeat lease expired",
                            cause_id=lease_eid)
            # the silent-member sweep: a lease entry wiped by a commit's
            # health-scope clear and never re-established leaves a dead
            # member with NO verdict at all — after the (opt-in) grace
            # past stability, missing reads as dead too
            if self._silent_grace > 0 and self._stable \
                    and now - self._stable_time > self._silent_grace:
                ranks = report.get("ranks", {})
                for i, worker in enumerate(roster):
                    if not self._stable:
                        break  # a removal above re-opened the epoch
                    if str(i) in ranks or worker not in self.world \
                            or worker in self.finished:
                        continue
                    eid = self._event(
                        "lease.expired", severity="critical",
                        payload={"rank": i, "worker": worker,
                                 "silent": True,
                                 "grace": self._silent_grace},
                        rank=i)
                    self.remove(
                        worker, f"rank {i} (worker {worker}) never "
                        "re-established its heartbeat lease",
                        cause_id=eid)
        if self._stable:
            # pending preemption notices become planned drains at the
            # next stable boundary (mid-rebuild, the key just waits)
            items = self.server.scope_items(MEMBERSHIP_SCOPE)
            for key in sorted(items):
                if not key.startswith(PREEMPT_PREFIX):
                    continue
                if not self._stable:
                    break  # an earlier preempt re-opened the epoch
                worker = key[len(PREEMPT_PREFIX):]
                grace = None
                try:
                    grace = json.loads(items[key]).get("grace")
                except (ValueError, TypeError):
                    pass
                self.server.delete(MEMBERSHIP_SCOPE, key)
                self.preempt(worker, grace=grace)
        if self._stable and self.failed_reason is None \
                and not self.finished:
            # no admissions once any member finished: the job is winding
            # down, and a joiner would inherit a roster of exiting peers
            self._purge_dead_spares()
            announced = self._announced()
            for w in sorted(announced & self.blocklist):
                # a blocklisted flapper's announce can never be admitted;
                # leaving the key would read as a forever-pending rejoin
                self.server.delete(MEMBERSHIP_SCOPE, f"{ANNOUNCE_PREFIX}{w}")
            pending = sorted(announced - set(self.world) - self.blocklist)
            if pending:
                for w in pending:
                    self.server.delete(MEMBERSHIP_SCOPE,
                                       f"{ANNOUNCE_PREFIX}{w}")
                if self.hold_admissions:
                    # serving mode: spares are capacity-in-reserve for
                    # the autoscaler, not immediate members
                    self.spares.extend(w for w in pending
                                       if w not in self.spares)
                    log.info("holding announced worker(s) %s as spares "
                             "(%d held)", pending, len(self.spares))
                else:
                    self.admit(pending)
            if self.autoscaler is not None:
                try:
                    self.autoscaler.tick()
                except Exception:  # noqa: BLE001 — a policy bug must
                    log.exception(   # not take down supervision
                        "serving autoscaler tick failed")

    # -- supervision ---------------------------------------------------------
    def supervise(self, job, poll_interval: float = 0.2) -> int:
        """Drive the job to completion: ``job.procs[i]`` is the child of
        initial worker ``str(i)``.  Child failures shrink the world (or
        fail the job below ``min_np``); externally admitted workers are
        tracked through their leases only.  Returns 0 when every worker
        still in the world exited cleanly."""
        procs = job.procs
        handled: set = set()
        while True:
            self.poll()
            states = [p.poll() for p in procs]
            for wid, code in enumerate(states):
                w = str(wid)
                if code is None or w in handled:
                    continue
                handled.add(w)
                if code == 0:
                    if w in self.world:
                        # a MEMBER exiting 0 means end of training: the
                        # job is winding down (admissions pause)
                        self.finished.add(w)
                    else:
                        # a worker the autoscaler drained out of the
                        # world exits 0 as the normal end of its
                        # removal — that must NOT read as the job
                        # winding down, or the first serving scale-
                        # down would freeze autoscaling forever
                        log.info("removed worker %s exited cleanly", w)
                    continue
                if w in self.world:
                    if not self.remove(
                            w, f"worker {w} exited with code {code}"):
                        log.error("elastic give-up: %s", self.failed_reason)
                        self._publish_giveup(self.failed_reason)
                        job.kill_all()
                        return code
                else:
                    log.info("already-removed worker %s exited with code "
                             "%d", w, code)
            if self.failed_reason is not None:
                # a lease-expiry removal inside poll() hit the min_np
                # floor: fail the job the fail-stop way
                log.error("elastic give-up: %s", self.failed_reason)
                self._publish_giveup(self.failed_reason)
                job.kill_all()
                return 1
            if all(c is not None for c in states):
                bad = [c for wid, c in enumerate(states)
                       if str(wid) in self.world and c != 0]
                if not bad:
                    self._drain_external()
                return bad[0] if bad else 0
            time.sleep(poll_interval)

    def _drain_external(self) -> None:
        """Externally admitted joiners have no child process to wait on;
        give them up to the elastic timeout to finish (their heartbeat
        lease going dead is the exit signal) before the launcher tears
        the rendezvous down from under them.  Their exit codes cannot be
        observed — a joiner's failure does not change the job result."""
        external = set(self.world) - self.initial - self.finished
        if not external:
            return
        log.info("waiting up to %.0fs for externally admitted worker(s) "
                 "%s to finish", self._timeout, sorted(external))
        deadline = time.monotonic() + self._timeout
        while time.monotonic() < deadline:
            report = self.server.health_report()
            live = set()
            for w in external:
                if w not in self.world:
                    continue
                info = report.get("ranks", {}).get(
                    str(self.world.index(w)))
                if info is not None and info.get("verdict") != "dead":
                    live.add(w)
            if not live:
                return
            time.sleep(0.5)
        log.warning("externally admitted worker(s) still live at "
                    "teardown: %s", sorted(external))

    def _publish_giveup(self, reason: Optional[str]) -> None:
        """An epoch-less abort flag: honored by EVERY epoch, so all
        survivors (including external joiners) stop."""
        flag = make_flag(reason or "elastic driver gave up", rank=None,
                         source="elastic_driver")
        eid = self._event("epoch.giveup", severity="critical",
                          payload={"reason": reason,
                                   "min_np": self.min_np,
                                   "epoch": self.epoch})
        if eid:
            flag["event_id"] = eid
            try:
                from ..observe import events as events_mod

                corr = events_mod.correlation_of(eid)
                if corr:
                    flag["correlation_id"] = corr
            except Exception:  # noqa: BLE001
                pass
            # the launcher's restart loop chains restart.attempt onto
            # the give-up that triggered the relaunch (run/run.py)
            self.last_giveup_event_id = eid
        self.server.put(ABORT_SCOPE, ABORT_KEY, json.dumps(flag).encode())

    def shutdown(self) -> None:
        if self.ctrl_server is not None:
            self.ctrl_server.stop()
            self.ctrl_server = None
