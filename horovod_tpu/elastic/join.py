"""Join: uneven-data participation.

Reference semantics (horovod/common/operations.cc:922-946 EnqueueJoin,
controller.cc:73-77,210-213,253-264,291-298, zero-fill in
collective_operations.cc:217-225, torch binding horovod/torch/__init__.py:42):
a rank that has exhausted its data calls ``hvd.join()`` and from then on
participates in every allreduce with zero tensors until all ranks join; the
average divides by the number of *non-joined* ranks.  Allgather/broadcast
are unsupported under Join (controller.cc:453-456,527-531) — same here.

TPU-native form: under SPMD there is no per-rank control flow divergence —
every rank runs the same compiled step.  Joined-ness becomes a per-rank
boolean *input* (``active``), and :func:`join_allreduce` masks contributions
and divides by the active count.  This is the compiled-world expression of
the same contract, and it is how uneven dataset tails are handled in the
DataLoader shim (data/loader.py): the last partial batch runs with
``active=False`` on ranks that ran out.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import core
from ..core import Average, Sum


def join_allreduce(tensor, active, *, op: str = Average):
    """Allreduce where ranks with ``active == False`` contribute zeros and
    Average divides by the number of active ranks (min 1).

    ``active``: per-rank bool scalar (traced).
    """
    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError("join_allreduce must run inside an SPMD region")
    axis = axes if len(axes) > 1 else axes[0]
    act = jnp.asarray(active)
    masked = jnp.where(act, tensor, jnp.zeros_like(tensor))
    total = lax.psum(masked, axis)
    if op == Sum:
        return total
    if op == Average:
        count = lax.psum(act.astype(jnp.float32), axis)
        return total / jnp.maximum(count, 1.0)
    raise ValueError(f"join_allreduce supports Average/Sum, got {op!r}")


def join_count(active):
    """Number of active (non-joined) ranks this step."""
    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError("join_count must run inside an SPMD region")
    axis = axes if len(axes) > 1 else axes[0]
    return lax.psum(jnp.asarray(active).astype(jnp.int32), axis)


def join() -> int:
    """Host-level join barrier for the eager/process plane.

    Blocks until every controller process has called join; returns the last
    rank to join (reference returns the last joining rank so callers can
    detect stragglers; horovod/torch/mpi_ops.py join()).  Single-process:
    returns this process's rank immediately.
    """
    core._require_init()
    from .. import metrics

    if metrics.on():
        metrics.JOIN_EVENTS.inc()
    if core.process_size() == 1:
        return core.process_rank()
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("hvd_join")
    return core.process_size() - 1
