"""Elastic / failure-domain runtime.

Two halves:

* **join** (join.py) — uneven-data participation, the reference's
  ``hvd.join()`` contract in compiled-SPMD form.
* **failure-domain runtime** (abort.py, heartbeat.py, state.py,
  faults.py; docs/fault_tolerance.md) — heartbeat leases with a
  ``GET /health`` view, one job-wide coordinated abort flag raised as
  :class:`HorovodAbortError` at the dispatch/train-step seams,
  :class:`ElasticState` auto-resume under ``tpurun --restarts``, and the
  ``HVD_FAULT_SPEC`` fault-injection harness that tests all of it.
"""

from .abort import HorovodAbortError, abort  # noqa: F401
from .state import ElasticState  # noqa: F401
from . import faults, heartbeat  # noqa: F401
