"""Elastic / failure-domain runtime.

Three layers:

* **join** (join.py) — uneven-data participation, the reference's
  ``hvd.join()`` contract in compiled-SPMD form.
* **failure-domain runtime** (abort.py, heartbeat.py, state.py,
  faults.py; docs/fault_tolerance.md) — heartbeat leases with a
  ``GET /health`` view, one job-wide coordinated abort flag raised as
  :class:`HorovodAbortError` at the dispatch/train-step seams,
  :class:`ElasticState` auto-resume under ``tpurun --restarts``, and the
  ``HVD_FAULT_SPEC`` fault-injection harness that tests all of it.
  The **peer state plane** (peerstate.py, ``HVD_SNAPSHOT=1``) layers
  async K-peer-replicated snapshots over the storage checkpoints:
  µs-stall step path, restore-from-peers in sub-seconds, storage tier
  demoted to a slow durable backstop.
* **elastic membership** (membership.py worker side, driver.py launcher
  side; ``tpurun --elastic``) — shrink/grow worlds through committed
  membership epochs: survivors rebuild in process (``core.reinit()``),
  ranks are re-assigned densely, state re-syncs via rank-0 in-memory
  broadcast, and spare hosts rejoin at epoch boundaries without a
  relaunch.  :func:`run` is the ``@hvd.elastic.run`` analog.
"""

from .abort import HorovodAbortError, abort  # noqa: F401
from .state import ElasticState  # noqa: F401
from .membership import (  # noqa: F401
    RemovedFromWorldError,
    join_world,
    run,
)
from . import driver, faults, heartbeat, membership, peerstate  # noqa: F401
