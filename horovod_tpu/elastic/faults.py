"""Fault-injection harness: drive detect→abort→restart→resume on purpose.

A fault-tolerance path that only runs when hardware actually dies is an
untested path.  ``HVD_FAULT_SPEC`` injects failures at three seams so
tests (tests/test_elastic_runtime.py and the tier-1 tpurun smoke) exercise
the full failure-domain loop deterministically:

* **step** — the train-step callback (training.py) and any loop that
  calls :func:`on_step` directly;
* **dispatch** — every eager collective dispatch
  (eager._dispatch_guard);
* **http** — the rendezvous HTTP client (run/http_client.py), to
  exercise its retry/backoff path;
* **controller** — the eager-plane negotiation handshake
  (runtime/eager_controller.negotiate);
* **peer_push** / **peer_pull** — the peer state plane's shard upload
  and restore reads (elastic/peerstate.py).  ``peer_push`` is a
  *mutating* seam: a ``corrupt`` fault flips bytes in the shard on its
  way to the replica, so the checksum-reject → storage-fallback path is
  drivable end to end; ``peer_pull`` fires before each shard fetch, so
  ``http_drop`` / ``partition`` there model a peer dying mid-restore.

Grammar (specs separated by ``;``, fields by ``:``, ``key=value``)::

    HVD_FAULT_SPEC="rank=1:step=3:kind=crash"
    HVD_FAULT_SPEC="rank=*:kind=slow=200ms:prob=0.5;rank=0:step=10:kind=hang"
    HVD_FAULT_SPEC="kind=http_drop:prob=0.3:restart=*"
    HVD_FAULT_SPEC="rank=1:step=4:kind=partition"
    HVD_FAULT_SPEC="kind=corrupt:seam=peer_push:restart=*"
    HVD_FAULT_SPEC="kind=http_drop:seam=peer_pull:restart=*"

Fields:

``rank``     int or ``*`` (default ``*``): the HVD_PROCESS_ID it fires on.
``step``     int or ``*`` (default ``*``): the 0-based invocation counter
             of the seam in this process (each seam counts separately).
``kind``     ``crash`` (``os._exit(17)`` — a sudden worker death),
             ``hang`` (sleep forever, the wedged-collective shape),
             ``slow=<dur>`` (inject ``<dur>`` latency, e.g. ``200ms`` /
             ``1.5s``, then continue), ``http_drop`` (raise
             ``URLError`` from the HTTP client), ``partition`` (a
             network split: from the firing point on, EVERY rendezvous
             HTTP request raises ``URLError`` and every controller
             negotiation raises ``TimeoutError``, while the process
             itself stays alive — heartbeat leases expire and the
             elastic driver removes the rank without a process death),
             ``corrupt`` (flip bytes in the payload at a mutating
             seam — only ``peer_push`` today; elsewhere it is a no-op),
             or ``preempt[=<grace>]`` (deliver a grace-window
             preemption notice: the worker publishes
             ``membership/preempt.<worker>`` and keeps training; the
             elastic driver's poll turns the notice into a planned
             drain+snapshot — elastic/driver.preempt — instead of a
             crash.  Fires at most once per process).
``prob``     float in [0, 1] (default 1.0).
``seam``     ``step`` / ``dispatch`` / ``http`` / ``controller`` /
             ``peer_push`` / ``peer_pull``; defaults to ``http`` for
             ``http_drop``, ``peer_push`` for ``corrupt``, and ``step``
             otherwise.
``restart``  int or ``*`` (default 0): the ``HVD_RESTART_COUNT``
             incarnation the fault applies to.  The default means a
             crash fires on the first run only, so a supervised restart
             (tpurun --restarts) relaunches into a clean incarnation.
"""

from __future__ import annotations

import os
import random
import re
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)

#: exit code of an injected ``crash`` — distinguishable from real failures
#: in launcher logs and test assertions.
FAULT_EXIT_CODE = 17

KINDS = ("crash", "hang", "slow", "http_drop", "partition", "corrupt",
         "preempt")
SEAMS = ("step", "dispatch", "http", "controller", "peer_push",
         "peer_pull")

_DURATION = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m)?$")
_DUR_SCALE = {"ms": 1e-3, "s": 1.0, "m": 60.0, None: 1.0}


class FaultSpecError(ValueError):
    """``HVD_FAULT_SPEC`` did not parse; the message pins the bad field."""


@dataclass(frozen=True)
class Fault:
    kind: str
    seam: str
    rank: Optional[int] = None      # None = any rank
    step: Optional[int] = None      # None = every invocation
    restart: Optional[int] = 0      # None = every incarnation
    prob: float = 1.0
    duration: float = 0.0           # slow: injected latency, seconds


def parse_duration(text: str) -> float:
    m = _DURATION.match(text.strip())
    if not m:
        raise FaultSpecError(f"bad duration {text!r} (want e.g. 200ms, 1.5s)")
    return float(m.group(1)) * _DUR_SCALE[m.group(2)]


def _int_or_any(value: str, field: str) -> Optional[int]:
    if value == "*":
        return None
    try:
        return int(value)
    except ValueError:
        raise FaultSpecError(f"bad {field}={value!r} (want an int or '*')")


def parse_spec(text: str) -> List[Fault]:
    """Parse one ``HVD_FAULT_SPEC`` value into its fault list."""
    faults: List[Fault] = []
    for chunk in text.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        fields = {}
        for field in chunk.split(":"):
            key, sep, value = field.partition("=")
            key = key.strip()
            if not sep or not key:
                raise FaultSpecError(
                    f"bad field {field!r} in {chunk!r} (want key=value)")
            fields[key] = value.strip()
        unknown = set(fields) - {"rank", "step", "kind", "prob", "seam",
                                 "restart"}
        if unknown:
            raise FaultSpecError(
                f"unknown field(s) {sorted(unknown)} in {chunk!r}")
        if "kind" not in fields:
            raise FaultSpecError(f"missing kind= in {chunk!r}")
        kind, _, arg = fields["kind"].partition("=")
        if kind not in KINDS:
            raise FaultSpecError(
                f"unknown kind {kind!r} in {chunk!r} (want one of {KINDS})")
        duration = 0.0
        if kind == "slow":
            if not arg:
                raise FaultSpecError(
                    f"kind=slow needs a duration (slow=200ms) in {chunk!r}")
            duration = parse_duration(arg)
        elif kind == "preempt":
            # optional grace window: preempt=30s; 0 means "driver default"
            duration = parse_duration(arg) if arg else 0.0
        elif arg:
            raise FaultSpecError(
                f"kind={kind} takes no argument (got {arg!r}) in {chunk!r}")
        default_seam = {"http_drop": "http",
                        "corrupt": "peer_push"}.get(kind, "step")
        seam = fields.get("seam", default_seam)
        if seam not in SEAMS:
            raise FaultSpecError(
                f"unknown seam {seam!r} in {chunk!r} (want one of {SEAMS})")
        prob = float(fields.get("prob", 1.0))
        if not 0.0 <= prob <= 1.0:
            raise FaultSpecError(f"prob={prob} out of [0, 1] in {chunk!r}")
        faults.append(Fault(
            kind=kind, seam=seam,
            rank=_int_or_any(fields.get("rank", "*"), "rank"),
            step=_int_or_any(fields.get("step", "*"), "step"),
            restart=_int_or_any(fields.get("restart", "0"), "restart"),
            prob=prob, duration=duration,
        ))
    return faults


class FaultInjector:
    """One process's armed fault set.  Each seam keeps its own 0-based
    invocation counter; a matching fault acts when the counter, rank,
    incarnation, and probability all line up."""

    def __init__(self, faults: List[Fault], rank: int, restart: int,
                 seed: Optional[int] = None):
        self.faults = list(faults)
        self.rank = int(rank)
        self.restart = int(restart)
        self._counts = {seam: 0 for seam in SEAMS}
        self._lock = threading.Lock()
        # probabilistic faults draw from a PER-INJECTOR stream: with
        # HVD_FAULT_SEED set, the seed is mixed with rank + incarnation
        # so every process draws a distinct but replayable sequence —
        # a failing prob= chaos run reproduces under the same seed
        if seed is None:
            self._rng = random.Random()
        else:
            self._rng = random.Random(
                (int(seed) * 0x9E3779B1
                 + self.rank * 0x85EBCA6B
                 + self.restart * 0xC2B2AE35) & 0xFFFFFFFF)
        # once a `partition` fault fires, this process's rendezvous +
        # controller traffic is dropped for good (the network-split shape)
        self.partitioned = False
        # a `preempt` fault delivers its notice at most once
        self.preempted = False

    def fire(self, seam: str, detail: str = "") -> None:
        with self._lock:
            n = self._counts[seam]
            self._counts[seam] = n + 1
        for f in self.faults:
            if f.seam != seam:
                continue
            if f.rank is not None and f.rank != self.rank:
                continue
            if f.restart is not None and f.restart != self.restart:
                continue
            if f.step is not None and f.step != n:
                continue
            if f.prob < 1.0 and self._rng.random() >= f.prob:
                continue
            self._act(f, seam, n, detail)

    def mutate(self, seam: str, data: bytes) -> bytes:
        """The mutating variant of :meth:`fire` for seams that carry a
        payload (``peer_push``): a matching ``corrupt`` fault flips
        bytes in ``data``; any other matching kind acts as usual.  The
        seam's invocation counter advances exactly once per call."""
        with self._lock:
            n = self._counts[seam]
            self._counts[seam] = n + 1
        for f in self.faults:
            if f.seam != seam:
                continue
            if f.rank is not None and f.rank != self.rank:
                continue
            if f.restart is not None and f.restart != self.restart:
                continue
            if f.step is not None and f.step != n:
                continue
            if f.prob < 1.0 and self._rng.random() >= f.prob:
                continue
            if f.kind == "corrupt":
                from .. import metrics

                if metrics.on():
                    metrics.FAULTS_INJECTED.labels(f.kind).inc()
                log.warning(
                    "fault injection: corrupt at %s[%d] rank=%d "
                    "restart=%d (%d bytes)", seam, n, self.rank,
                    self.restart, len(data))
                data = _flip_bytes(data)
            else:
                self._act(f, seam, n, f"{len(data)}B")
        return data

    def _act(self, f: Fault, seam: str, n: int, detail: str) -> None:
        from .. import metrics

        if metrics.on():
            metrics.FAULTS_INJECTED.labels(f.kind).inc()
        log.warning("fault injection: %s at %s[%d] rank=%d restart=%d %s",
                    f.kind, seam, n, self.rank, self.restart, detail)
        if f.kind == "crash":
            os._exit(FAULT_EXIT_CODE)
        elif f.kind == "hang":
            while True:  # the wedged-worker shape: only a signal ends it
                time.sleep(3600)
        elif f.kind == "slow":
            time.sleep(f.duration)
        elif f.kind == "partition":
            self.partitioned = True
        elif f.kind == "http_drop":
            import urllib.error

            raise urllib.error.URLError(
                f"injected http_drop at {seam}[{n}] {detail}")
        elif f.kind == "preempt":
            self._deliver_preemption(f.duration)
        # `corrupt` outside a mutating seam has no payload to flip — the
        # log line above is its only effect

    def _deliver_preemption(self, grace: float) -> None:
        """Publish a one-shot preemption notice for this worker; the
        elastic driver handles it as a planned drain+snapshot
        (elastic/driver.preempt).  The process keeps training inside
        the grace window — preemption is NOT a crash."""
        if self.preempted:
            return
        self.preempted = True
        try:
            from . import membership

            membership.notify_preemption(grace or None)
        except Exception as e:  # noqa: BLE001 — a worker without
            # rendezvous wiring still marks itself preempted; the
            # notice simply cannot reach a driver
            log.warning("preemption notice could not be published: %s", e)


def _flip_bytes(data: bytes) -> bytes:
    """Deterministic corruption: XOR a stride of bytes so any CRC32
    content checksum rejects the shard (elastic/peerstate.py)."""
    if not data:
        return b"\xff"
    out = bytearray(data)
    stride = max(len(out) // 8, 1)
    for i in range(0, len(out), stride):
        out[i] ^= 0xFF
    return bytes(out)


# ---------------------------------------------------------------------------
# process-wide wiring (built lazily from HVD_FAULT_SPEC, like the sanitizer)
# ---------------------------------------------------------------------------
_UNSET = object()
_instance = _UNSET
_instance_lock = threading.Lock()


def _build_from_env() -> Optional[FaultInjector]:
    spec = env_util.get_str(env_util.HVD_FAULT_SPEC)
    if not spec:
        return None
    faults = parse_spec(spec)  # a malformed spec must fail loudly, not arm 0
    if not faults:
        return None
    rank = env_util.get_int(env_util.HVD_PROCESS_ID, 0)
    restart = env_util.get_int(env_util.HVD_RESTART_COUNT, 0)
    seed: Optional[int] = None
    seed_raw = env_util.get_str(env_util.HVD_FAULT_SEED)
    if seed_raw is not None:
        try:
            seed = int(seed_raw)
        except ValueError:
            raise FaultSpecError(
                f"bad {env_util.HVD_FAULT_SEED}={seed_raw!r} (want an int)")
    inj = FaultInjector(faults, rank, restart, seed=seed)
    log.warning("fault injection armed: %d fault(s) on rank %d "
                "(incarnation %d): %s", len(faults), rank, restart, spec)
    return inj


def instance() -> Optional[FaultInjector]:
    global _instance
    if _instance is _UNSET:
        with _instance_lock:
            if _instance is _UNSET:
                _instance = _build_from_env()
    return _instance


def reset() -> None:
    """Drop the cached injector (tests / re-init re-read the env)."""
    global _instance
    with _instance_lock:
        _instance = _UNSET


def on_step() -> None:
    """The train-step seam (training.py; callable from any train loop)."""
    inj = instance()
    if inj is not None:
        inj.fire("step")


def on_dispatch(name: str) -> None:
    """The eager-dispatch seam (eager._dispatch_guard)."""
    inj = instance()
    if inj is not None:
        inj.fire("dispatch", detail=name)


def on_http(path: str) -> None:
    """The HTTP-client seam (run/http_client._request).  A partitioned
    process drops every rendezvous request from the firing point on."""
    inj = instance()
    if inj is not None:
        inj.fire("http", detail=path)
        if inj.partitioned:
            import urllib.error

            raise urllib.error.URLError(
                f"injected partition: rendezvous traffic dropped ({path})")


def on_peer_push(data: bytes) -> bytes:
    """The shard-upload seam (elastic/peerstate.py snapshot push).  A
    ``corrupt`` fault returns flipped bytes — the replica lands with a
    checksum that can never verify, driving the checksum-reject →
    next-replica → storage-fallback chain in tier-1."""
    inj = instance()
    if inj is None:
        return data
    return inj.mutate("peer_push", data)


def on_peer_pull(key: str) -> None:
    """The shard-fetch seam (elastic/peerstate.py restore).  An
    ``http_drop`` or ``partition`` here is a peer dying mid-restore:
    the puller falls to the next replica, then to the storage tier."""
    inj = instance()
    if inj is not None:
        inj.fire("peer_pull", detail=key)
        if inj.partitioned:
            import urllib.error

            raise urllib.error.URLError(
                f"injected partition: peer shard traffic dropped ({key})")


def on_controller(name: str) -> None:
    """The controller-negotiation seam (runtime/eager_controller.
    negotiate).  A partitioned process's negotiations time out the way a
    real network split's would."""
    inj = instance()
    if inj is not None:
        inj.fire("controller", detail=name)
        if inj.partitioned:
            raise TimeoutError(
                f"injected partition: controller traffic dropped for "
                f"{name!r}")
