"""Coordinated abort: one job-wide flag instead of N hanging ranks.

The reference's only reaction to a wedged rank is per-rank: the stall
inspector warns, then ``HOROVOD_STALL_SHUTDOWN`` hard-exits *that* rank
(reference stall_inspector.h:42) — and every other rank keeps blocking in
its next collective until a transport timeout, with no root cause in any
log.  Here the failure domain is the *job*: a single abort flag lives on
the launcher's rendezvous KV store (run/http_server.py ``abort`` scope),
set by whichever plane notices the failure first —

* the launcher's supervision loop, on a worker death (run/run.py);
* the stall inspector's shutdown path (runtime/stall_inspector.py);
* any rank, via :func:`abort` (application code that detects an
  unrecoverable condition, e.g. the sanitizer's divergence handler).

Each rank's heartbeat thread (elastic/heartbeat.py) polls the flag every
lease interval; the next eager dispatch or train-step raises
:class:`HorovodAbortError` naming the dead/diverging rank and the reason,
so surviving ranks exit in seconds with a diagnosis instead of hanging
until a collective timeout.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from ..run.http_server import ABORT_KEY, ABORT_SCOPE  # noqa: F401 — the
#   wire constants live with the server (single source of truth for the
#   /abort/flag key); re-exported here for the runtime side
from ..utils import env as env_util
from ..utils.logging import get_logger

log = get_logger(__name__)


class HorovodAbortError(RuntimeError):
    """The job was aborted by the coordinated-abort protocol.  The message
    names the source plane, the failing rank (when known), and the reason
    recorded by whoever set the flag."""


def format_abort(info: dict) -> str:
    who = info.get("rank")
    src = info.get("source", "unknown")
    epoch = info.get("epoch")
    parts = [f"reported by {src}"]
    if who is not None:
        parts.append(f"failing rank {who}")
    if epoch is not None:
        parts.append(f"membership epoch {epoch}")
    where = f" ({', '.join(parts)})"
    return f"coordinated abort: {info.get('reason', '<no reason>')}{where}"


def _rendezvous_from_env():
    """(addr, port, secret) of the launcher's rendezvous server, from the
    same wiring the metrics pusher and sanitizer ride — or None when this
    process was not launched under tpurun / run()."""
    addr = env_util.get_str(env_util.HVD_METRICS_KV_ADDR)
    port = env_util.get_int(env_util.HVD_METRICS_KV_PORT, 0)
    if not addr or not port:
        return None
    secret_hex = env_util.get_str(env_util.HVD_METRICS_SECRET)
    secret = bytes.fromhex(secret_hex) if secret_hex else None
    return addr, port, secret


def make_flag(reason: str, *, rank: Optional[int] = None,
              source: str = "api", epoch: Optional[int] = None) -> dict:
    """``epoch`` scopes the flag to one membership epoch: the elastic
    driver stamps the epoch it is aborting, and heartbeats of a LATER
    epoch ignore the flag (a survivor that already rebuilt must not be
    re-aborted by the stale flag of the world it just left).  ``None``
    (the launcher/stall/api flags) is honored by every epoch."""
    if rank is None:
        rank = env_util.get_int(env_util.HVD_PROCESS_ID, -1)
        rank = rank if rank >= 0 else None
    flag = {
        "reason": str(reason),
        "rank": rank,
        "source": source,
        "pid": os.getpid(),
        "time": time.time(),
    }
    if epoch is not None:
        flag["epoch"] = int(epoch)
    return flag


def publish(flag: dict, *, addr: Optional[str] = None,
            port: Optional[int] = None, secret: Optional[bytes] = None,
            timeout: float = 10.0) -> bool:
    """Publish one prebuilt abort flag (best-effort, never raises).
    Explicit ``addr``/``port`` override the env wiring; returns False
    when no rendezvous server is reachable — callers must still fail
    locally.  ``timeout`` bounds each HTTP attempt: exit paths (the
    stall shutdown) pass a short one so a dead server cannot delay the
    local exit by the full retry budget."""
    if addr is None or port is None:
        wired = _rendezvous_from_env()
        if wired is None:
            log.debug("abort flag %r: no rendezvous wiring",
                      flag.get("reason"))
            return False
        addr, port, secret = wired
    # flight-recorder: the abort flag is a chain link — the event id
    # rides the flag itself, so every rank that OBSERVES it (heartbeat)
    # can chain its own abort.observe onto this publish, across
    # processes (observe/events.py)
    try:
        from ..observe import events as events_mod

        eid = events_mod.record_event(
            "abort.publish", severity="critical",
            payload={"reason": flag.get("reason"),
                     "source": flag.get("source"),
                     "rank": flag.get("rank"),
                     "epoch": flag.get("epoch")},
            cause_id=flag.get("cause_event_id"),
            correlation_id=flag.get("correlation_id"),
            rank=flag.get("rank"))
        if eid:
            flag.setdefault("event_id", eid)
            corr = events_mod.correlation_of(eid)
            if corr:
                flag.setdefault("correlation_id", corr)
    except Exception:  # noqa: BLE001 — recording must not mask the abort
        pass
    try:
        from ..run.http_client import put_kv

        put_kv(addr, port, ABORT_SCOPE, ABORT_KEY,
               json.dumps(flag).encode(), secret=secret, retry=True,
               timeout=timeout)
    except Exception as e:  # noqa: BLE001 — a dead server must not mask
        log.warning("abort flag %r publish failed: %s",  # the abort
                    flag.get("reason"), e)
        return False
    from .. import metrics

    if metrics.on():
        metrics.ABORTS.labels(flag.get("source", "unknown")).inc()
    log.error("coordinated abort set: %s", format_abort(flag))
    return True


def trigger(reason: str, *, rank: Optional[int] = None, source: str = "api",
            addr: Optional[str] = None, port: Optional[int] = None,
            secret: Optional[bytes] = None, timeout: float = 10.0) -> bool:
    """Build + publish the job-wide abort flag (best-effort, never
    raises)."""
    return publish(make_flag(reason, rank=rank, source=source),
                   addr=addr, port=port, secret=secret, timeout=timeout)


def read_flag(addr: str, port: int,
              secret: Optional[bytes] = None) -> Optional[dict]:
    """The current abort flag on the rendezvous server (None if unset)."""
    from ..run.http_client import get_kv

    raw = get_kv(addr, port, ABORT_SCOPE, ABORT_KEY, secret=secret)
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return {"reason": "<undecodable abort flag>", "source": "unknown"}


def abort(reason: str) -> None:
    """Abort the whole job from this rank: publish the flag so every peer's
    heartbeat sees it, then raise :class:`HorovodAbortError` locally —
    one flag object, so the local error and what peers observe agree."""
    flag = make_flag(reason, source="api")
    publish(flag)
    raise HorovodAbortError(format_abort(flag))
