"""Collective operations over the XLA data plane.

TPU-native replacement for the reference's operation stack: the
chain-of-responsibility op classes (reference
horovod/common/ops/collective_operations.h:31-159), the MPI/NCCL/Gloo
backends (mpi_operations.cc, nccl_operations.cc, gloo_operations.cc), and
the fusion-buffer memcpys, all collapse into XLA collective HLOs —
``lax.psum`` / ``lax.all_gather`` / ``lax.psum_scatter`` /
``lax.all_to_all`` / ``lax.ppermute`` — which XLA schedules onto ICI
directly.  There is no fusion-buffer copy: XLA's all-reduce combiner plus
our gradient bucketing (ops/fusion.py) play that role.

Each function works in two planes:

* **in-SPMD** (inside :func:`horovod_tpu.spmd` / a ``rank_context``): emits
  the collective over the mesh axis — the hot path, compiled by XLA.
* **eager / host-level** (outside): operates on a rank-sharded global array
  (see :func:`horovod_tpu.spmd.put_per_rank`) by jit-compiling a tiny SPMD
  program on the fly — the analog of Horovod's enqueue-to-background-thread
  eager path (reference operations.cc:795 EnqueueTensorAllreduce), with the
  jit cache standing in for the response cache.

``process_set`` arguments take a :class:`ProcessSet` (a subset of ranks) and
map to ``axis_index_groups`` — the analog of Horovod's sub-communicator
``hvd.init(comm=...)`` (reference operations.cc:655-663).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import core
from ..core import Average, Sum, Adasum, Min, Max
from .compression import Compression


class ProcessSet:
    """A subset of ranks forming their own collective group.

    Analog of Horovod's restricted communicator (reference
    horovod/common/operations.cc:655-663, basics.py:33-65 ``init(comm=...)``)
    — implemented as ``axis_index_groups``, so XLA lowers a group-local
    collective with no extra bootstrap.
    """

    def __init__(self, ranks: Sequence[int]):
        self.ranks = tuple(sorted(int(r) for r in ranks))
        if len(set(self.ranks)) != len(self.ranks):
            raise ValueError("duplicate ranks in process set")

    def groups(self) -> list:
        """axis_index_groups covering the whole mesh: this set plus the
        complement (XLA requires groups to partition the axis)."""
        world = set(range(core.size()))
        rest = sorted(world - set(self.ranks))
        groups = [list(self.ranks)]
        if rest:
            # Complement ranks reduce among themselves (their results are
            # ignored by callers that gate on membership).
            groups.append(rest)
        return groups

    def size(self) -> int:
        return len(self.ranks)


def _axes() -> tuple:
    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError(
            "not inside an SPMD region; use the eager API (allreduce_ on a "
            "per-rank sharded array) or wrap your step in hvd.spmd"
        )
    return axes


def _group_args(process_set: Optional[ProcessSet]):
    if process_set is None:
        return None, core.size()
    return process_set.groups(), process_set.size()


# --------------------------------------------------------------------------
# allreduce
# --------------------------------------------------------------------------
def allreduce(
    tensor,
    *,
    op: str = Average,
    name: Optional[str] = None,
    compression=Compression.none,
    process_set: Optional[ProcessSet] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
):
    """Allreduce a per-rank tensor across all ranks.

    Mirrors ``hvd.allreduce`` (reference horovod/torch/mpi_ops.py:94-129 /
    horovod/tensorflow/mpi_ops.py): ``op`` is Average / Sum / Adasum /
    Min / Max; ``compression`` casts before the wire and back after
    (reference horovod/torch/compression.py).
    """
    axes = _axes()
    groups, group_size = _group_args(process_set)

    if op == Adasum:
        from .adasum import adasum_allreduce

        return adasum_allreduce(tensor, process_set=process_set)

    compressed, ctx = compression.compress(tensor)
    if prescale_factor != 1.0:
        compressed = compressed * prescale_factor

    if op in (Average, Sum):
        if len(axes) == 1:
            out = lax.psum(compressed, axes[0], axis_index_groups=groups)
        else:
            out = lax.psum(compressed, axes)
        if op == Average:
            out = out / group_size
    elif op == Min:
        out = lax.pmin(compressed, axes if len(axes) > 1 else axes[0],
                       axis_index_groups=groups if len(axes) == 1 else None)
    elif op == Max:
        out = lax.pmax(compressed, axes if len(axes) > 1 else axes[0],
                       axis_index_groups=groups if len(axes) == 1 else None)
    else:
        raise ValueError(f"unknown reduce op: {op!r}")

    if postscale_factor != 1.0:
        out = out * postscale_factor
    return compression.decompress(out, ctx)


def grouped_allreduce(
    tensors: Sequence[Any],
    *,
    op: str = Average,
    compression=Compression.none,
    process_set: Optional[ProcessSet] = None,
    threshold_bytes: Optional[int] = None,
):
    """Allreduce a list of tensors as one fused operation.

    The explicit-fusion API: the analog of the tensor-fusion buffer pass
    (reference controller.cc:665 FuseResponses + the MemcpyInFusionBuffer /
    MemcpyOutFusionBuffer pair in ops/collective_operations.cc) — but here
    "fusion" is a flatten/concat in HLO that XLA folds into its all-reduce
    combiner, with no staging copy through a persistent buffer.
    """
    from .fusion import fused_allreduce

    return fused_allreduce(
        list(tensors), op=op, compression=compression,
        process_set=process_set, threshold_bytes=threshold_bytes,
    )


def allreduce_gradients(grads, *, op: str = Average, compression=Compression.none):
    """Allreduce every leaf of a gradient pytree (fused by dtype buckets).

    The hot-path entry used by DistributedOptimizer/DistributedGradientTape
    (reference horovod/tensorflow/__init__.py:231-252
    ``_make_allreduce_grads_fn``).
    """
    from .fusion import allreduce_pytree

    return allreduce_pytree(grads, op=op, compression=compression)


# --------------------------------------------------------------------------
# allgather
# --------------------------------------------------------------------------
def allgather(tensor, *, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    """Concatenate each rank's tensor along axis 0 and replicate the result.

    Mirrors ``hvd.allgather`` (reference
    horovod/common/ops/collective_operations.cc allgather output allocation
    + displacement math).  In-SPMD requires equal shapes per rank (static
    SPMD program); for Horovod's varying-first-dimension contract use
    :func:`allgatherv`.
    """
    axes = _axes()
    groups, _ = _group_args(process_set)
    if len(axes) == 1:
        return lax.all_gather(
            tensor, axes[0], axis=0, tiled=True, axis_index_groups=groups
        )
    return lax.all_gather(tensor, axes, axis=0, tiled=True)


def allgatherv(tensor, *, valid_rows, max_rows: int,
               process_set: Optional[ProcessSet] = None):
    """Allgather with per-rank varying first dimension.

    Horovod negotiates per-rank sizes at runtime through the coordinator
    (reference controller.cc:377 ConstructResponse collects tensor sizes
    into the Response).  A static SPMD program can't have per-rank shapes,
    so the TPU-native contract is pad-to-``max_rows`` + a ``valid_rows``
    scalar; returns ``(gathered, row_counts)`` where ``gathered`` is
    ``[size * max_rows, ...]`` with invalid rows zeroed, and ``row_counts``
    the per-rank valid counts — callers slice out valid rows on host.
    """
    axes = _axes()
    groups, _ = _group_args(process_set)
    pad_width = [(0, max_rows - tensor.shape[0])] + [(0, 0)] * (tensor.ndim - 1)
    padded = jnp.pad(tensor, pad_width)
    mask = (jnp.arange(max_rows) < valid_rows).reshape(
        (max_rows,) + (1,) * (tensor.ndim - 1)
    )
    padded = jnp.where(mask, padded, jnp.zeros_like(padded))
    axis = axes[0] if len(axes) == 1 else axes
    gathered = lax.all_gather(padded, axis, axis=0, tiled=True,
                              axis_index_groups=groups if len(axes) == 1 else None)
    counts = lax.all_gather(jnp.asarray(valid_rows, jnp.int32), axis,
                            axis_index_groups=groups if len(axes) == 1 else None)
    return gathered, counts


# --------------------------------------------------------------------------
# broadcast
# --------------------------------------------------------------------------
def broadcast(tensor, root_rank: int = 0, *, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    """Every rank receives ``root_rank``'s value.

    Mirrors ``hvd.broadcast`` (reference horovod/common/ops/
    mpi_operations.cc MPIBroadcast / nccl_operations.cc NCCLBroadcast).
    Implemented as a masked psum — one collective, no gather blow-up.
    """
    axes = _axes()
    groups, _ = _group_args(process_set)
    r = core.rank()
    masked = jnp.where(r == root_rank, tensor, jnp.zeros_like(tensor))
    if len(axes) == 1:
        return lax.psum(masked, axes[0], axis_index_groups=groups)
    return lax.psum(masked, axes)


# --------------------------------------------------------------------------
# alltoall / reducescatter
# --------------------------------------------------------------------------
def alltoall(tensor, *, process_set: Optional[ProcessSet] = None):
    """Equal-split all-to-all: rank i's j-th chunk (along axis 0) goes to
    rank j.  Requires ``tensor.shape[0] % size == 0``.

    (Beyond-parity: upstream Horovod grew alltoall in 0.20; included here
    because sequence-parallel attention — parallel/ring_attention.py — and
    MoE expert dispatch are built on it.)
    """
    axes = _axes()
    if len(axes) != 1:
        raise NotImplementedError("alltoall over hierarchical mesh")
    n = core.size() if process_set is None else process_set.size()
    if tensor.shape[0] % n:
        raise ValueError(
            f"alltoall first dim {tensor.shape[0]} not divisible by {n}"
        )
    split = tensor.reshape((n, tensor.shape[0] // n) + tensor.shape[1:])
    groups, _ = _group_args(process_set)
    out = lax.all_to_all(split, axes[0], split_axis=0, concat_axis=0,
                         axis_index_groups=groups, tiled=False)
    return out.reshape((-1,) + tensor.shape[1:])


def reducescatter(tensor, *, op: str = Sum,
                  process_set: Optional[ProcessSet] = None):
    """Reduce across ranks and scatter equal chunks of axis 0.

    The building block of hierarchical allreduce (reference
    nccl_operations.cc:241-246 uses ncclReduceScatter for exactly this).
    """
    axes = _axes()
    if len(axes) != 1:
        raise NotImplementedError("reducescatter over hierarchical mesh")
    groups, group_size = _group_args(process_set)
    out = lax.psum_scatter(tensor, axes[0], scatter_dimension=0, tiled=True,
                           axis_index_groups=groups)
    if op == Average:
        out = out / group_size
    return out
