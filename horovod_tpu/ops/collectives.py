"""Collective operations over the XLA data plane.

TPU-native replacement for the reference's operation stack: the
chain-of-responsibility op classes (reference
horovod/common/ops/collective_operations.h:31-159), the MPI/NCCL/Gloo
backends (mpi_operations.cc, nccl_operations.cc, gloo_operations.cc), and
the fusion-buffer memcpys, all collapse into XLA collective HLOs —
``lax.psum`` / ``lax.all_gather`` / ``lax.psum_scatter`` /
``lax.all_to_all`` / ``lax.ppermute`` — which XLA schedules onto ICI
directly.  There is no fusion-buffer copy: XLA's all-reduce combiner plus
our gradient bucketing (ops/fusion.py) play that role.

Each function works in two planes:

* **in-SPMD** (inside :func:`horovod_tpu.spmd` / a ``rank_context``): emits
  the collective over the mesh axis — the hot path, compiled by XLA.
* **eager / host-level** (outside): operates on a rank-sharded global array
  (see :func:`horovod_tpu.spmd.put_per_rank`) by jit-compiling a tiny SPMD
  program on the fly — the analog of Horovod's enqueue-to-background-thread
  eager path (reference operations.cc:795 EnqueueTensorAllreduce), with the
  jit cache standing in for the response cache.

``process_set`` arguments take a :class:`ProcessSet` (a subset of ranks) and
map to ``axis_index_groups`` — the analog of Horovod's sub-communicator
``hvd.init(comm=...)`` (reference operations.cc:655-663).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import core
from .. import metrics as _metrics
from ..core import Average, Sum, Adasum, Min, Max
from .compression import Compression


class ProcessSet:
    """A subset of ranks forming their own collective group.

    Analog of Horovod's restricted communicator (reference
    horovod/common/operations.cc:655-663, basics.py:33-65 ``init(comm=...)``)
    — implemented as ``axis_index_groups``, so XLA lowers a group-local
    collective with no extra bootstrap.
    """

    def __init__(self, ranks: Sequence[int]):
        self.ranks = tuple(sorted(int(r) for r in ranks))
        if not self.ranks:
            raise ValueError("process set must contain at least one rank")
        if len(set(self.ranks)) != len(self.ranks):
            raise ValueError("duplicate ranks in process set")

    def groups(self) -> list:
        """axis_index_groups covering the whole mesh: this set plus the
        complement (XLA requires groups to partition the axis).

        When the complement is a multiple of the set size it is split into
        equal-size groups so shape-changing collectives (``all_gather``,
        ``psum_scatter``, ``all_to_all``) — which XLA only lowers for
        equal-size groups — take the fast path.  Complement ranks reduce
        among themselves; their results are ignored by callers that gate
        on membership.
        """
        world = set(range(core.size()))
        if not set(self.ranks) <= world:
            raise ValueError(
                f"process set ranks {self.ranks} exceed world size "
                f"{core.size()}"
            )
        rest = sorted(world - set(self.ranks))
        groups = [list(self.ranks)]
        k = len(self.ranks)
        if rest:
            if len(rest) % k == 0:
                groups += [rest[i:i + k] for i in range(0, len(rest), k)]
            else:
                groups.append(rest)
        return groups

    def equal_groups(self) -> Optional[list]:
        """:meth:`groups` if every group has the same size (the only layout
        XLA's shape-changing collectives accept), else None."""
        g = self.groups()
        return g if len({len(x) for x in g}) == 1 else None

    def member_position(self):
        """(is_member, position-in-set) for the current rank — traced
        values inside an SPMD region.  Non-members get a position that
        scatter-drops (== set size when their rank sorts past the set)."""
        r = core.rank()
        ranks = jnp.asarray(self.ranks)
        member = jnp.any(jnp.asarray(r) == ranks)
        pos = jnp.searchsorted(ranks, jnp.asarray(r))
        return member, pos

    def size(self) -> int:
        return len(self.ranks)


def _axes() -> tuple:
    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError(
            "not inside an SPMD region; use the eager API (allreduce_ on a "
            "per-rank sharded array) or wrap your step in hvd.spmd"
        )
    return axes


def _group_args(process_set: Optional[ProcessSet]):
    if process_set is None:
        return None, core.size()
    return process_set.groups(), process_set.size()


# --------------------------------------------------------------------------
# allreduce
# --------------------------------------------------------------------------
def allreduce(
    tensor,
    *,
    op: str = Average,
    name: Optional[str] = None,
    compression=Compression.none,
    process_set: Optional[ProcessSet] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    hierarchical: bool = False,
    two_level: bool = False,
):
    """Allreduce a per-rank tensor across all ranks.

    Mirrors ``hvd.allreduce`` (reference horovod/torch/mpi_ops.py:94-129 /
    horovod/tensorflow/mpi_ops.py): ``op`` is Average / Sum / Adasum /
    Min / Max; ``compression`` casts before the wire and back after
    (reference horovod/torch/compression.py).  ``hierarchical`` selects the
    two-level local/cross decomposition (the reference's
    HOROVOD_HIERARCHICAL_ALLREDUCE knob, common.h:72).  ``two_level``
    selects the compressed two-level path instead — reduce-scatter on
    ICI, ``compression`` applied to the cross-stage payload only
    (parallel/hierarchical.py two_level_allreduce, HVD_TWO_LEVEL_ALLREDUCE).
    """
    axes = _axes()
    groups, group_size = _group_args(process_set)
    # Executes once per compile (tracing), not per step: the traced-
    # collective inventory a scrape can compare against the step cadence.
    _metrics.record_traced("allreduce", tensor)

    if two_level and op in (Average, Sum, Adasum) and len(axes) == 1:
        if process_set is not None:
            raise ValueError(
                "two-level allreduce over a process subset is unsupported"
            )
        from ..parallel.hierarchical import two_level_allreduce

        t = tensor * prescale_factor if prescale_factor != 1.0 else tensor
        out = two_level_allreduce(t, op=op, compression=compression)
        return out * postscale_factor if postscale_factor != 1.0 else out

    if op == Adasum:
        from .adasum import adasum_allreduce

        # prescale BEFORE the wire cast: scaling a quantized int8/fp8
        # payload would silently promote its dtype (and re-bias the
        # quantization grid)
        if prescale_factor != 1.0:
            tensor = tensor * prescale_factor
        compressed, ctx = compression.compress_for(tensor, group_size) \
            if hasattr(compression, "compress_for") \
            else compression.compress(tensor)
        out = adasum_allreduce(
            compressed, process_set=process_set, hierarchical=hierarchical
        )
        if postscale_factor != 1.0:
            out = out * postscale_factor
        return compression.decompress(out, ctx)

    if hierarchical and op in (Min, Max):
        raise ValueError("hierarchical allreduce supports Sum/Average/Adasum")

    if prescale_factor != 1.0:
        tensor = tensor * prescale_factor     # before the wire cast, ditto
    compressed, ctx = compression.compress_for(tensor, group_size) \
        if hasattr(compression, "compress_for") \
        else compression.compress(tensor)

    if hierarchical and op in (Average, Sum) and len(axes) == 1:
        if process_set is not None:
            raise ValueError(
                "hierarchical allreduce over a process subset is unsupported"
            )
        from ..parallel.hierarchical import hierarchical_allreduce

        out = hierarchical_allreduce(compressed, op=op)
    elif op in (Average, Sum):
        if len(axes) == 1:
            out = lax.psum(compressed, axes[0], axis_index_groups=groups)
        else:
            out = lax.psum(compressed, axes)
        if op == Average:
            out = out / group_size
    elif op == Min:
        out = lax.pmin(compressed, axes if len(axes) > 1 else axes[0],
                       axis_index_groups=groups if len(axes) == 1 else None)
    elif op == Max:
        out = lax.pmax(compressed, axes if len(axes) > 1 else axes[0],
                       axis_index_groups=groups if len(axes) == 1 else None)
    else:
        raise ValueError(f"unknown reduce op: {op!r}")

    if postscale_factor != 1.0:
        out = out * postscale_factor
    return compression.decompress(out, ctx)


def grouped_allreduce(
    tensors: Sequence[Any],
    *,
    op: str = Average,
    compression=Compression.none,
    process_set: Optional[ProcessSet] = None,
    threshold_bytes: Optional[int] = None,
):
    """Allreduce a list of tensors as one fused operation.

    The explicit-fusion API: the analog of the tensor-fusion buffer pass
    (reference controller.cc:665 FuseResponses + the MemcpyInFusionBuffer /
    MemcpyOutFusionBuffer pair in ops/collective_operations.cc) — but here
    "fusion" is a flatten/concat in HLO that XLA folds into its all-reduce
    combiner, with no staging copy through a persistent buffer.
    """
    from .fusion import fused_allreduce

    return fused_allreduce(
        list(tensors), op=op, compression=compression,
        process_set=process_set, threshold_bytes=threshold_bytes,
    )


def allreduce_gradients(grads, *, op: str = Average, compression=Compression.none):
    """Allreduce every leaf of a gradient pytree (fused by dtype buckets).

    The hot-path entry used by DistributedOptimizer/DistributedGradientTape
    (reference horovod/tensorflow/__init__.py:231-252
    ``_make_allreduce_grads_fn``).
    """
    from .fusion import allreduce_pytree

    return allreduce_pytree(grads, op=op, compression=compression)


# --------------------------------------------------------------------------
# allgather
# --------------------------------------------------------------------------
def allgather(tensor, *, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    """Concatenate each rank's tensor along axis 0 and replicate the result.

    Mirrors ``hvd.allgather`` (reference
    horovod/common/ops/collective_operations.cc allgather output allocation
    + displacement math).  In-SPMD requires equal shapes per rank (static
    SPMD program); for Horovod's varying-first-dimension contract use
    :func:`allgatherv`.
    """
    axes = _axes()
    _metrics.record_traced("allgather", tensor)
    if len(axes) != 1:
        return lax.all_gather(tensor, axes, axis=0, tiled=True)
    if process_set is None:
        return lax.all_gather(tensor, axes[0], axis=0, tiled=True)
    eq = process_set.equal_groups()
    if eq is not None:
        return lax.all_gather(
            tensor, axes[0], axis=0, tiled=True, axis_index_groups=eq
        )
    # Uneven groups: XLA all_gather requires equal-size groups, but psum
    # accepts any partition — embed each member's shard at its position in
    # a zero buffer and sum over the set (complement ranks sum zeros).
    return _psum_embed_gather(tensor, axes[0], process_set)


def _psum_embed_gather(tensor, axis_name, process_set: "ProcessSet"):
    k = process_set.size()
    member, pos = process_set.member_position()
    contrib = jnp.where(member, tensor, jnp.zeros_like(tensor))
    buf = jnp.zeros((k,) + tuple(tensor.shape), tensor.dtype)
    buf = buf.at[pos].set(contrib)  # OOB pos (non-member) drops the update
    out = lax.psum(buf, axis_name, axis_index_groups=process_set.groups())
    return out.reshape((k * tensor.shape[0],) + tuple(tensor.shape[1:]))


def allgatherv(tensor, *, valid_rows, max_rows: int,
               process_set: Optional[ProcessSet] = None):
    """Allgather with per-rank varying first dimension.

    Horovod negotiates per-rank sizes at runtime through the coordinator
    (reference controller.cc:377 ConstructResponse collects tensor sizes
    into the Response).  A static SPMD program can't have per-rank shapes,
    so the TPU-native contract is pad-to-``max_rows`` + a ``valid_rows``
    scalar; returns ``(gathered, row_counts)`` where ``gathered`` is
    ``[size * max_rows, ...]`` with invalid rows zeroed, and ``row_counts``
    the per-rank valid counts — callers slice out valid rows on host.
    """
    pad_width = [(0, max_rows - tensor.shape[0])] + [(0, 0)] * (tensor.ndim - 1)
    padded = jnp.pad(tensor, pad_width)
    mask = (jnp.arange(max_rows) < valid_rows).reshape(
        (max_rows,) + (1,) * (tensor.ndim - 1)
    )
    padded = jnp.where(mask, padded, jnp.zeros_like(padded))
    counts_in = jnp.asarray(valid_rows, jnp.int32)
    return (
        allgather(padded, process_set=process_set),
        allgather(counts_in[None], process_set=process_set),
    )


# --------------------------------------------------------------------------
# broadcast
# --------------------------------------------------------------------------
def broadcast(tensor, root_rank: int = 0, *, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    """Every rank receives ``root_rank``'s value.

    Mirrors ``hvd.broadcast`` (reference horovod/common/ops/
    mpi_operations.cc MPIBroadcast / nccl_operations.cc NCCLBroadcast).
    Implemented as a masked psum — XLA has no one-to-all HLO, and of the
    expressible schedules this is the deliberate choice: a ring psum
    moves 2(n-1)/n x bytes over ICI (~2x a textbook broadcast's
    (n-1)/n) in ONE collective, vs n x bytes for all_gather-and-index or
    (n-1) serial latency hops for a ppermute pipeline.  On ICI the 2x is
    noise (broadcast traffic is start-up parameter sync, docs/PERF.md
    measures the gradient allreduce at 102 MB vs ~1 ms); across DCN
    prefer the host-plane ``eager.process_broadcast``, which sends the
    payload once.
    """
    axes = _axes()
    _metrics.record_traced("broadcast", tensor)
    groups, _ = _group_args(process_set)
    r = core.rank()
    masked = jnp.where(r == root_rank, tensor, jnp.zeros_like(tensor))
    if len(axes) == 1:
        return lax.psum(masked, axes[0], axis_index_groups=groups)
    return lax.psum(masked, axes)


# --------------------------------------------------------------------------
# alltoall / reducescatter
# --------------------------------------------------------------------------
def alltoall(tensor, *, process_set: Optional[ProcessSet] = None):
    """Equal-split all-to-all: rank i's j-th chunk (along axis 0) goes to
    rank j.  Requires ``tensor.shape[0] % size == 0``.

    (Beyond-parity: upstream Horovod grew alltoall in 0.20; included here
    because sequence-parallel attention — parallel/ring_attention.py — and
    MoE expert dispatch are built on it.)
    """
    axes = _axes()
    _metrics.record_traced("alltoall", tensor)
    if len(axes) != 1:
        raise NotImplementedError("alltoall over hierarchical mesh")
    n = core.size() if process_set is None else process_set.size()
    if tensor.shape[0] % n:
        raise ValueError(
            f"alltoall first dim {tensor.shape[0]} not divisible by {n}"
        )
    groups = None
    if process_set is not None:
        groups = process_set.equal_groups()
        if groups is None:
            # XLA all_to_all needs equal-size groups; psum accepts any
            # partition — same embed trick as allgather's uneven path
            return _psum_embed_alltoall(tensor, axes[0], process_set)
    split = tensor.reshape((n, tensor.shape[0] // n) + tensor.shape[1:])
    out = lax.all_to_all(split, axes[0], split_axis=0, concat_axis=0,
                         axis_index_groups=groups, tiled=False)
    return out.reshape((-1,) + tensor.shape[1:])


def _psum_embed_alltoall(tensor, axis_name, process_set: "ProcessSet"):
    """alltoall for uneven ProcessSets: member at position p embeds its k
    chunks at row p of a zero [k, k, chunk, ...] buffer; after a psum
    over the set, every member holds the full exchange matrix and takes
    column p (its incoming chunks).  Wire cost is k× the minimal
    alltoall — acceptable at ProcessSet control sizes, and the only
    schedule XLA can express for ragged groups (reference keeps uneven
    sets on MPI sub-communicators instead, operations.cc:655-663)."""
    k = process_set.size()
    chunk = tensor.shape[0] // k
    member, pos = process_set.member_position()
    split = tensor.reshape((k, chunk) + tuple(tensor.shape[1:]))
    contrib = jnp.where(member, split, jnp.zeros_like(split))
    buf = jnp.zeros((k,) + split.shape, tensor.dtype)
    buf = buf.at[pos].set(contrib)  # OOB pos (non-member) drops the update
    full = lax.psum(buf, axis_name, axis_index_groups=process_set.groups())
    out = jnp.take(full, jnp.minimum(pos, k - 1), axis=1)  # [k, chunk, ...]
    return out.reshape((-1,) + tuple(tensor.shape[1:]))


def reducescatter(tensor, *, op: str = Sum,
                  process_set: Optional[ProcessSet] = None):
    """Reduce across ranks and scatter equal chunks of axis 0.

    The building block of hierarchical allreduce (reference
    nccl_operations.cc:241-246 uses ncclReduceScatter for exactly this).
    """
    axes = _axes()
    _metrics.record_traced("reducescatter", tensor)
    if len(axes) != 1:
        raise NotImplementedError("reducescatter over hierarchical mesh")
    if process_set is None:
        out = lax.psum_scatter(tensor, axes[0], scatter_dimension=0,
                               tiled=True)
        if op == Average:
            out = out / core.size()
        return out
    k = process_set.size()
    if tensor.shape[0] % k:
        raise ValueError(
            f"reducescatter first dim {tensor.shape[0]} not divisible by "
            f"process set size {k}"
        )
    eq = process_set.equal_groups()
    if eq is not None:
        out = lax.psum_scatter(tensor, axes[0], scatter_dimension=0,
                               tiled=True, axis_index_groups=eq)
    else:
        # Uneven groups: full psum over the set (psum accepts any
        # partition), then each member slices out its own chunk.
        full = lax.psum(tensor, axes[0],
                        axis_index_groups=process_set.groups())
        chunk = tensor.shape[0] // k
        _, pos = process_set.member_position()
        out = lax.dynamic_slice_in_dim(
            full, jnp.minimum(pos, k - 1) * chunk, chunk, axis=0
        )
    if op == Average:
        out = out / k
    return out
