"""Pallas elementwise kernels for the HBM-bound ResNet joins.

docs/PERF.md's profile names the 56×56 residual-add fusions (3 × 5.45 ms
at batch 256) as the one untried framework-side lever on the ResNet-50
headline; this module is that experiment's kernel.  ``residual_relu``
computes ``relu(x + y)`` in one HBM pass with explicit [rows, 256]
blocking; ``scripts/pallas_residual_experiment.py`` measures it against
XLA's own elementwise fusion standalone and end-to-end (the result —
either a headline move or a measured negative — is recorded in
docs/PERF.md).

``scale_bias_relu`` is the compute-tier companion (docs/PERF.md
"compute tier"): the norm+activation join ``relu(x * scale + bias)`` —
the elementwise half of every BatchNorm→ReLU pair once the per-channel
statistics are folded — in one HBM pass with a custom VJP whose
backward reuses the masked-grad kernel.  models/resnet.py wires it in
as ``norm_act="pallas"`` (the ``BatchNormReLU`` module).

Off-TPU the kernels run in Pallas interpreter mode, same policy as
ops/flash_attention.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _resolve_interpret


def _residual_relu_kernel(x_ref, y_ref, o_ref):
    o_ref[...] = jnp.maximum(x_ref[...] + y_ref[...], 0)


def _relu_grad_kernel(o_ref, g_ref, dx_ref):
    # compare in f32: Mosaic can't lower bf16 vector cmpf on this target
    mask = o_ref[...].astype(jnp.float32) > 0
    dx_ref[...] = jnp.where(mask, g_ref[...], jnp.zeros_like(g_ref[...]))


# per-buffer VMEM budget: 3 buffers x 2 (double buffering) must fit the
# ~16 MB scoped-vmem limit with headroom
_BLOCK_BYTES = 2 << 20


def _flat_call(kernel, a, b, *, block_rows, interpret):
    lanes = a.shape[-1]
    af = a.reshape(-1, lanes)
    bf = b.reshape(-1, lanes)
    rows = af.shape[0]
    cap = max(8, _BLOCK_BYTES // (lanes * a.dtype.itemsize))
    block = min(block_rows, cap, rows)
    out = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, block),),
        in_specs=[
            pl.BlockSpec((block, lanes), lambda i: (i, 0)),
            pl.BlockSpec((block, lanes), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, lanes), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), a.dtype),
        interpret=_resolve_interpret(interpret),
    )(af, bf)
    return out.reshape(a.shape)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def residual_relu(x, y, block_rows: int = 1024,
                  interpret: Optional[bool] = None):
    """``relu(x + y)`` as a single Pallas pass (custom VJP: the backward
    is one masked pass reusing the saved output, the same residual the
    XLA fusion keeps).

    Shapes: any, as long as x and y match; internally flattened to
    [rows, lanes] with the trailing dimension kept whole (channel-last
    NHWC tensors put C on the lanes, which is the TPU-friendly layout).
    """
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch: {x.shape} vs {y.shape}")
    return _flat_call(_residual_relu_kernel, x, y,
                      block_rows=block_rows, interpret=interpret)


def _residual_relu_fwd(x, y, block_rows, interpret):
    out = residual_relu(x, y, block_rows, interpret)
    return out, out


def _residual_relu_bwd(block_rows, interpret, out, g):
    dx = _flat_call(_relu_grad_kernel, out, g,
                    block_rows=block_rows, interpret=interpret)
    return dx, dx


residual_relu.defvjp(_residual_relu_fwd, _residual_relu_bwd)


# ---------------------------------------------------------------------------
# norm+activation join: relu(x * scale + bias) in one pass
# ---------------------------------------------------------------------------
def _scale_bias_relu_kernel(x_ref, s_ref, b_ref, o_ref):
    y = x_ref[...].astype(jnp.float32) * s_ref[0][None, :] + b_ref[0][None, :]
    o_ref[...] = jnp.maximum(y, 0).astype(o_ref.dtype)


def _affine_call(x, scale, bias, *, block_rows, interpret):
    """One blocked pass of the affine+relu kernel; scale/bias ride as
    [1, C] rows broadcast to every block (the conv_bn.py layout)."""
    lanes = x.shape[-1]
    xf = x.reshape(-1, lanes)
    rows = xf.shape[0]
    cap = max(8, _BLOCK_BYTES // (lanes * x.dtype.itemsize))
    block = min(block_rows, cap, rows)
    out = pl.pallas_call(
        _scale_bias_relu_kernel,
        grid=(pl.cdiv(rows, block),),
        in_specs=[
            pl.BlockSpec((block, lanes), lambda i: (i, 0)),
            pl.BlockSpec((1, lanes), lambda i: (0, 0)),
            pl.BlockSpec((1, lanes), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block, lanes), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), x.dtype),
        interpret=_resolve_interpret(interpret),
    )(xf, scale.reshape(1, lanes).astype(jnp.float32),
      bias.reshape(1, lanes).astype(jnp.float32))
    return out.reshape(x.shape)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def scale_bias_relu(x, scale, bias, block_rows: int = 1024,
                    interpret: Optional[bool] = None):
    """``relu(x * scale + bias)`` as a single Pallas pass — the folded
    norm+activation join.  ``x``: any shape with channels last;
    ``scale``/``bias``: [C] (f32 — the folded BN affine).  The custom
    VJP masks the upstream gradient with the saved output (one masked
    pass, the ``residual_relu`` backward kernel) and reduces
    ``dscale``/``dbias`` over the non-channel axes; gradients flow to
    ``scale``/``bias`` so a caller computing them from batch statistics
    gets the full BatchNorm backward through ordinary autodiff
    (models/resnet.py ``BatchNormReLU``)."""
    if scale.shape != (x.shape[-1],) or bias.shape != (x.shape[-1],):
        raise ValueError(
            f"scale/bias must be [{x.shape[-1]}], got "
            f"{scale.shape} / {bias.shape}")
    return _affine_call(x, scale, bias, block_rows=block_rows,
                        interpret=interpret)


def _scale_bias_relu_fwd(x, scale, bias, block_rows, interpret):
    out = scale_bias_relu(x, scale, bias, block_rows, interpret)
    return out, (x, scale, out)


def _scale_bias_relu_bwd(block_rows, interpret, res, g):
    x, scale, out = res
    # masked upstream grad in one pass (reuses the relu-grad kernel)
    gm = _flat_call(_relu_grad_kernel, out, g,
                    block_rows=block_rows, interpret=interpret)
    gm32 = gm.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    dx = (gm32 * scale).astype(x.dtype)
    dscale = (gm32 * x.astype(jnp.float32)).sum(axis=axes)
    dbias = gm32.sum(axis=axes)
    return dx, dscale.astype(scale.dtype), dbias.astype(scale.dtype)


scale_bias_relu.defvjp(_scale_bias_relu_fwd, _scale_bias_relu_bwd)
