"""Tensor fusion: bucketing many small tensors into few large collectives.

TPU-native re-design of the fusion buffer (reference
horovod/common/fusion_buffer_manager.cc/.h — persistent 64 MB buffers per
(device, framework, stream) — plus the response-fusion pass in
controller.cc:665 FuseResponses and the MemcpyIn/OutFusionBuffer kernels in
ops/collective_operations.cc).

On TPU there is no persistent staging buffer and no memcpy kernel: we
flatten each gradient leaf, group leaves of the same dtype into buckets of
at most ``HVD_FUSION_THRESHOLD`` bytes (reference default 64 MB,
common.h:69), concatenate each bucket, run ONE ``psum`` per bucket, and
split back.  XLA fuses the concat/split with neighbors, and its own
all-reduce combiner provides a second level of batching — the autotuner
(optim/autotune.py) owns both knobs, as SURVEY §7.3(2) requires.

Bucketing is a *trace-time* planner (shapes are static under jit), which is
exactly the negotiated-once-then-cached steady state of the reference's
response cache — except the "cache" is the compiled executable.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .. import core
from .. import metrics as _metrics
from ..core import Average, Sum
from ..utils import env as env_util
from .compression import Compression


def dispatch_group_label(process_set=None) -> str:
    """The communication-group label a dispatch reduces over — ``world``
    for the flat mesh, ``process_set:<ranks>`` for a restricted
    communicator.  The label vocabulary is a protocol string documented
    in docs/analysis.md: the traced inventory
    (metrics.record_traced_group), the runtime sanitizer fingerprints
    (analysis/sanitizer.py), and the static schedule checker
    (analysis/schedule/ir.py) all spell the same family names."""
    if process_set is None:
        return "world"
    return "process_set:" + ",".join(str(r) for r in process_set.ranks)


class FusionPlan:
    """A static bucketing of a fixed list of (shape, dtype) leaves.

    Two construction modes:

    * **threshold** (the default, the reference's single global knob):
      greedy same-dtype packing under ``threshold_bytes``;
    * **explicit** (``explicit_buckets`` — the profile-guided planner's
      vector-of-buckets knob, optim/profile_guided.py): the caller names
      exactly which leaves fuse together, in which dispatch order.
      Buckets are split by dtype where members mix (one ``concatenate``
      per dtype), and leaves no bucket claims ride as singletons
      appended after the plan — an explicit plan can therefore never
      drop a gradient.

    ``buckets`` is the dispatch order: ``fused_allreduce`` launches
    bucket 0's collective first, which under XLA's latency-hiding
    scheduler is the overlap hook — the planner orders buckets so early
    gradients go on the wire while later compute still runs.
    """

    def __init__(self, leaves: Sequence[Any],
                 threshold_bytes: Optional[int] = None,
                 explicit_buckets: Optional[Sequence[Sequence[int]]] = None,
                 bucket_compression: Optional[Sequence[Optional[str]]] = None):
        if threshold_bytes is None:
            threshold_bytes = env_util.fusion_threshold_bytes()
        self.threshold_bytes = max(int(threshold_bytes), 1)
        self.explicit = explicit_buckets is not None
        self.buckets: List[List[int]] = []
        #: per final bucket: compression registry name or None (global
        #: compression applies) — the planner's per-bucket wire-format
        #: knob (optim/profile_guided.py FusionPlanSpec.compression)
        self.bucket_compression: List[Optional[str]] = []
        if explicit_buckets is not None:
            self._build_explicit(leaves, explicit_buckets,
                                 bucket_compression)
        else:
            self._build_threshold(leaves)

    def _build_threshold(self, leaves: Sequence[Any]) -> None:
        # bucket := list of leaf indices, all same dtype, total bytes <= threshold
        current: dict = {}  # dtype -> (bucket_idx, bytes_so_far)
        for i, leaf in enumerate(leaves):
            dt = jnp.result_type(leaf)
            nbytes = leaf.size * dt.itemsize
            slot = current.get(dt)
            if slot is not None and slot[1] + nbytes <= self.threshold_bytes:
                self.buckets[slot[0]].append(i)
                current[dt] = (slot[0], slot[1] + nbytes)
            else:
                self.buckets.append([i])
                current[dt] = (len(self.buckets) - 1, nbytes)
        self.bucket_compression = [None] * len(self.buckets)

    def _build_explicit(self, leaves: Sequence[Any],
                        explicit: Sequence[Sequence[int]],
                        compression: Optional[Sequence[Optional[str]]] = None
                        ) -> None:
        n = len(leaves)
        seen: set = set()
        for bi, bucket in enumerate(explicit):
            comp = compression[bi] if compression is not None \
                and bi < len(compression) else None
            by_dtype: dict = {}  # dtype -> list of indices, order kept
            for i in bucket:
                i = int(i)
                if not 0 <= i < n:
                    raise ValueError(
                        f"fusion plan references leaf {i} but only {n} "
                        "leaves exist")
                if i in seen:
                    raise ValueError(
                        f"fusion plan assigns leaf {i} to two buckets")
                seen.add(i)
                by_dtype.setdefault(jnp.result_type(leaves[i]),
                                    []).append(i)
            for b in by_dtype.values():
                if b:
                    # dtype-split halves inherit the source bucket's
                    # compression choice
                    self.buckets.append(b)
                    self.bucket_compression.append(comp)
        # unclaimed leaves: singletons, appended in leaf order, no
        # plan-level compression (the global compressor still applies)
        for i in range(n):
            if i not in seen:
                self.buckets.append([i])
                self.bucket_compression.append(None)

    @classmethod
    def from_named_buckets(cls, leaves: Sequence[Any],
                           names: Sequence[str],
                           named_buckets: Sequence[Sequence[str]],
                           bucket_compression:
                           Optional[Sequence[Optional[str]]] = None
                           ) -> "FusionPlan":
        """Explicit plan from tensor NAMES (the vocabulary of the replay
        plan payload) matched against this call's leaf names: exact
        match first, then path-suffix either way (trace span names are
        often the trailing component of ``a/b/kernel`` manifest names).
        Unmatched plan names are ignored — the trace may mention tensors
        this step doesn't carry — and unmatched leaves fall out as
        appended singletons (explicit-plan semantics above)."""
        index: dict = {str(nm): i for i, nm in enumerate(names)}

        def match(name: str) -> Optional[int]:
            if name in index:
                return index[name]
            for nm, i in index.items():
                if nm.endswith("/" + name) or name.endswith("/" + nm):
                    return i
            return None

        used: set = set()
        explicit: List[List[int]] = []
        comps: List[Optional[str]] = []
        for bi, bucket in enumerate(named_buckets):
            idxs = []
            for name in bucket:
                i = match(str(name))
                if i is not None and i not in used:
                    used.add(i)
                    idxs.append(i)
            if idxs:
                explicit.append(idxs)
                comps.append(bucket_compression[bi]
                             if bucket_compression is not None
                             and bi < len(bucket_compression) else None)
        return cls(leaves, explicit_buckets=explicit,
                   bucket_compression=comps)

    def num_buckets(self) -> int:
        return len(self.buckets)


def tree_leaf_names(tree, *, is_leaf=None) -> List[str]:
    """Slash-joined key paths of a pytree's leaves (``params/dense/kernel``
    vocabulary — matches the Recorder's gradient manifest names)."""
    paths = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)[0]

    def key_str(k) -> str:
        for attr in ("key", "name", "idx"):
            if hasattr(k, attr):
                return str(getattr(k, attr))
        return str(k)

    return ["/".join(key_str(k) for k in path) for path, _leaf in paths]


def _reduce_flat(flat, *, op, axes, groups, group_size):
    if len(axes) == 1:
        out = lax.psum(flat, axes[0], axis_index_groups=groups)
    else:
        out = lax.psum(flat, axes)
    if op == Average:
        out = out / group_size
    return out


def _compress_with(comp, tensor, group_size: int):
    """One compressor call, via ``compress_for`` when the compressor has
    it (quantizers need the reducing-group headroom) with a fallback to
    the legacy two-method interface for user subclasses."""
    fn = getattr(comp, "compress_for", None)
    if fn is not None:
        return fn(tensor, group_size)
    return comp.compress(tensor)


def fused_allreduce(
    tensors: List[Any],
    *,
    op: str = Average,
    compression=Compression.none,
    process_set=None,
    threshold_bytes: Optional[int] = None,
    plan: Optional[FusionPlan] = None,
    residuals: Optional[List[Any]] = None,
):
    """Allreduce a list of tensors with static bucketing; returns the list in
    the original order (reference semantics: grouped allreduce results are
    per-input, horovod/common/controller.cc FuseResponses).  ``plan``
    overrides the threshold bucketing with an explicit
    :class:`FusionPlan` (profile-guided tuning); buckets dispatch in plan
    order, which is the overlap schedule under XLA's latency-hiding
    scheduler.  A plan may carry per-bucket ``bucket_compression``
    (registry names) overriding the global ``compression`` for its
    members — the planner's wire-format knob.

    ``residuals`` (a list aligned with ``tensors``) switches on error
    feedback: each float tensor reduces ``t + r`` and the call returns
    ``(outputs, new_residuals)`` with ``r' = (t + r) - dequantized local
    contribution`` (docs/compression.md) — the residual list is the
    explicit state the caller must thread to the next step."""
    from .compression import _compressible

    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError("fused_allreduce must run inside an SPMD region")
    if process_set is None:
        groups, group_size = None, core.size()
    else:
        groups, group_size = process_set.groups(), process_set.size()
    # group identity surfaced to dispatch: restricted-communicator
    # reductions ride the group-labelled traced inventory (the flat
    # world is the unlabelled default, counted at the collectives seam)
    group_label = dispatch_group_label(process_set)
    if group_label != "world":
        for _ in tensors:
            _metrics.record_traced_group("allreduce", group_label)
    if residuals is not None and len(residuals) != len(tensors):
        raise ValueError(
            f"error-feedback residual list has {len(residuals)} entries "
            f"for {len(tensors)} tensors")

    # per-tensor compressor: the plan's per-bucket choice where given,
    # the global compression elsewhere.  Resolution happens BEFORE the
    # compress pass so each tensor is quantized exactly once, with its
    # own scale, in its bucket's wire format.
    comps = [compression] * len(tensors)
    if plan is not None and plan.bucket_compression:
        for bi, bucket in enumerate(plan.buckets):
            name = plan.bucket_compression[bi] \
                if bi < len(plan.bucket_compression) else None
            if name:
                comp = Compression.lookup(name)
                for i in bucket:
                    comps[i] = comp

    compressed = []
    ctxs = []
    new_res: Optional[List[Any]] = list(residuals) \
        if residuals is not None else None
    for i, t in enumerate(tensors):
        x = t
        ef = residuals is not None and _compressible(t)
        if ef:
            x = t + residuals[i].astype(t.dtype)
        c, ctx = _compress_with(comps[i], x, group_size)
        if ef:
            # this rank's dequantized contribution to the sum; what the
            # wire dropped is carried to the next step
            new_res[i] = (x - comps[i].decompress(c, ctx)).astype(
                residuals[i].dtype)
        compressed.append(c)
        ctxs.append(ctx)

    if plan is None:
        plan = FusionPlan(compressed, threshold_bytes)
    elif {i for b in plan.buckets for i in b} != set(range(len(compressed))):
        # exact coverage both ways: a stale plan (model gained or lost a
        # parameter since it was built) must fail loudly, not silently
        # return None in place of the uncovered gradients
        raise ValueError(
            f"fusion plan covers {sum(len(b) for b in plan.buckets)} "
            f"tensors but the call passed {len(compressed)}")
    out: List[Any] = [None] * len(tensors)
    for bucket in plan.buckets:
        if len(bucket) == 1:
            i = bucket[0]
            red = _reduce_flat(compressed[i], op=op, axes=axes, groups=groups,
                               group_size=group_size)
            out[i] = comps[i].decompress(red, ctxs[i])
            continue
        flats = [compressed[i].reshape(-1) for i in bucket]
        fused = jnp.concatenate(flats)
        red = _reduce_flat(fused, op=op, axes=axes, groups=groups,
                           group_size=group_size)
        offset = 0
        for i in bucket:
            n = compressed[i].size
            piece = lax.dynamic_slice_in_dim(red, offset, n).reshape(
                compressed[i].shape
            )
            out[i] = comps[i].decompress(piece, ctxs[i])
            offset += n
    if new_res is not None:
        return out, new_res
    return out


def allreduce_pytree(
    tree,
    *,
    op: str = Average,
    compression=Compression.none,
    process_set=None,
    threshold_bytes: Optional[int] = None,
    sparse_as_dense: bool = False,
    named_buckets: Optional[Sequence[Sequence[str]]] = None,
    bucket_compression: Optional[Sequence[Optional[str]]] = None,
    residual=None,
):
    """Fused allreduce over every array leaf of a pytree (gradients).

    ``IndexedSlices`` leaves take the sparse allgather path (reference
    tensorflow/__init__.py:75-90) unless ``sparse_as_dense`` (reference
    DistributedOptimizer option) densifies them first.

    ``named_buckets`` applies an explicit profile-guided fusion plan
    (lists of tensor names in dispatch order, the replay plan payload's
    vocabulary) matched against the tree's slash-joined leaf paths —
    see :meth:`FusionPlan.from_named_buckets` for the matching rules.
    ``bucket_compression`` (registry names aligned with
    ``named_buckets``) selects a wire format per bucket — the
    profile-guided compression decision (docs/compression.md).

    ``residual`` (a pytree shaped like ``tree``) switches on error
    feedback: the call reduces ``tree + residual`` and returns
    ``(reduced, new_residual)``; the caller owns the residual state
    (``TrainState.residual``, ``DistributedOptimizer`` state).  Sparse
    leaves keep their residual untouched (the allgather path is
    exact)."""
    from .sparse import (
        allreduce_indexed_slices, is_indexed_slices, to_dense,
    )

    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=is_indexed_slices
    )
    res_leaves = None
    if residual is not None:
        res_leaves = jax.tree_util.tree_flatten(
            residual, is_leaf=is_indexed_slices)[0]
        if len(res_leaves) != len(leaves):
            raise ValueError(
                "error-feedback residual pytree does not match the "
                f"gradient pytree ({len(res_leaves)} vs {len(leaves)} "
                "leaves) — initialize it with ErrorFeedback.init_state")
    names = tree_leaf_names(tree, is_leaf=is_indexed_slices) \
        if named_buckets else [""] * len(leaves)
    dense_idx = []
    dense_leaves = []
    dense_names = []
    dense_res = [] if res_leaves is not None else None
    out: list = [None] * len(leaves)
    res_out: list = list(res_leaves) if res_leaves is not None else []
    for i, leaf in enumerate(leaves):
        if is_indexed_slices(leaf):
            if sparse_as_dense:
                dense_idx.append(i)
                dense_leaves.append(to_dense(leaf))
                dense_names.append(names[i])
                if dense_res is not None:
                    # sparse residuals are dense zero trees; EF on the
                    # densified form is well defined
                    dense_res.append(res_leaves[i])
            else:
                out[i] = allreduce_indexed_slices(
                    leaf, op=op, process_set=process_set
                )
        else:
            dense_idx.append(i)
            dense_leaves.append(leaf)
            dense_names.append(names[i])
            if dense_res is not None:
                dense_res.append(res_leaves[i])
    plan = FusionPlan.from_named_buckets(
        dense_leaves, dense_names, named_buckets,
        bucket_compression=bucket_compression) if named_buckets else None
    reduced = fused_allreduce(
        dense_leaves, op=op, compression=compression,
        process_set=process_set, threshold_bytes=threshold_bytes,
        plan=plan, residuals=dense_res,
    )
    if dense_res is not None:
        reduced, new_dense_res = reduced
        for i, r in zip(dense_idx, new_dense_res):
            res_out[i] = r
    for i, r in zip(dense_idx, reduced):
        out[i] = r
    result = jax.tree_util.tree_unflatten(treedef, out)
    if residual is not None:
        return result, jax.tree_util.tree_unflatten(treedef, res_out)
    return result
