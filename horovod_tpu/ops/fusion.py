"""Tensor fusion: bucketing many small tensors into few large collectives.

TPU-native re-design of the fusion buffer (reference
horovod/common/fusion_buffer_manager.cc/.h — persistent 64 MB buffers per
(device, framework, stream) — plus the response-fusion pass in
controller.cc:665 FuseResponses and the MemcpyIn/OutFusionBuffer kernels in
ops/collective_operations.cc).

On TPU there is no persistent staging buffer and no memcpy kernel: we
flatten each gradient leaf, group leaves of the same dtype into buckets of
at most ``HVD_FUSION_THRESHOLD`` bytes (reference default 64 MB,
common.h:69), concatenate each bucket, run ONE ``psum`` per bucket, and
split back.  XLA fuses the concat/split with neighbors, and its own
all-reduce combiner provides a second level of batching — the autotuner
(optim/autotune.py) owns both knobs, as SURVEY §7.3(2) requires.

Bucketing is a *trace-time* planner (shapes are static under jit), which is
exactly the negotiated-once-then-cached steady state of the reference's
response cache — except the "cache" is the compiled executable.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .. import core
from ..core import Average, Sum
from ..utils import env as env_util
from .compression import Compression


class FusionPlan:
    """A static bucketing of a fixed list of (shape, dtype) leaves."""

    def __init__(self, leaves: Sequence[Any], threshold_bytes: Optional[int] = None):
        if threshold_bytes is None:
            threshold_bytes = env_util.fusion_threshold_bytes()
        self.threshold_bytes = max(int(threshold_bytes), 1)
        # bucket := list of leaf indices, all same dtype, total bytes <= threshold
        self.buckets: List[List[int]] = []
        current: dict = {}  # dtype -> (bucket_idx, bytes_so_far)
        for i, leaf in enumerate(leaves):
            dt = jnp.result_type(leaf)
            nbytes = leaf.size * dt.itemsize
            slot = current.get(dt)
            if slot is not None and slot[1] + nbytes <= self.threshold_bytes:
                self.buckets[slot[0]].append(i)
                current[dt] = (slot[0], slot[1] + nbytes)
            else:
                self.buckets.append([i])
                current[dt] = (len(self.buckets) - 1, nbytes)

    def num_buckets(self) -> int:
        return len(self.buckets)


def _reduce_flat(flat, *, op, axes, groups, group_size):
    if len(axes) == 1:
        out = lax.psum(flat, axes[0], axis_index_groups=groups)
    else:
        out = lax.psum(flat, axes)
    if op == Average:
        out = out / group_size
    return out


def fused_allreduce(
    tensors: List[Any],
    *,
    op: str = Average,
    compression=Compression.none,
    process_set=None,
    threshold_bytes: Optional[int] = None,
):
    """Allreduce a list of tensors with static bucketing; returns the list in
    the original order (reference semantics: grouped allreduce results are
    per-input, horovod/common/controller.cc FuseResponses)."""
    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError("fused_allreduce must run inside an SPMD region")
    if process_set is None:
        groups, group_size = None, core.size()
    else:
        groups, group_size = process_set.groups(), process_set.size()

    compressed = []
    ctxs = []
    for t in tensors:
        c, ctx = compression.compress(t)
        compressed.append(c)
        ctxs.append(ctx)

    plan = FusionPlan(compressed, threshold_bytes)
    out: List[Any] = [None] * len(tensors)
    for bucket in plan.buckets:
        if len(bucket) == 1:
            i = bucket[0]
            red = _reduce_flat(compressed[i], op=op, axes=axes, groups=groups,
                               group_size=group_size)
            out[i] = compression.decompress(red, ctxs[i])
            continue
        flats = [compressed[i].reshape(-1) for i in bucket]
        fused = jnp.concatenate(flats)
        red = _reduce_flat(fused, op=op, axes=axes, groups=groups,
                           group_size=group_size)
        offset = 0
        for i in bucket:
            n = compressed[i].size
            piece = lax.dynamic_slice_in_dim(red, offset, n).reshape(
                compressed[i].shape
            )
            out[i] = compression.decompress(piece, ctxs[i])
            offset += n
    return out


def allreduce_pytree(
    tree,
    *,
    op: str = Average,
    compression=Compression.none,
    process_set=None,
    threshold_bytes: Optional[int] = None,
    sparse_as_dense: bool = False,
):
    """Fused allreduce over every array leaf of a pytree (gradients).

    ``IndexedSlices`` leaves take the sparse allgather path (reference
    tensorflow/__init__.py:75-90) unless ``sparse_as_dense`` (reference
    DistributedOptimizer option) densifies them first."""
    from .sparse import (
        allreduce_indexed_slices, is_indexed_slices, to_dense,
    )

    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=is_indexed_slices
    )
    dense_idx = []
    dense_leaves = []
    out: list = [None] * len(leaves)
    for i, leaf in enumerate(leaves):
        if is_indexed_slices(leaf):
            if sparse_as_dense:
                dense_idx.append(i)
                dense_leaves.append(to_dense(leaf))
            else:
                out[i] = allreduce_indexed_slices(
                    leaf, op=op, process_set=process_set
                )
        else:
            dense_idx.append(i)
            dense_leaves.append(leaf)
    reduced = fused_allreduce(
        dense_leaves, op=op, compression=compression,
        process_set=process_set, threshold_bytes=threshold_bytes,
    )
    for i, r in zip(dense_idx, reduced):
        out[i] = r
    return jax.tree_util.tree_unflatten(treedef, out)
