from .collectives import (  # noqa: F401
    allreduce,
    grouped_allreduce,
    allgather,
    allgatherv,
    broadcast,
    alltoall,
    reducescatter,
    allreduce_gradients,
)
from .compression import Compression  # noqa: F401
