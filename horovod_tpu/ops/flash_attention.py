"""Pallas TPU flash-attention kernels.

The reference framework contains no attention code at all (SURVEY §5:
sequence parallelism "absent"); long-context support is a first-class goal
of the TPU build, and this module is its compute core: a blockwise
online-softmax ("flash") attention kernel family written in Pallas so the
hot loop runs out of VMEM and the q·kᵀ / p·v contractions land on the MXU.

Kernel structure: the kv loop is the innermost *grid* dimension (not a
``fori_loop``) with the streaming accumulators in VMEM scratch that
persists across grid steps — this lets the Mosaic pipeline overlap each
kv-block DMA with the previous block's compute, which is ~2x over the
loop-over-resident-kv formulation.

Three public entry points:

* :func:`flash_attention` — full (normalized) local attention with a
  custom VJP whose backward pass is also Pallas kernels.  Drop-in
  ``attention_fn`` for the flax models and the local step of Ulysses.
* :func:`mha_partial` — unnormalized streaming triple ``(o, m, l)`` for one
  q-shard × kv-shard pair with *global-position* causal masking via
  dynamic offsets; this is the per-hop block compute of ring attention
  (the offsets arrive as scalar-prefetch operands, so the ring step can
  pass traced ``lax.axis_index``-derived values).
* :func:`mha_bwd_dq` / :func:`mha_bwd_dkv` — backward blocks with the same
  offset masking, used by the ring attention backward rotation.

All kernels take/return the ``[batch, heads, seq, head_dim]`` layout; the
callers transpose from the model-facing ``[batch, seq, heads, head_dim]``.

Off-TPU (the CPU test mesh) the kernels run in Pallas interpreter mode,
which keeps every test oracle-checkable on the 8-device virtual slice.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Finite stand-in for -inf: keeps exp()-of-differences NaN-free for fully
# masked rows (exp(NEG_INF - NEG_INF) = 1, then zeroed by the mask select).
NEG_INF = -1e30

# 512x512 measured best on the v5e across 128..1024 sweeps (beats both
# smaller blocks and XLA's fused attention at seq>=2048, docs/PERF.md);
# _fit_block shrinks automatically for shorter sequences
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512

_DIM_SEMANTICS = ("parallel", "parallel", "parallel", "arbitrary")


def _on_tpu() -> bool:
    """True if the devices the framework runs on are TPUs.

    The mesh devices, not ``jax.devices()[0]``, are authoritative: the test
    harness runs an 8-device *CPU* mesh even when a TPU backend is present
    (conftest.py), and there the kernels must take the interpreter path.
    """
    try:
        from .. import core

        dev = (core.mesh().devices.flat[0] if core.is_initialized()
               else jax.devices()[0])
    except Exception:  # pragma: no cover - no backend at all
        return False
    return "tpu" in dev.platform.lower() or "TPU" in getattr(
        dev, "device_kind", ""
    )


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    return (not _on_tpu()) if interpret is None else interpret


def _offsets(q_offset, kv_offset):
    q_offset = jnp.asarray(q_offset, jnp.int32).reshape(())
    kv_offset = jnp.asarray(kv_offset, jnp.int32).reshape(())
    return jnp.stack([q_offset, kv_offset])


def _compiler_params(interpret):
    if interpret:
        return None
    return pltpu.CompilerParams(dimension_semantics=_DIM_SEMANTICS)


def _fit_block(seq: int, cap: int) -> int:
    """Largest divisor of ``seq`` that is <= ``cap``, preferring a
    lane-aligned multiple of 8 (MXU tiling) — but only when alignment
    doesn't collapse the block (e.g. seq 136: plain 68 beats aligned 8)."""
    cap = min(cap, seq)
    aligned = next(
        (b for b in range(cap, 0, -1) if seq % b == 0 and b % 8 == 0), 0
    )
    plain = next((b for b in range(cap, 0, -1) if seq % b == 0), 1)
    return aligned if aligned * 4 >= plain else plain


def _check_blocks(sq, sk, block_q, block_k):
    """Fit block sizes to the seq lengths: the grid must tile exactly (a
    non-dividing seq would silently truncate the grid and leave the tail
    of the output uninitialized), so shrink each block to the largest
    divisor of its seq length instead of erroring on shapes like 192/128."""
    return _fit_block(sq, block_q), _fit_block(sk, block_k)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                acc_ref, mi_ref, li_ref, *,
                causal, scale, normalize):
    bq, d = q_ref.shape[2], q_ref.shape[3]
    bk = k_ref.shape[2]
    iq = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    q_off = offs_ref[0]
    kv_off = offs_ref[1]

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        mi_ref[:] = jnp.full_like(mi_ref, NEG_INF)
        li_ref[:] = jnp.zeros_like(li_ref)

    def compute():
        q = q_ref[0, 0]
        kb = k_ref[0, 0]
        vb = v_ref[0, 0]
        s = lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            q_pos = (q_off + iq * bq
                     + lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            k_pos = (kv_off + j * bk
                     + lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, NEG_INF)
        m_prev = mi_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        mi_ref[:] = m_new
        li_ref[:] = li_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Skip kv blocks strictly in the future of every row of this q block.
        pl.when(kv_off + j * bk <= q_off + iq * bq + bq - 1)(compute)
    else:
        compute()

    @pl.when(j == nk - 1)
    def _():
        acc = acc_ref[:]
        if normalize:
            acc = acc / jnp.maximum(li_ref[:], 1e-30)
        o_ref[0, 0] = acc.astype(o_ref.dtype)
        m_ref[0, 0] = mi_ref[:]
        l_ref[0, 0] = li_ref[:]


def _mha_fwd(q, k, v, offs, *, causal, scale, block_q, block_k,
             normalize, interpret):
    """q/k/v ``[b,h,s,d]``; returns ``(o, m, l)`` with m/l ``[b,h,sq,1]``."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _check_blocks(sq, sk, block_q, block_k)
    interpret = _resolve_interpret(interpret)
    grid = (b, h, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, normalize=normalize,
    )
    out_dtype = q.dtype if normalize else jnp.float32
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h_, i, j, *_: (b_, h_, j, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h_, i, j, *_: (b_, h_, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1),
                             lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1),
                             lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), out_dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(offs, q, k, v)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc_ref, *, causal, scale):
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    iq = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    q_off = offs_ref[0]
    kv_off = offs_ref[1]

    @pl.when(j == 0)
    def _():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    def compute():
        q = q_ref[0, 0]
        kb = k_ref[0, 0]
        vb = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            q_pos = (q_off + iq * bq
                     + lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            k_pos = (kv_off + j * bk
                     + lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        if causal:
            p = jnp.where(mask, p, 0.0)
        dp = lax.dot_general(
            do, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dq_acc_ref[:] += lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(kv_off + j * bk <= q_off + iq * bq + bq - 1)(compute)
    else:
        compute()

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0, 0] = dq_acc_ref[:]


def _bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *,
                    causal, scale):
    bk = k_ref.shape[2]
    bq = q_ref.shape[2]
    ik = pl.program_id(2)
    i = pl.program_id(3)
    nq = pl.num_programs(3)
    q_off = offs_ref[0]
    kv_off = offs_ref[1]

    @pl.when(i == 0)
    def _():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    def compute():
        kb = k_ref[0, 0]
        vb = v_ref[0, 0]
        qb = q_ref[0, 0]
        dob = do_ref[0, 0]
        lseb = lse_ref[0, 0]
        deltab = delta_ref[0, 0]
        s = lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            q_pos = (q_off + i * bq
                     + lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            k_pos = (kv_off + ik * bk
                     + lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lseb)
        if causal:
            p = jnp.where(mask, p, 0.0)
        dv_acc_ref[:] += lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - deltab) * scale
        dk_acc_ref[:] += lax.dot_general(
            ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Skip q blocks entirely before this kv block.
        pl.when(kv_off + ik * bk <= q_off + i * bq + bq - 1)(compute)
    else:
        compute()

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc_ref[:]
        dv_ref[0, 0] = dv_acc_ref[:]


def _mha_bwd_dq(q, k, v, do, lse, delta, offs, *, causal, scale, block_q,
                block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _check_blocks(sq, sk, block_q, block_k)
    interpret = _resolve_interpret(interpret)
    grid = (b, h, sq // block_q, sk // block_k)
    kernel = functools.partial(_bwd_dq_kernel, causal=causal, scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h_, i, j, *_: (b_, h_, j, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h_, i, j, *_: (b_, h_, j, 0)),
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1),
                             lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1),
                             lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, d),
                                   lambda b_, h_, i, j, *_: (b_, h_, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), jnp.float32),
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(offs, q, k, v, do, lse, delta)


def _mha_bwd_dkv(q, k, v, do, lse, delta, offs, *, causal, scale, block_q,
                 block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _check_blocks(sq, sk, block_q, block_k)
    interpret = _resolve_interpret(interpret)
    grid = (b, h, sk // block_k, sq // block_q)
    kernel = functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b_, h_, jk, i, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h_, jk, i, *_: (b_, h_, jk, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h_, jk, i, *_: (b_, h_, jk, 0)),
                pl.BlockSpec((1, 1, block_q, d),
                             lambda b_, h_, jk, i, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1),
                             lambda b_, h_, jk, i, *_: (b_, h_, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1),
                             lambda b_, h_, jk, i, *_: (b_, h_, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h_, jk, i, *_: (b_, h_, jk, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b_, h_, jk, i, *_: (b_, h_, jk, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(offs, q, k, v, do, lse, delta)


# ---------------------------------------------------------------------------
# ring building blocks (dynamic offsets, [b,h,s,d] layout)
# ---------------------------------------------------------------------------


def mha_partial(q, k, v, q_offset, kv_offset, *, causal, scale,
                block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                interpret=None):
    """Unnormalized streaming triple ``(o[f32], m, l)`` for one q-shard ×
    kv-shard pair; offsets are *global positions* and may be traced.
    m/l come back ``[b,h,sq,1]`` so they broadcast against ``o``."""
    return _mha_fwd(
        q, k, v, _offsets(q_offset, kv_offset), causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, normalize=False,
        interpret=interpret,
    )


def mha_bwd_dq(q, k, v, do, lse, delta, q_offset, kv_offset, *, causal,
               scale, block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
               interpret=None):
    """dq (f32) contribution of one kv shard; lse/delta are ``[b,h,sq,1]``."""
    return _mha_bwd_dq(
        q, k, v, do, lse, delta, _offsets(q_offset, kv_offset),
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


def mha_bwd_dkv(q, k, v, do, lse, delta, q_offset, kv_offset, *, causal,
                scale, block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                interpret=None):
    """(dk, dv) (f32) contributions of one q shard to one kv shard."""
    return _mha_bwd_dkv(
        q, k, v, do, lse, delta, _offsets(q_offset, kv_offset),
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# local flash attention with custom VJP
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _flash_fn(causal, scale, block_q, block_k, interpret):
    kw = dict(causal=causal, scale=scale, block_q=block_q, block_k=block_k,
              interpret=interpret)

    @jax.custom_vjp
    def f(q, k, v, offs):
        o, _, _ = _mha_fwd(q, k, v, offs, normalize=True, **kw)
        return o

    def fwd(q, k, v, offs):
        o, m, l = _mha_fwd(q, k, v, offs, normalize=True, **kw)
        lse = m + jnp.log(jnp.maximum(l, 1e-30))  # [b,h,sq,1]
        return o, (q, k, v, o, lse, offs)

    def bwd(res, do):
        q, k, v, o, lse, offs = res
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
        dq = _mha_bwd_dq(q, k, v, do, lse, delta, offs, **kw)
        dk, dv = _mha_bwd_dkv(q, k, v, do, lse, delta, offs, **kw)
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
                np.zeros(offs.shape, dtype=jax.dtypes.float0))

    f.defvjp(fwd, bwd)
    return f


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    q_offset=0, kv_offset=0,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None):
    """Flash attention over local shards, differentiable end to end.

    Args:
      q, k, v: ``[batch, seq, heads, head_dim]`` (the model-facing layout
        used throughout :mod:`horovod_tpu.parallel`).
      causal: apply causal masking in global positions
        (``q_offset + i >= kv_offset + j``).
      scale: logit scale, default ``1/sqrt(head_dim)``.
      q_offset, kv_offset: global position of element 0 of the q / kv
        shards (used by sequence-parallel callers).

    Returns attention output, same shape/dtype as ``q``.
    """
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    fn = _flash_fn(bool(causal), float(scale), int(block_q), int(block_k),
                   _resolve_interpret(interpret))
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = fn(qt, kt, vt, _offsets(q_offset, kv_offset))
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)


def softmax_attention(q, k, v, *, causal: bool = False,
                      scale: Optional[float] = None):
    """Plain (materialized) softmax attention in ``[b,s,h,d]`` layout —
    the XLA-fused reference path the flash kernels are checked against,
    shared by the Ulysses local step and the benchmarks' --attn xla
    mode.  XLA fuses the chain; memory is O(s^2)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    sl = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s = q.shape[1]
        pos = jnp.arange(s)
        sl = jnp.where((pos[:, None] >= pos[None, :])[None, None], sl,
                       -jnp.inf)
    p = jax.nn.softmax(sl, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
