"""Adasum: scale-invariant adaptive summation of gradients.

TPU-native re-design of Microsoft's Adasum (reference
horovod/common/ops/adasum/adasum.h — the vector-halving distance-doubling
(VHDD) allreduce documented at adasum.h:167-195, with the per-merge
coefficient math in DispatchComputeDotAndNormSqrds (:101-121) and
DispatchScaledAdd (:124-140); MPI point-to-point variant in
adasum_mpi_operations.cc, NCCL-hierarchical variant in
adasum_gpu_operations.cc).

The math: merging two gradients a, b uses

    a' = (1 - <a,b> / (2 |a|^2)) * a  +  (1 - <a,b> / (2 |b|^2)) * b

applied recursively over a binary tree of ranks (distance doubling:
partner = rank XOR 2^k at level k).  When a and b are orthogonal this is a
plain sum; when parallel, an average — interpolating smoothly so larger
effective batch sizes don't require LR retuning.

On TPU we keep the distance-doubling recursion but exchange *whole* vectors
via ``lax.ppermute`` instead of halving them over MPI send/recv: ICI
bandwidth makes the halving optimization unnecessary at gradient sizes, and
whole-vector exchange keeps every rank's state identical (deterministic,
no reassembly allgather at the end — the reference needs one because each
rank owns only a fragment).  Dot products are computed in fp32 regardless
of input dtype, matching the reference's accumulate-in-double for fp16
inputs (adasum.h DispatchComputeDotAndNormSqrds).

``numpy_adasum`` is the reference implementation used by tests, mirroring
the NumPy checker in reference test/test_adasum_pytorch.py:16-32.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import core


def _adasum_combine(a, b, dot, na2, nb2):
    """The Adasum coefficient merge, numerically guarded like the reference
    (zero-norm ranks contribute as plain sum)."""
    eps = jnp.asarray(1e-30, jnp.float32)
    ca = 1.0 - dot / jnp.maximum(2.0 * na2, eps)
    cb = 1.0 - dot / jnp.maximum(2.0 * nb2, eps)
    ca = jnp.where(na2 == 0, 1.0, ca)
    cb = jnp.where(nb2 == 0, 1.0, cb)
    return (ca * a.astype(jnp.float32) + cb * b.astype(jnp.float32)).astype(a.dtype)


def _vhdd(tensor, axis, n, pos, *, perm_for_level, dot_reduce=None):
    """The distance-doubling Adasum recursion shared by the flat,
    process-set, and hierarchical variants.

    ``pos`` is this rank's position within the reducing group (traced);
    ``perm_for_level(level)`` builds the ppermute pairing; ``dot_reduce``,
    when set, sums the partial dot/norm values over the ranks sharding the
    vector — the analog of the reference's SumAllreduceWithComm over the
    reduction communicator (adasum.h:370-372), which makes sharded ranks
    use FULL-vector dot products.
    """
    a = tensor
    level = 1
    while level < n:
        b = lax.ppermute(a, axis, perm_for_level(level))
        af = a.astype(jnp.float32)
        bf = b.astype(jnp.float32)
        dot = jnp.sum(af * bf)
        na2 = jnp.sum(af * af)
        nb2 = jnp.sum(bf * bf)
        if dot_reduce is not None:
            dot, na2, nb2 = dot_reduce(jnp.stack([dot, na2, nb2]))
        # Both members of a pair must compute the SAME combination, so order
        # the operands canonically by position parity at this level.
        low_first = (pos // level) % 2 == 0
        first = jnp.where(low_first, 1.0, 0.0)
        a_c = first * af + (1 - first) * bf
        b_c = first * bf + (1 - first) * af
        na_c = first * na2 + (1 - first) * nb2
        nb_c = first * nb2 + (1 - first) * na2
        a = _adasum_combine(a_c, b_c, dot, na_c, nb_c).astype(tensor.dtype)
        level *= 2
    return a


def adasum_allreduce(tensor, *, process_set: Optional[object] = None,
                     hierarchical: bool = False):
    """Adasum-allreduce ``tensor`` across all ranks (power-of-two count).

    Exposed through ``hvd.allreduce(x, op=hvd.Adasum)`` exactly as the
    reference exposes ``ReduceOp.ADASUM`` (horovod/torch/mpi_ops.py:103-119,
    which also asserts the power-of-two requirement).  With
    ``hierarchical=True`` (or on the 2-D (cross, local) mesh) this is the
    reference's GPU-hierarchical variant (adasum_gpu_operations.cc:250-261):
    plain reduce-scatter within the node, Adasum VHDD across nodes on each
    local shard (with full-vector dot products via a local psum),
    allgather back.
    """
    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError("adasum_allreduce must run inside an SPMD region")
    if len(axes) == 2:
        if process_set is not None:
            raise NotImplementedError(
                "Adasum over a process subset of the hierarchical mesh"
            )
        return _hierarchical_adasum_2d(tensor, axes)
    if hierarchical:
        if process_set is not None:
            raise NotImplementedError(
                "hierarchical Adasum over a process subset"
            )
        return _hierarchical_adasum_flat(tensor, axes[0])

    axis = axes[0]
    if process_set is not None:
        k = process_set.size()
        if k & (k - 1):
            raise ValueError(
                f"Adasum requires a power-of-two rank count, got {k}"
            )
        if k == 1:
            return tensor
        ranks = list(process_set.ranks)
        member_set = set(ranks)
        _, pos = process_set.member_position()

        def perm_for_level(level):
            # XOR pairing on positions *within the set*; non-members map to
            # themselves — an identity exchange is an Adasum fixed point
            # (ca = cb = 1/2 with a == b), so they pass through unchanged.
            perm = [(r, r) for r in range(core.size()) if r not in member_set]
            perm += [(ranks[i], ranks[i ^ level]) for i in range(k)]
            return perm

        return _vhdd(tensor, axis, k, pos, perm_for_level=perm_for_level)

    n = core.size()
    if n & (n - 1):
        raise ValueError(f"Adasum requires a power-of-two rank count, got {n}")
    if n == 1:
        return tensor
    rank = lax.axis_index(axis)
    # partner = rank XOR level — the distance-doubling pairing of VHDD
    # (reference adasum.h:167-195).
    return _vhdd(
        tensor, axis, n, rank,
        perm_for_level=lambda level: [(r, r ^ level) for r in range(n)],
    )


def _check_cross_pow2(cross_n: int) -> None:
    if cross_n & (cross_n - 1):
        raise ValueError(
            f"hierarchical Adasum requires a power-of-two node count, "
            f"got {cross_n}"
        )


def _hierarchical_adasum_2d(tensor, axes):
    """Local reduce-scatter → cross VHDD on shards → local allgather, on
    the 2-D (cross, local) mesh."""
    cross_axis, local_axis = axes
    cross_n = core.cross_size()
    local_n = core.local_size()
    _check_cross_pow2(cross_n)
    flat = tensor.reshape(-1)
    pad = (-flat.shape[0]) % local_n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    # Node-internal stage: plain sum reduce-scatter (the reference's NCCL
    # ReduceScatter with start_level=local_size skipping the local VHDD
    # levels, adasum_gpu_operations.cc:257).
    shard = lax.psum_scatter(flat, local_axis, scatter_dimension=0, tiled=True)
    if cross_n > 1:
        crank = lax.axis_index(cross_axis)
        shard = _vhdd(
            shard, cross_axis, cross_n, crank,
            perm_for_level=lambda level: [
                (r, r ^ level) for r in range(cross_n)
            ],
            dot_reduce=lambda v: lax.psum(v, local_axis),
        )
    out = lax.all_gather(shard, local_axis, axis=0, tiled=True)
    if pad:
        out = out[:-pad]
    return out.reshape(tensor.shape)


def _hierarchical_adasum_flat(tensor, axis):
    """Same three phases on the flat 1-D mesh with axis_index_groups (the
    style of parallel/hierarchical.py, so it composes with the 1-D rank
    model used by make_train_step)."""
    from ..parallel.hierarchical import _local_groups

    ls = core.local_size()
    cross_n = core.cross_size()
    _check_cross_pow2(cross_n)
    if cross_n == 1:
        # Single node: the reference GPU variant degenerates to a plain
        # local sum (its cross-node Adasum stage is empty).
        return lax.psum(tensor, axis)
    flat = tensor.reshape(-1)
    pad = (-flat.shape[0]) % ls
    if pad:
        flat = jnp.pad(flat, (0, pad))
    local_groups = _local_groups()
    shard = lax.psum_scatter(
        flat, axis, scatter_dimension=0, tiled=True,
        axis_index_groups=local_groups,
    )
    crank = lax.axis_index(axis) // ls
    shard = _vhdd(
        shard, axis, cross_n, crank,
        perm_for_level=lambda level: [
            (n * ls + r, (n ^ level) * ls + r)
            for n in range(cross_n) for r in range(ls)
        ],
        dot_reduce=lambda v: lax.psum(
            v, axis, axis_index_groups=local_groups
        ),
    )
    out = lax.all_gather(
        shard, axis, axis=0, tiled=True, axis_index_groups=local_groups
    )
    if pad:
        out = out[:-pad]
    return out.reshape(tensor.shape)


def numpy_adasum_pair(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Reference two-operand Adasum (float64 accumulate), mirroring the
    NumPy checker in reference test/test_adasum_pytorch.py:16-32."""
    af = a.astype(np.float64).ravel()
    bf = b.astype(np.float64).ravel()
    dot = float(af @ bf)
    na2 = float(af @ af)
    nb2 = float(bf @ bf)
    ca = 1.0 if na2 == 0 else 1.0 - dot / (2.0 * na2)
    cb = 1.0 if nb2 == 0 else 1.0 - dot / (2.0 * nb2)
    return (ca * a.astype(np.float64) + cb * b.astype(np.float64)).astype(a.dtype)


def numpy_hierarchical_adasum(tensors, local_size: int) -> np.ndarray:
    """Oracle for the hierarchical variant: sum within each node, Adasum
    across the node sums (the reference GPU variant's semantics —
    reduce-scatter is a plain sum, VHDD dots span the full vector)."""
    vals = [np.asarray(t, np.float64) for t in tensors]
    assert len(vals) % local_size == 0
    node_sums = [
        np.sum(vals[i: i + local_size], axis=0)
        for i in range(0, len(vals), local_size)
    ]
    if len(node_sums) == 1:
        return node_sums[0].astype(np.asarray(tensors[0]).dtype)
    return numpy_adasum(node_sums).astype(np.asarray(tensors[0]).dtype)


def numpy_adasum(tensors) -> np.ndarray:
    """Tree-reduce a list of per-rank arrays with the same pairing order the
    device implementation uses (rank XOR distance).

    Non-power-of-two counts use remainder folding, the classic
    recursive-doubling remainder trick (the reference clamps its VHDD comm
    setup to nearest_power_2 the same way, adasum.h:209-217 /
    adasum_mpi.cc:45-52, but its bindings then refuse such world sizes —
    torch/mpi_ops.py:117-118): with p = largest power of two <= n, each
    rank p+i first merges into rank i via the same scale-invariant pair
    rule, then the standard VHDD tree runs over the p survivors.  The
    merge being Adasum (not a plain sum) keeps the defining invariants at
    every count: identical inputs -> that input, orthogonal inputs -> sum."""
    vals = [np.asarray(t) for t in tensors]
    n = len(vals)
    p = 1
    while p * 2 <= n:
        p *= 2
    for r in range(p, n):
        vals[r - p] = numpy_adasum_pair(vals[r - p], vals[r])
    vals = vals[:p]
    n = p
    level = 1
    while level < n:
        nxt = list(vals)
        for r in range(n):
            p = r ^ level
            lo, hi = (r, p) if (r // level) % 2 == 0 else (p, r)
            nxt[r] = numpy_adasum_pair(vals[lo], vals[hi])
        vals = nxt
        level *= 2
    return vals[0]
