"""Adasum: scale-invariant adaptive summation of gradients.

TPU-native re-design of Microsoft's Adasum (reference
horovod/common/ops/adasum/adasum.h — the vector-halving distance-doubling
(VHDD) allreduce documented at adasum.h:167-195, with the per-merge
coefficient math in DispatchComputeDotAndNormSqrds (:101-121) and
DispatchScaledAdd (:124-140); MPI point-to-point variant in
adasum_mpi_operations.cc, NCCL-hierarchical variant in
adasum_gpu_operations.cc).

The math: merging two gradients a, b uses

    a' = (1 - <a,b> / (2 |a|^2)) * a  +  (1 - <a,b> / (2 |b|^2)) * b

applied recursively over a binary tree of ranks (distance doubling:
partner = rank XOR 2^k at level k).  When a and b are orthogonal this is a
plain sum; when parallel, an average — interpolating smoothly so larger
effective batch sizes don't require LR retuning.

On TPU we keep the distance-doubling recursion but exchange *whole* vectors
via ``lax.ppermute`` instead of halving them over MPI send/recv: ICI
bandwidth makes the halving optimization unnecessary at gradient sizes, and
whole-vector exchange keeps every rank's state identical (deterministic,
no reassembly allgather at the end — the reference needs one because each
rank owns only a fragment).  Dot products are computed in fp32 regardless
of input dtype, matching the reference's accumulate-in-double for fp16
inputs (adasum.h DispatchComputeDotAndNormSqrds).

``numpy_adasum`` is the reference implementation used by tests, mirroring
the NumPy checker in reference test/test_adasum_pytorch.py:16-32.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import core


def _adasum_combine(a, b, dot, na2, nb2):
    """The Adasum coefficient merge, numerically guarded like the reference
    (zero-norm ranks contribute as plain sum)."""
    eps = jnp.asarray(1e-30, jnp.float32)
    ca = 1.0 - dot / jnp.maximum(2.0 * na2, eps)
    cb = 1.0 - dot / jnp.maximum(2.0 * nb2, eps)
    ca = jnp.where(na2 == 0, 1.0, ca)
    cb = jnp.where(nb2 == 0, 1.0, cb)
    return (ca * a.astype(jnp.float32) + cb * b.astype(jnp.float32)).astype(a.dtype)


def adasum_allreduce(tensor, *, process_set: Optional[object] = None):
    """Adasum-allreduce ``tensor`` across all ranks (power-of-two count).

    Exposed through ``hvd.allreduce(x, op=hvd.Adasum)`` exactly as the
    reference exposes ``ReduceOp.ADASUM`` (horovod/torch/mpi_ops.py:103-119,
    which also asserts the power-of-two requirement).
    """
    axes = core._spmd_axes()
    if axes is None:
        raise RuntimeError("adasum_allreduce must run inside an SPMD region")
    if process_set is not None:
        raise NotImplementedError("Adasum over a process subset")
    n = core.size()
    if n & (n - 1):
        raise ValueError(f"Adasum requires a power-of-two rank count, got {n}")
    if n == 1:
        return tensor

    axis = axes[0] if len(axes) == 1 else axes[0]
    if len(axes) == 2:
        raise NotImplementedError(
            "Adasum over the hierarchical mesh: flatten with hvd.spmd "
            "(hierarchical=False)"
        )

    rank = lax.axis_index(axis)
    a = tensor
    level = 1
    while level < n:
        # partner = rank XOR level — the distance-doubling pairing of VHDD
        # (reference adasum.h:167-195).
        perm = [(r, r ^ level) for r in range(n)]
        b = lax.ppermute(a, axis, perm)
        af = a.astype(jnp.float32)
        bf = b.astype(jnp.float32)
        dot = jnp.sum(af * bf)
        na2 = jnp.sum(af * af)
        nb2 = jnp.sum(bf * bf)
        # Both members of a pair must compute the SAME combination, so order
        # the operands canonically by rank parity at this level.
        low_first = (rank // level) % 2 == 0
        first = jnp.where(low_first, 1.0, 0.0)
        a_c = first * af + (1 - first) * bf
        b_c = first * bf + (1 - first) * af
        na_c = first * na2 + (1 - first) * nb2
        nb_c = first * nb2 + (1 - first) * na2
        a = _adasum_combine(a_c, b_c, dot, na_c, nb_c).astype(tensor.dtype)
        level *= 2
    return a


def numpy_adasum_pair(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Reference two-operand Adasum (float64 accumulate), mirroring the
    NumPy checker in reference test/test_adasum_pytorch.py:16-32."""
    af = a.astype(np.float64).ravel()
    bf = b.astype(np.float64).ravel()
    dot = float(af @ bf)
    na2 = float(af @ af)
    nb2 = float(bf @ bf)
    ca = 1.0 if na2 == 0 else 1.0 - dot / (2.0 * na2)
    cb = 1.0 if nb2 == 0 else 1.0 - dot / (2.0 * nb2)
    return (ca * a.astype(np.float64) + cb * b.astype(np.float64)).astype(a.dtype)


def numpy_adasum(tensors) -> np.ndarray:
    """Tree-reduce a list of per-rank arrays with the same pairing order the
    device implementation uses (rank XOR distance)."""
    vals = [np.asarray(t) for t in tensors]
    n = len(vals)
    assert n & (n - 1) == 0, "power-of-two rank count required"
    level = 1
    while level < n:
        nxt = list(vals)
        for r in range(n):
            p = r ^ level
            lo, hi = (r, p) if (r // level) % 2 == 0 else (p, r)
            nxt[r] = numpy_adasum_pair(vals[lo], vals[hi])
        vals = nxt
        level *= 2
    return vals[0]
