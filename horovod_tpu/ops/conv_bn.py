"""Pallas fused 3x3-conv + BatchNorm kernels — the round-4 named-lever
experiment (docs/PERF.md "custom Pallas conv+BN kernels could shave part
of the elementwise traffic" on the HBM-bound 56x56 ResNet stage).

Two variants, matching the two halves of XLA's own training-BN
structure (PERF.md trace: `convert_reduce_fusion` = conv with fused
BN-stat epilogues, `multiply_add_fusion` = conv fused with BN-apply
chains):

* :func:`conv3x3_bn_relu` — conv + folded-BN affine + ReLU in one pass
  (the inference/apply shape: stats are inputs);
* :func:`conv3x3_stats` — conv emitting per-channel sum/sum-of-squares
  epilogues accumulated across the batch grid (the training-stats
  shape).

One grid step processes one image: the whole padded 56x56 input tile
lives in VMEM (~430 KB bf16 at C=64) and each of the 9 taps is a
``[H*W, Cin] @ [Cin, Cout]`` MXU matmul accumulated in f32 — the
classic shift-and-matmul conv lowering.  Measured against XLA's fused
equivalents by ``scripts/pallas_conv_bn_experiment.py``; the verdict
(positive or negative) is recorded in docs/PERF.md.

Off-TPU the kernels run in interpreter mode, same policy as
ops/flash_attention.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _resolve_interpret


def _conv_taps(x_ref, w_ref, h: int, w: int, cin: int):
    """Sum of the nine shift-and-matmul taps, f32 accumulation.
    x_ref: [1, H+2, W+2, Cin] (padded); w_ref: [9*Cin, Cout]."""
    acc = None
    for dy in range(3):
        for dx in range(3):
            win = x_ref[0, dy:dy + h, dx:dx + w, :].reshape(h * w, cin)
            tap = w_ref[(dy * 3 + dx) * cin:(dy * 3 + dx + 1) * cin, :]
            t = jnp.dot(win, tap, preferred_element_type=jnp.float32)
            acc = t if acc is None else acc + t
    return acc  # [H*W, Cout] f32


def _bn_relu_kernel(x_ref, w_ref, scale_ref, bias_ref, o_ref):
    h, w = o_ref.shape[1], o_ref.shape[2]
    cin = x_ref.shape[3]
    acc = _conv_taps(x_ref, w_ref, h, w, cin)
    y = acc * scale_ref[0][None, :] + bias_ref[0][None, :]
    o_ref[0] = jnp.maximum(y, 0).reshape(
        h, w, o_ref.shape[3]).astype(o_ref.dtype)


def _stats_kernel(x_ref, w_ref, o_ref, sum_ref, sq_ref):
    h, w = o_ref.shape[1], o_ref.shape[2]
    cin = x_ref.shape[3]
    acc = _conv_taps(x_ref, w_ref, h, w, cin)
    o_ref[0] = acc.reshape(h, w, o_ref.shape[3]).astype(o_ref.dtype)
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    # grid steps run sequentially on TPU: accumulate the per-channel
    # BN-stat epilogues into the shared [1, C] outputs
    sum_ref[0, :] += acc.sum(axis=0)
    sq_ref[0, :] += (acc * acc).sum(axis=0)


def _plain_kernel(x_ref, w_ref, o_ref):
    h, w = o_ref.shape[1], o_ref.shape[2]
    cin = x_ref.shape[3]
    acc = _conv_taps(x_ref, w_ref, h, w, cin)
    o_ref[0] = acc.reshape(h, w, o_ref.shape[3]).astype(o_ref.dtype)


def _pad_and_pack(x, w):
    if x.ndim != 4 or w.shape[:2] != (3, 3) or w.shape[2] != x.shape[3]:
        raise ValueError(f"need NHWC x + [3,3,Cin,Cout] w, got "
                         f"{x.shape} / {w.shape}")
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    cin, cout = w.shape[2], w.shape[3]
    wp = w.reshape(9 * cin, cout)
    return xp, wp, cin, cout


def conv3x3_bn_relu(x, w, scale, bias, *,
                    interpret: Optional[bool] = None):
    """``relu(conv3x3_same(x, w) * scale + bias)`` in one Pallas pass.
    x: [B, H, W, Cin] NHWC; w: [3, 3, Cin, Cout]; scale/bias: [Cout]
    (the folded-BN affine, scale = gamma*rsqrt(var+eps))."""
    xp, wp, cin, cout = _pad_and_pack(x, w)
    b, h, wd = x.shape[0], x.shape[1], x.shape[2]
    return pl.pallas_call(
        _bn_relu_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h + 2, wd + 2, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9 * cin, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, wd, cout), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, wd, cout), x.dtype),
        interpret=_resolve_interpret(interpret),
    )(xp, wp, scale.reshape(1, cout).astype(jnp.float32),
      bias.reshape(1, cout).astype(jnp.float32))


def conv3x3_stats(x, w, *, interpret: Optional[bool] = None):
    """``conv3x3_same(x, w)`` plus fused per-channel sum / sum-of-squares
    epilogues (the BN-stats half of training BN).  Returns
    ``(y [B,H,W,Cout], sum [Cout] f32, sumsq [Cout] f32)``."""
    xp, wp, cin, cout = _pad_and_pack(x, w)
    b, h, wd = x.shape[0], x.shape[1], x.shape[2]
    y, s, sq = pl.pallas_call(
        _stats_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h + 2, wd + 2, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9 * cin, cout), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, wd, cout), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, wd, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        interpret=_resolve_interpret(interpret),
    )(xp, wp)
    return y, s[0], sq[0]


def conv3x3_plain(x, w, *, interpret: Optional[bool] = None):
    """``conv3x3_same(x, w)`` alone (used for the transpose conv in the
    fused op's backward: stride-1 SAME conv-transpose == conv with
    spatially-flipped, io-transposed weights — no dilation)."""
    xp, wp, cin, cout = _pad_and_pack(x, w)
    b, h, wd = x.shape[0], x.shape[1], x.shape[2]
    return pl.pallas_call(
        _plain_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h + 2, wd + 2, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9 * cin, cout), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, wd, cout), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, wd, cout), x.dtype),
        interpret=_resolve_interpret(interpret),
    )(xp, wp)


# ---------------------------------------------------------------------------
# Training-mode fused op: conv + batch-stats + BN-normalize + ReLU with a
# custom VJP implementing the full BatchNorm backward (gradients flow
# through mean/var, exactly like flax.linen.BatchNorm under autodiff).
# ---------------------------------------------------------------------------
@partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def conv3x3_bn_relu_train(x, w, gamma, beta, eps: float = 1e-5,
                          interpret: Optional[bool] = None):
    """Training forward: ``relu(BN(conv3x3_same(x, w)))`` with batch
    statistics, as one Pallas conv+stats pass plus an elementwise apply.
    Returns ``(out, batch_mean, batch_var)`` — the caller updates running
    stats from mean/var (their cotangents are treated as zero, matching
    flax's stop-gradient running-average update)."""
    out, mean, var, _ = _cbr_fwd_impl(x, w, gamma, beta, eps, interpret)
    return out, mean, var


def _cbr_fwd_impl(x, w, gamma, beta, eps, interpret):
    from jax import lax

    y, s, sq = conv3x3_stats(x, w, interpret=interpret)
    n = x.shape[0] * x.shape[1] * x.shape[2]
    mean = s / n
    var = jnp.maximum(sq / n - mean * mean, 0.0)
    rstd = lax.rsqrt(var + eps)
    yf = y.astype(jnp.float32)
    xhat = (yf - mean) * rstd
    out = jnp.maximum(xhat * gamma + beta, 0.0).astype(x.dtype)
    return out, mean, var, (x, w, y, mean, rstd, gamma, out)


def _cbr_fwd(x, w, gamma, beta, eps, interpret):
    out, mean, var, res = _cbr_fwd_impl(x, w, gamma, beta, eps, interpret)
    return (out, mean, var), res


def _cbr_bwd(eps, interpret, res, cts):
    from jax import lax

    x, w, y, mean, rstd, gamma, out = res
    g_out = cts[0].astype(jnp.float32)  # mean/var feed the stop-gradient
    #                                     running-stats update: ct == 0
    n = x.shape[0] * x.shape[1] * x.shape[2]
    mask = out.astype(jnp.float32) > 0
    g = jnp.where(mask, g_out, 0.0)
    xhat = (y.astype(jnp.float32) - mean) * rstd
    dbeta = g.sum(axis=(0, 1, 2))
    dgamma = (g * xhat).sum(axis=(0, 1, 2))
    # standard BN backward (gradient through mean and var):
    dy = (gamma * rstd) * (g - dbeta / n - xhat * (dgamma / n))
    dy = dy.astype(x.dtype)
    wt = jnp.flip(w, axis=(0, 1)).transpose(0, 1, 3, 2)  # [3,3,Cout,Cin]
    dx = conv3x3_plain(dy, wt, interpret=interpret)
    # weight grad through XLA's conv machinery (it is a conv over the
    # batch dim; nothing Pallas would improve here)
    _, w_vjp = jax.vjp(
        lambda w_: lax.conv_general_dilated(
            x, w_, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ), w,
    )
    (dw,) = w_vjp(dy)
    return dx, dw, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


conv3x3_bn_relu_train.defvjp(_cbr_fwd, _cbr_bwd)


# ---------------------------------------------------------------------------
# XLA reference twins (the A side of the A/B): exactly what the compiler
# builds today for the same math.
# ---------------------------------------------------------------------------
def xla_conv3x3_bn_relu(x, w, scale, bias):
    from jax import lax

    y = lax.conv_general_dilated(
        x, w.astype(x.dtype), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return jnp.maximum(
        y.astype(jnp.float32) * scale + bias, 0).astype(x.dtype)


def xla_conv3x3_stats(x, w):
    from jax import lax

    y = lax.conv_general_dilated(
        x, w.astype(x.dtype), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    yf = y.astype(jnp.float32)
    return y, yf.sum(axis=(0, 1, 2)), (yf * yf).sum(axis=(0, 1, 2))
