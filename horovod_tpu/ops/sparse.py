"""Sparse (IndexedSlices) gradients: allgather-based reduction.

The reference converts sparse gradients to an allgather of (values,
indices) instead of a dense allreduce (horovod/tensorflow/__init__.py:75-90:
``if isinstance(tensor, tf.IndexedSlices): return tf.IndexedSlices(
allgather(values) / horovod_size, allgather(indices))``) — embedding-heavy
models only ship the touched rows.  TPU-native version:

* :class:`IndexedSlices` — a pytree (values ``[k, ...]``, indices ``[k]``,
  static ``dense_shape``), the JAX carrier for embedding-style gradients.
* :func:`allreduce_indexed_slices` — ``lax.all_gather(tiled)`` of values and
  indices over the mesh axis; Average divides values by the group size.
  Duplicate indices are legal — consumers scatter-**add**.
* :func:`to_dense` — scatter-add into the dense shape (XLA lowers to an
  efficient sorted scatter on TPU).
* :func:`embedding_grad_as_slices` — sparse gradient of a table used only
  through ``table[ids]``, taken w.r.t. the gathered rows (TF produces
  IndexedSlices from ``tf.gather`` automatically; JAX cotangents must
  structurally match their primal, so the sparsity is recovered at the
  lookup boundary instead).

``fusion.allreduce_pytree`` routes IndexedSlices leaves here, and
``DistributedOptimizer(sparse_as_dense=True)`` forces the dense path
(reference DistributedOptimizer's ``sparse_as_dense`` option,
tensorflow/__init__.py:267-319).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from .. import core
from ..core import Average, Sum


@jax.tree_util.register_pytree_node_class
class IndexedSlices:
    """Sparse rows of a dense tensor: ``dense[indices[i]] += values[i]``.

    ``values``: ``[k, *dense_shape[1:]]``; ``indices``: ``[k]`` int32;
    ``dense_shape``: static tuple (aux data — jit-stable).
    """

    def __init__(self, values, indices, dense_shape: Sequence[int]):
        self.values = values
        self.indices = indices
        self.dense_shape = tuple(int(d) for d in dense_shape)

    def tree_flatten(self):
        return (self.values, self.indices), self.dense_shape

    @classmethod
    def tree_unflatten(cls, dense_shape, children):
        values, indices = children
        return cls(values, indices, dense_shape)

    def __repr__(self):
        return (f"IndexedSlices(values={self.values!r}, "
                f"indices={self.indices!r}, dense_shape={self.dense_shape})")


def is_indexed_slices(x: Any) -> bool:
    return isinstance(x, IndexedSlices)


def to_dense(s: IndexedSlices):
    """Scatter-add the slices into their dense shape."""
    dense = jnp.zeros(s.dense_shape, jnp.result_type(s.values))
    return dense.at[s.indices].add(s.values)


def allreduce_indexed_slices(
    s: IndexedSlices,
    *,
    op: str = Average,
    process_set=None,
) -> IndexedSlices:
    """Cross-rank reduction of sparse rows by allgathering (values, indices)
    (reference tensorflow/__init__.py:75-90).  Must run inside an SPMD
    region.  The result holds every rank's rows concatenated — duplicates
    are resolved by the consumer's scatter-add, matching TF IndexedSlices
    semantics."""
    from .collectives import allgather

    if core._spmd_axes() is None:
        raise RuntimeError(
            "allreduce_indexed_slices must run inside an SPMD region"
        )
    size = process_set.size() if process_set is not None else core.size()

    # collectives.allgather owns the group handling (incl. the uneven-
    # process-set psum-embed fallback XLA's all_gather can't lower)
    values = allgather(s.values, process_set=process_set)
    indices = allgather(s.indices, process_set=process_set)
    if op == Average:
        values = values / size
    elif op != Sum:
        raise ValueError(f"unsupported op for sparse allreduce: {op}")
    return IndexedSlices(values, indices, s.dense_shape)


# ---------------------------------------------------------------------------
# sparse-gradient producer
# ---------------------------------------------------------------------------
def embedding_grad_as_slices(loss_of_rows, table, ids, *args, **kwargs):
    """Sparse gradient of an embedding table used only through ``table[ids]``.

    TF produces IndexedSlices from ``tf.gather`` automatically; JAX
    cotangents must structurally match their primal, so the sparse gradient
    is taken w.r.t. the *gathered rows* instead — exact whenever the table
    enters the loss only via this lookup (the embedding-layer contract)::

        loss, slices = embedding_grad_as_slices(
            lambda rows: loss_fn(rows, batch), table, ids)
        grads = {"embedding": slices, ...}        # flows through
        hvd.DistributedOptimizer(...)             # the sparse allgather path

    Returns ``(loss, IndexedSlices)`` with one row per lookup (duplicate
    ids stay duplicated; scatter-add resolves them, as in TF).
    """
    rows = jnp.take(table, ids, axis=0)
    loss, g_rows = jax.value_and_grad(loss_of_rows)(rows, *args, **kwargs)
    flat_ids = ids.reshape(-1)
    flat_g = g_rows.reshape((flat_ids.shape[0],) + tuple(table.shape[1:]))
    return loss, IndexedSlices(flat_g, flat_ids, table.shape)


def densify_tree(tree):
    """Convert every IndexedSlices leaf to its dense tensor (what optax
    update rules consume)."""
    return jax.tree_util.tree_map(
        lambda x: to_dense(x) if is_indexed_slices(x) else x,
        tree, is_leaf=is_indexed_slices,
    )
