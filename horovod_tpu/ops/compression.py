"""Gradient compression: the wire-efficiency tier's ops layer.

Grew out of the reference's stateless cast pair
(horovod/torch/compression.py / horovod/tensorflow/compression.py:
``Compressor`` with ``compress``/``decompress`` and the ``Compression``
namespace) into a registry of wire formats plus an error-feedback
wrapper (docs/compression.md):

* :class:`NoneCompressor` / :class:`BF16Compressor` — kept, API
  compatible (``fp16`` stays an alias: bf16 is the TPU-native half
  type, same exponent range as fp32 so no loss scaling).
* :class:`Int8Compressor` / :class:`FP8Compressor` (e4m3) /
  :class:`FP8E5M2Compressor` — per-tensor-scaled quantizers.  The
  scale is the *global* max-|x| (a scalar ``pmax`` when called inside
  an SPMD region, the local max otherwise), so every rank dequantizes
  with the same factor; the quantized range is divided by the reducing
  group size so the integer/fp8 *sum* across ranks cannot wrap or
  saturate (XLA reduces in the wire dtype — an un-headroomed int8 psum
  over 8 ranks wraps, measured).  The precision lost to headroom is
  exactly what :class:`ErrorFeedback` carries forward.
* :class:`ErrorFeedback` — wraps any compressor with the
  residual-carrying scheme of deep-gradient-compression / 1-bit Adam
  (PAPERS.md lineage): each step reduces ``grad + residual`` and keeps
  ``residual' = (grad + residual) - dequantize(quantize(...))`` — the
  quantization error is fed back instead of dropped, so the *sum over
  steps* of what reached the optimizer tracks the true gradient sum.
  The residual is an explicit pytree threaded through
  ``allreduce_pytree``/``fused_allreduce`` (ops/fusion.py),
  ``DistributedOptimizer`` state (optim/distributed.py) and
  ``TrainState.residual`` (training.py) — surviving jit, checkpointing
  (utils/checkpoint.py saves the state pytree) and elastic rebuilds.
* :class:`ErrorFeedbackGuard` — the convergence guard: trips when the
  residual norm diverges (or goes non-finite), at which point the
  train step falls back to uncompressed allreduce
  (``hvd_compression_fallbacks_total``) instead of silently training
  on a broken wire format.

Every compressor passes integer/bool/complex leaves through untouched
(``_compressible``): gradients routed via ``allreduce_pytree`` can
carry non-float leaves (step counters, masks) and a cast would
silently corrupt them.

``numpy_quantize``/``numpy_dequantize`` are the ground-truth mirrors
used by tests, in the style of ``ops/adasum.py``'s ``numpy_adasum``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type

import numpy as np

import jax.numpy as jnp


def _compressible(tensor) -> bool:
    """Only real floating leaves are compressed; integer/bool/complex
    leaves pass through untouched (casting them would corrupt data,
    not round it)."""
    return jnp.issubdtype(jnp.result_type(tensor), jnp.floating)


def _global_max_abs(tensor):
    """max |x| across every rank reducing this tensor — inside an SPMD
    region a scalar pmax (every rank must dequantize with the SAME
    factor for the reduced sum to mean anything), the local max
    otherwise (single-rank/eager use)."""
    from jax import lax

    from .. import core

    m = jnp.max(jnp.abs(tensor.astype(jnp.float32)))
    axes = core._spmd_axes()
    if axes is not None:
        m = lax.pmax(m, axes if len(axes) > 1 else axes[0])
    return m


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    #: registry name (Compression.lookup vocabulary)
    name = "none"
    #: wire bytes per element (None = unchanged) — the cost model's
    #: comm_report.COMPRESSION_MODEL must agree with these
    wire_itemsize: Optional[int] = None
    #: True when compress needs a cross-rank scale exchange (the α the
    #: cost model bills per compressed collective)
    scale_exchange = False

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) — context is whatever
        decompress needs to undo the transform."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError

    @classmethod
    def compress_for(cls, tensor, group_size: int):
        """Compress for a reduction over ``group_size`` ranks.  The
        stateless cast compressors ignore the group size; quantizers
        use it to reserve summation headroom."""
        del group_size
        return cls.compress(tensor)


class NoneCompressor(Compressor):
    """No-op (reference compression.py NoneCompressor)."""

    name = "none"

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class BF16Compressor(Compressor):
    """Cast to bfloat16 for the collective, cast back after.

    The reference's FP16Compressor halves wire bytes on NCCL rings; here
    it halves ICI bytes, and since bf16 is MXU-native the reduce itself
    also runs at full throughput.
    """

    name = "bf16"
    wire_itemsize = 2

    @staticmethod
    def compress(tensor):
        if not _compressible(tensor):
            return tensor, None        # int/bool/complex: untouched
        ctx = tensor.dtype
        if tensor.dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), ctx
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class _ScaledQuantizer(Compressor):
    """Shared scale/headroom arithmetic for the int8/fp8 wire formats.

    ``q = round_or_cast(x / scale * (max_mag / group_size))`` with
    ``scale = global max |x|``: every |q| ≤ max_mag / group_size, so the
    sum over the reducing group stays within the wire dtype's range —
    no wrap (int8) and no saturation (fp8).  ``ctx`` carries
    ``(orig_dtype, dequant_factor)``; dequantization is linear, so it
    commutes with the Average division the collective layer applies.
    """

    #: wire dtype's maximum representable magnitude
    max_mag = 1.0
    wire_dtype = jnp.int8

    @classmethod
    def _quantize(cls, x32, headroom):
        raise NotImplementedError

    @classmethod
    def compress_for(cls, tensor, group_size: int):
        if not _compressible(tensor):
            return tensor, None
        headroom = cls.max_mag / max(int(group_size), 1)
        if headroom < 2.0:
            # fewer than two quantization levels survive the summation
            # headroom (int8 over >63 ranks, e4m3 over >224): every
            # value would truncate toward zero and the "compressed"
            # gradient is noise.  Ship uncompressed instead — the flat
            # quantized path is for small worlds; big worlds compress
            # the cross stage of two_level_allreduce, whose group is
            # the (small) host count.
            from ..utils.logging import get_logger

            get_logger(__name__).warning(
                "%s over a %d-rank group leaves %.2f quantization "
                "levels — shipping uncompressed (use two-level "
                "reduction to compress across hosts instead)",
                cls.name, group_size, headroom)
            return tensor, None
        orig_dtype = tensor.dtype
        scale = jnp.maximum(_global_max_abs(tensor),
                            jnp.asarray(1e-30, jnp.float32))
        q = cls._quantize(tensor.astype(jnp.float32) / scale, headroom)
        return q, (orig_dtype, scale / headroom)

    @classmethod
    def compress(cls, tensor):
        # eager / single-rank entry: no summation headroom needed
        return cls.compress_for(tensor, 1)

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is None:
            return tensor
        orig_dtype, factor = ctx
        return (tensor.astype(jnp.float32) * factor).astype(orig_dtype)


class Int8Compressor(_ScaledQuantizer):
    """Per-tensor-scaled symmetric int8 (round-to-nearest, clipped)."""

    name = "int8"
    wire_itemsize = 1
    scale_exchange = True
    max_mag = 127.0
    wire_dtype = jnp.int8

    @classmethod
    def _quantize(cls, x_unit, headroom):
        # clip to the HEADROOM, not max_mag: round(±headroom) can land
        # one grid step above it (127/8 = 15.875 rounds to 16, and
        # 8 x 16 = 128 wraps int8) — the truncating int cast then keeps
        # every |q| <= floor(headroom), so the group sum can never wrap
        q = jnp.clip(jnp.round(x_unit * headroom), -headroom, headroom)
        return q.astype(jnp.int8)


class FP8Compressor(_ScaledQuantizer):
    """Per-tensor-scaled float8 e4m3 (448 max, ~3 mantissa bits)."""

    name = "fp8_e4m3"
    wire_itemsize = 1
    scale_exchange = True
    max_mag = 448.0
    wire_dtype = jnp.float8_e4m3fn

    @classmethod
    def _quantize(cls, x_unit, headroom):
        return (x_unit * headroom).astype(cls.wire_dtype)


class FP8E5M2Compressor(FP8Compressor):
    """float8 e5m2: wider range (57344 max), ~2 mantissa bits — for
    gradients whose dynamic range overwhelms e4m3."""

    name = "fp8_e5m2"
    max_mag = 57344.0
    wire_dtype = jnp.float8_e5m2


# ---------------------------------------------------------------------------
# error feedback
# ---------------------------------------------------------------------------
class ErrorFeedback:
    """Carry the quantization residual across steps (DGC / 1-bit-Adam
    scheme).  Stateless compressor calls delegate to the wrapped
    compressor; the residual arithmetic itself lives where the state
    does — ``fused_allreduce(..., residuals=...)`` applies

        x  = grad + residual
        q  = compress(x);  reduce(q)
        residual' = x - decompress_local(q)

    so this wrapper's job is (a) marking the compression as stateful
    and (b) building the initial residual pytree.  Wrapping
    :class:`NoneCompressor` is a valid degenerate case (residual stays
    0) — and switching a compressed job back to ``none`` flushes the
    outstanding residual into the next reduction instead of dropping
    it."""

    stateful = True

    def __init__(self, compressor: Optional[Type[Compressor]] = None):
        self.compressor = compressor if compressor is not None \
            else Int8Compressor

    @property
    def name(self) -> str:
        return f"ef_{self.compressor.name}"

    @property
    def wire_itemsize(self):
        return self.compressor.wire_itemsize

    @property
    def scale_exchange(self):
        return self.compressor.scale_exchange

    def compress(self, tensor):
        return self.compressor.compress(tensor)

    def compress_for(self, tensor, group_size: int):
        return self.compressor.compress_for(tensor, group_size)

    def decompress(self, tensor, ctx):
        return self.compressor.decompress(tensor, ctx)

    @staticmethod
    def init_state(tree):
        """Zero residual pytree shaped like the gradients (float leaves
        carry state; non-float leaves get zeros that stay zeros)."""
        import jax

        return jax.tree_util.tree_map(jnp.zeros_like, tree)


class ErrorFeedbackGuard:
    """Convergence guard for error-feedback compression: the residual
    norm of a healthy EF loop is bounded by the per-step quantization
    error; a norm that grows past ``factor`` × its early baseline (or
    goes non-finite) means the feedback loop is diverging and the job
    must fall back to uncompressed allreduce (training.py increments
    ``hvd_compression_fallbacks_total`` and rebuilds without
    compression).  Pure host-side float logic so it is deterministic
    across processes observing the same replicated residual."""

    def __init__(self, factor: Optional[float] = None, warmup: int = 3):
        from ..utils import env as env_util

        self.factor = factor if factor is not None else env_util.get_float(
            env_util.HVD_COMPRESSION_GUARD_FACTOR,
            env_util.DEFAULT_COMPRESSION_GUARD_FACTOR)
        self.warmup = max(int(warmup), 1)
        self._early: List[float] = []
        self.baseline: Optional[float] = None

    def observe(self, norm: float) -> bool:
        """Feed one residual-norm sample; True = diverged (fall back)."""
        norm = float(norm)
        if not np.isfinite(norm):
            return True
        if self.baseline is None:
            self._early.append(norm)
            if len(self._early) < self.warmup:
                return False
            self.baseline = float(np.median(self._early))
            return False
        return norm > self.factor * max(self.baseline, 1e-30)


def _sq_norm(leaves):
    total = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        x = leaf.astype(jnp.float32)
        total = total + jnp.vdot(x, x)
    return total


_sq_norm_jit = None


def residual_norm(residual) -> float:
    """Global L2 norm of a residual pytree (float leaves only) — the
    ``hvd_compression_residual_norm`` gauge's value.  One jitted
    reduction + one device sync per call (jit caches by leaf structure,
    so the guard cadence pays a single dispatch, not one per leaf)."""
    import jax

    global _sq_norm_jit
    leaves = [leaf for leaf in jax.tree_util.tree_leaves(residual)
              if _compressible(leaf)]
    if not leaves:
        return 0.0
    if _sq_norm_jit is None:
        _sq_norm_jit = jax.jit(_sq_norm)
    return float(np.sqrt(max(float(_sq_norm_jit(leaves)), 0.0)))


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------
_REGISTRY: Dict[str, Type[Compressor]] = {
    "none": NoneCompressor,
    "fp16": BF16Compressor,   # parity alias: bf16 is the TPU half type
    "bf16": BF16Compressor,
    "int8": Int8Compressor,
    "fp8": FP8Compressor,
    "fp8_e4m3": FP8Compressor,
    "fp8_e5m2": FP8E5M2Compressor,
}


class Compression:
    """Gradient compression registry used during allreduce (grew out of
    the reference compression.py Compression namespace).  Attribute
    access for the built-ins, :meth:`lookup` for knob/plan strings
    (``HVD_COMPRESSION``, ``tpurun --compression``, per-bucket plan
    payloads), :meth:`register` for custom wire formats."""

    none = NoneCompressor
    fp16 = BF16Compressor  # parity alias: bf16 is the TPU-native half type
    bf16 = BF16Compressor
    int8 = Int8Compressor
    fp8 = FP8Compressor
    fp8_e4m3 = FP8Compressor
    fp8_e5m2 = FP8E5M2Compressor

    @staticmethod
    def names() -> List[str]:
        return sorted(_REGISTRY)

    @staticmethod
    def lookup(name: Optional[str], error_feedback: bool = False):
        """Resolve a compressor by registry name (None/'' → none).
        ``error_feedback=True`` wraps the result in
        :class:`ErrorFeedback` (a no-op for ``none``)."""
        key = str(name).strip().lower() if name else "none"
        if key.startswith("ef_"):
            key = key[3:]
            error_feedback = True
        try:
            comp = _REGISTRY[key]
        except KeyError:
            raise ValueError(
                f"unknown compression {name!r}; registered: "
                f"{', '.join(Compression.names())}") from None
        if error_feedback and comp is not NoneCompressor:
            return ErrorFeedback(comp)
        return comp

    @staticmethod
    def register(name: str, compressor: Type[Compressor]) -> None:
        _REGISTRY[str(name).strip().lower()] = compressor


def from_env():
    """The job-level compression choice: ``HVD_COMPRESSION`` (none |
    bf16 | int8 | fp8 | fp8_e5m2), error-feedback-wrapped unless
    ``HVD_COMPRESSION_ERROR_FEEDBACK=0`` (quantized wire formats
    without EF bias the gradient; EF is the accuracy story)."""
    from ..utils import env as env_util

    name = env_util.get_str(env_util.HVD_COMPRESSION, "none")
    ef = env_util.get_bool(env_util.HVD_COMPRESSION_ERROR_FEEDBACK, True)
    return Compression.lookup(name, error_feedback=ef)


# ---------------------------------------------------------------------------
# numpy ground truth (tests; ops/adasum.py numpy_adasum style)
# ---------------------------------------------------------------------------
def _numpy_wire(name: str):
    import ml_dtypes

    return {"int8": (np.int8, 127.0),
            "fp8_e4m3": (ml_dtypes.float8_e4m3fn, 448.0),
            "fp8": (ml_dtypes.float8_e4m3fn, 448.0),
            "fp8_e5m2": (ml_dtypes.float8_e5m2, 57344.0)}[name]


def numpy_quantize(x: np.ndarray, group_size: int = 1,
                   wire: str = "int8"):
    """Reference quantizer: returns ``(q, dequant_factor)`` with the
    same scale/headroom rule the device compressors use."""
    dtype, max_mag = _numpy_wire(wire)
    scale = max(float(np.max(np.abs(x))), 1e-30)
    headroom = max_mag / max(int(group_size), 1)
    if wire == "int8":
        # clip to headroom, truncating int cast — mirrors the device
        # quantizer's no-wrap guarantee
        q = np.clip(np.round(x.astype(np.float64) / scale * headroom),
                    -headroom, headroom).astype(dtype)
    else:
        # f32 arithmetic throughout, like the device path (f64
        # intermediate would double-round the f8 cast differently)
        q = (x.astype(np.float32) / np.float32(scale)
             * np.float32(headroom)).astype(dtype)
    return q, scale / headroom


def numpy_dequantize(q: np.ndarray, factor: float) -> np.ndarray:
    return q.astype(np.float64) * factor


def numpy_error_feedback_reduce(per_rank_grads, residuals,
                                wire: str = "int8"):
    """One error-feedback compressed allreduce step over a list of
    per-rank gradients: returns ``(mean_gradient, new_residuals)`` —
    the oracle the device parity tests pin against."""
    n = len(per_rank_grads)
    qs, factors, new_res = [], [], []
    # shared scale: the global max over every rank's (grad + residual)
    xs = [np.asarray(g, np.float64) + np.asarray(r, np.float64)
          for g, r in zip(per_rank_grads, residuals)]
    scale = max(max(float(np.max(np.abs(x))) for x in xs), 1e-30)
    dtype, max_mag = _numpy_wire(wire)
    headroom = max_mag / n
    for x in xs:
        if wire == "int8":
            q = np.clip(np.round(x / scale * headroom),
                        -headroom, headroom).astype(dtype)
        else:
            q = (x.astype(np.float32) / np.float32(scale)
                 * np.float32(headroom)).astype(dtype)
        qs.append(q)
        new_res.append(x - numpy_dequantize(q, scale / headroom))
    total = np.sum([q.astype(np.float64) for q in qs], axis=0)
    return numpy_dequantize(total, scale / headroom) / n, new_res
