"""Gradient compression algorithms.

Mirror of horovod/torch/compression.py and horovod/tensorflow/compression.py
(reference, 75 LoC each): a ``Compressor`` with ``compress``/``decompress``
and the ``Compression`` namespace with ``none`` and ``fp16``.  On TPU the
natural wire dtype is bfloat16 (hardware-native on the MXU, same exponent
range as fp32 so no loss scaling needed) — ``fp16`` is kept as an alias for
API parity and maps to bf16.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) — context is whatever
        decompress needs to undo the transform."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """No-op (reference compression.py NoneCompressor)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class BF16Compressor(Compressor):
    """Cast to bfloat16 for the collective, cast back after.

    The reference's FP16Compressor halves wire bytes on NCCL rings; here it
    halves ICI bytes, and since bf16 is MXU-native the reduce itself also
    runs at full throughput.
    """

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and tensor.dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), ctx
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class Compression:
    """Optional gradient compression algorithm used during allreduce
    (reference compression.py Compression namespace)."""

    none = NoneCompressor
    fp16 = BF16Compressor  # parity alias: bf16 is the TPU-native half type
    bf16 = BF16Compressor
