"""DataFrame → Store ingestion: the missing half of the estimator data
contract (round-4 VERDICT Missing #1).

Mirror of the reference's ``prepare_data`` pipeline (reference
horovod/spark/common/util.py:550-582 prepare_data → column validation;
:167-241 _get_col_info schema inference, uniform-shape enforcement;
:123-165 check_shape_compatibility; :534-547 check_validation;
spark/keras/remote.py / spark/torch/remote.py then train from the
materialized files).  TPU-era shape: instead of Spark executors writing
petastorm row groups, the driver-side process compiles the DataFrame's
columns into dense numpy tensors and materializes them through the Store
(estimator/data.py npz shards + manifest) — the estimators then stream
shards back per rank exactly as they do for array inputs.

DataFrames are duck-typed so both real pyspark and the test stub work:
anything with ``.columns`` and ``.collect()`` yielding rows with
``asDict()`` (pyspark ``Row``) or mapping semantics.  Cell values may be
scalars, ``DenseVector``-likes (``toArray()``), or Python lists — the
reference's supported column kinds (util.py:179-197).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .store import Store

_SCHEMA_FILE = "_df_schema.json"


def _collect_rows(df) -> List[dict]:
    """Rows as dicts from a pyspark(-like) DataFrame or a row sequence."""
    rows = df.collect() if hasattr(df, "collect") else list(df)
    return [r.asDict() if hasattr(r, "asDict") else dict(r) for r in rows]


def _df_columns(df, rows: List[dict]) -> List[str]:
    cols = getattr(df, "columns", None)
    if cols is not None:
        return list(cols)
    return list(rows[0]) if rows else []


def _cell_to_array(value, col: str) -> np.ndarray:
    """One cell compiled to a numpy array (scalar -> shape (), vector ->
    shape (k,)) — the reference's per-row intermediate-format step
    (util.py:322-355 to_petastorm_fn)."""
    if value is None:
        raise ValueError(
            f"Column {col!r} has null values; the reference rejects "
            "NullType columns the same way (util.py:190-193)"
        )
    if hasattr(value, "toArray"):  # pyspark.ml.linalg Dense/SparseVector
        return np.asarray(value.toArray())
    return np.asarray(value)  # scalars, lists, tuples, ndarrays


def compile_columns(rows: List[dict], columns: Sequence[str]
                    ) -> Tuple[Dict[str, dict], Dict[str, np.ndarray]]:
    """ONE pass over the cells: validate the reference's uniformity rules
    (reference util.py:167-241 _get_col_info: every row of a column must
    have the same shape; mixed sizes are only legal for sparse vectors,
    which the dense TPU data path does not carry) and stack each column
    into an ``[n_rows, *cell_shape]`` tensor.  Returns (schema, arrays).
    """
    schema: Dict[str, dict] = {}
    arrays: Dict[str, np.ndarray] = {}
    for col in columns:
        cells = []
        shapes = set()
        for row in rows:
            if col not in row:
                raise ValueError(
                    f"Column {col!r} does not exist in the DataFrame"
                )
            a = _cell_to_array(row[col], col)
            shapes.add(a.shape)
            cells.append(a)
        if len(shapes) > 1:
            raise ValueError(
                f"Column {col!r} does not have uniform shape. "
                f"shape set: {sorted(shapes)}"
            )
        dtype = np.result_type(*cells) if cells else np.dtype(np.float32)
        if not (np.issubdtype(dtype, np.number)
                or np.issubdtype(dtype, np.bool_)):
            raise ValueError(
                f"Column {col!r} has non-numeric type {dtype}; cannot "
                "compile it to a tensor"
            )
        shape = shapes.pop() if shapes else ()
        schema[col] = {"shape": list(shape), "dtype": str(dtype)}
        arrays[col] = np.stack([c.astype(dtype) for c in cells]) \
            if cells else np.zeros((0,) + shape, dtype)
    return schema, arrays


def compile_features(arrays: Dict[str, np.ndarray],
                     columns: Sequence[str]) -> np.ndarray:
    """Feature columns flattened + concatenated into one ``[n, d]``
    matrix (scalars contribute one feature, vectors their length) — the
    column→tensor compilation the estimators train on."""
    parts = [arrays[c].reshape(arrays[c].shape[0], -1) for c in columns]
    common = np.result_type(*[p.dtype for p in parts])
    if np.issubdtype(common, np.floating):
        # Spark doubles compile to the f32 training norm (the reference's
        # torch remote trains float32 the same way); raw columns keep
        # their natural dtype under col:<name>
        common = np.float32
    return np.concatenate([p.astype(common) for p in parts], axis=1)


def check_validation(validation, columns: Sequence[str]) -> None:
    """reference util.py:534-547 check_validation: a float split must be
    in [0, 1); a string names an existing indicator column."""
    if validation is None:
        return
    if isinstance(validation, float):
        if not 0 <= validation < 1:
            raise ValueError(
                f"Validation split {validation} must be in the range: "
                "[0, 1)"
            )
    elif isinstance(validation, str):
        if validation not in columns:
            raise ValueError(
                f"Validation column {validation} does not exist in the "
                "DataFrame"
            )
    else:
        raise ValueError(
            'Param validation must be of type "float" or "str", found: '
            f"{type(validation)}"
        )


def _split_indices(n: int, rows: List[dict], validation,
                   seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """(train_idx, val_idx) for the reference's two validation forms:
    a float fraction (random split) or a truthy indicator column."""
    if validation is None:
        return np.arange(n), np.zeros(0, np.int64)
    if isinstance(validation, str):
        mask = np.asarray([bool(r[validation]) for r in rows])
        return np.flatnonzero(~mask), np.flatnonzero(mask)
    n_val = int(n * validation)
    order = np.random.default_rng(seed).permutation(n)
    return np.sort(order[n_val:]), np.sort(order[:n_val])


def prepare_data(store: Store, df, label_columns: Sequence[str],
                 feature_columns: Optional[Sequence[str]] = None, *,
                 run_id: str, validation=None,
                 rows_per_shard: int = 65536, verbose: int = 0) -> dict:
    """Validate the DataFrame's schema, compile columns to tensors, and
    materialize train (and optional validation) datasets through the
    Store (reference util.py:550-582 prepare_data + _get_or_create_dataset).

    Written datasets carry columns ``x`` (features compiled to one
    ``[n, d]`` matrix) and ``y`` (labels compiled the same way to
    ``[n, k]`` — ALWAYS 2-D, so a scalar label trains against a
    ``Linear(d, 1)``-shaped output without the silent (n,)-vs-(n,1)
    broadcast that turns MSE regression into mean prediction), every
    original column in its natural shape/dtype under ``col:<name>``,
    plus a ``_df_schema.json`` describing the source schema.  Returns
    the train manifest augmented with the schema.
    """
    import json
    import os

    rows = _collect_rows(df)
    columns = _df_columns(df, rows)
    if not label_columns:
        raise ValueError("Parameter label_columns cannot be None or empty")
    for col in label_columns:
        if col not in columns:
            raise ValueError(
                f"Label column {col} does not exist in the DataFrame"
            )
    check_validation(validation, columns)
    if feature_columns is None:
        excluded = set(label_columns)
        if isinstance(validation, str):
            excluded.add(validation)
        feature_columns = [c for c in columns if c not in excluded]
    else:
        for col in feature_columns:
            if col not in columns:
                raise ValueError(
                    f"Feature column {col} does not exist in the DataFrame"
                )
    if not feature_columns:
        raise ValueError(
            "No feature columns: every non-label column was excluded and "
            "feature_columns was not provided (or was empty)"
        )

    used = list(feature_columns) + [
        c for c in label_columns if c not in feature_columns
    ]
    schema, arrays = compile_columns(rows, used)
    x_all = compile_features(arrays, feature_columns)
    y_all = compile_features(arrays, label_columns)

    train_idx, val_idx = _split_indices(len(rows), rows, validation)

    def _materialize(idx: np.ndarray, path: str) -> dict:
        from .data import materialize_dataset

        data = {"x": x_all[idx], "y": y_all[idx]}
        data.update({f"col:{c}": arrays[c][idx] for c in used})
        manifest = materialize_dataset(
            store, run_id, data, rows_per_shard=rows_per_shard, path=path,
        )
        store.write(
            os.path.join(path, _SCHEMA_FILE),
            json.dumps({
                "feature_columns": list(feature_columns),
                "label_columns": list(label_columns),
                "columns": schema,
            }).encode(),
        )
        return manifest

    manifest = _materialize(train_idx, store.get_train_data_path(run_id))
    manifest = dict(manifest, schema=schema)
    if validation is not None:
        val_manifest = _materialize(
            val_idx, store.get_val_data_path(run_id)
        )
        manifest["n_val_rows"] = val_manifest["n_rows"]
    if verbose:
        print(
            f"prepare_data: {manifest['n_rows']} train rows"
            + (f", {manifest.get('n_val_rows', 0)} val rows"
               if validation is not None else "")
        )
    return manifest


def read_schema(store: Store, run_id: str) -> dict:
    import json
    import os

    base = store.get_train_data_path(run_id)
    return json.loads(store.read(os.path.join(base, _SCHEMA_FILE)).decode())
