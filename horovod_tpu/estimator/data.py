"""Store-resident training data: materialization + sharded reading.

Re-design of the reference's estimator data path (reference
horovod/spark/common/util.py ``prepare_data``: validate the DataFrame
schema, write a petastorm dataset into ``store.get_train_data_path``;
spark/keras/remote.py then trains from those files).  The TPU-era
equivalent materializes named numpy arrays as npz shards plus a JSON
manifest under the same Store location, and :class:`StoreLoader` streams
them back per rank — one shard in memory at a time, global batches with
the same Join-tail contract as the in-memory ``ShardedLoader``
(padded final batch + per-rank ``active`` mask).

Works over any Store: local FS, ``gs://``, ``memory://`` (tests).
"""

from __future__ import annotations

import io
import json
import os
from typing import Dict, Iterator, List, Tuple

import numpy as np

from .. import core
from ..training import shard_batch
from .store import Store

_MANIFEST = "_manifest.json"


def materialize_dataset(store: Store, run_id: str,
                        arrays: Dict[str, np.ndarray], *,
                        rows_per_shard: int = 65536,
                        path: str = None) -> dict:
    """Write ``arrays`` (equal first dims) into ``path`` (default:
    ``store.get_train_data_path(run_id)``) as npz shards + a manifest.
    Returns the manifest (reference util.py returns dataset metadata —
    row counts, schema — the same facts)."""
    names = list(arrays)
    if not names:
        raise ValueError("no arrays to materialize")
    n = int(np.asarray(arrays[names[0]]).shape[0])
    for k, a in arrays.items():
        if np.asarray(a).shape[0] != n:
            raise ValueError(
                f"array {k!r} first dim {np.asarray(a).shape[0]} != {n}"
            )
    base = path or store.get_train_data_path(run_id)
    shards = []
    for i, start in enumerate(range(0, n, rows_per_shard)):
        buf = io.BytesIO()
        np.savez(buf, **{
            k: np.asarray(a)[start: start + rows_per_shard]
            for k, a in arrays.items()
        })
        fname = f"shard_{i:05d}.npz"
        store.write(os.path.join(base, fname), buf.getvalue())
        shards.append({
            "file": fname,
            "rows": min(rows_per_shard, n - start),
        })
    manifest = {
        "version": 1,
        "n_rows": n,
        "columns": {
            k: {"shape": list(np.asarray(a).shape[1:]),
                "dtype": str(np.asarray(a).dtype)}
            for k, a in arrays.items()
        },
        "shards": shards,
    }
    store.write(os.path.join(base, _MANIFEST),
                json.dumps(manifest).encode())
    return manifest


def read_manifest(store: Store, run_id: str, *, path: str = None) -> dict:
    base = path or store.get_train_data_path(run_id)
    return json.loads(store.read(os.path.join(base, _MANIFEST)).decode())


def materialize_with_barrier(store: Store, run_id: str,
                             arrays: Dict[str, np.ndarray]) -> str:
    """Rank-0 materialization with run_id agreement + completion barrier
    (THE multi-process materialization protocol — flax Estimator and the
    torch/keras estimators all share it).  Every rank must end up with
    rank 0's run_id (clock-derived defaults can differ across ranks) and
    must not read before rank 0 finished writing.  Returns the agreed
    run_id."""
    if core.is_initialized() and core.process_size() > 1:
        from .. import eager

        run_id = eager.broadcast_object(run_id)
        if core.process_rank() == 0:
            materialize_dataset(store, run_id, arrays)
        eager.broadcast_object("materialized")  # barrier; hvd-lint: disable=HVD008
    else:
        materialize_dataset(store, run_id, arrays)
    return run_id


def read_rows(store: Store, run_id: str, columns: List[str],
              start: int, stop: int, *,
              path: str = None) -> List[np.ndarray]:
    """Read global rows ``[start, stop)`` of each column, streaming only
    the overlapping shards (a rank reading its own slice must not
    download the whole dataset — the reference's petastorm reader shards
    row groups by rank the same way)."""
    manifest = read_manifest(store, run_id, path=path)
    base = path or store.get_train_data_path(run_id)
    parts: Dict[str, List[np.ndarray]] = {c: [] for c in columns}
    off = 0
    for shard in manifest["shards"]:
        lo, hi = off, off + shard["rows"]
        off = hi
        if hi <= start or lo >= stop:
            continue
        with np.load(io.BytesIO(
                store.read(os.path.join(base, shard["file"])))) as z:
            s = max(start - lo, 0)
            e = min(stop, hi) - lo
            for c in columns:
                parts[c].append(z[c][s:e])
    return [
        np.concatenate(parts[c]) if parts[c]
        else np.zeros((0,) + tuple(manifest["columns"][c]["shape"]),
                      np.dtype(manifest["columns"][c]["dtype"]))
        for c in columns
    ]


class StoreLoader:
    """Iterate global batches from Store-resident shards.

    Yield contract matches ``ShardedLoader``: ``(*columns, active)`` with
    dim 0 of every column split across ranks, the final partial batch
    zero-padded, and ``active`` marking ranks holding real rows (the
    Join-tail contract, data/loader.py).  Shuffle is two-level — shard
    order plus in-shard rows, seeded identically on every controller —
    so only one shard is resident per process at a time (the reference's
    petastorm reader streams row groups the same way)."""

    def __init__(self, store: Store, run_id: str, *, batch_size: int,
                 columns: List[str] = None, shuffle: bool = False,
                 seed: int = 0, drop_remainder: bool = False):
        self.store = store
        self.run_id = run_id
        self.manifest = read_manifest(store, run_id)
        self.columns = columns or list(self.manifest["columns"])
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.n = self.manifest["n_rows"]

    def __len__(self) -> int:
        g = self.batch_size * core.size()
        return self.n // g if self.drop_remainder else -(-self.n // g)

    def _shard_arrays(self, fname: str) -> List[np.ndarray]:
        base = self.store.get_train_data_path(self.run_id)
        with np.load(io.BytesIO(
                self.store.read(os.path.join(base, fname)))) as z:
            return [z[c] for c in self.columns]

    def __iter__(self) -> Iterator[Tuple]:
        size = core.size()
        g = self.batch_size * size
        rng = np.random.default_rng(self.seed)
        if self.shuffle:
            self.seed += 1
        order = list(range(len(self.manifest["shards"])))
        if self.shuffle:
            rng.shuffle(order)

        pending: List[List[np.ndarray]] = []  # per-column row buffers
        buffered = 0

        def flush(cols_rows: List[np.ndarray], valid: int):
            from ..data.loader import pad_tail

            cols_rows, rows_per_rank = pad_tail(
                cols_rows, valid, self.batch_size, size,
            )
            shards = tuple(shard_batch(a) for a in cols_rows)
            active = shard_batch(rows_per_rank > 0)
            return (*shards, active)

        for si in order:
            cols = self._shard_arrays(self.manifest["shards"][si]["file"])
            if self.shuffle:
                perm = rng.permutation(cols[0].shape[0])
                cols = [a[perm] for a in cols]
            pending.append(cols)
            buffered += cols[0].shape[0]
            while buffered >= g:
                batch_cols, taken = self._take(pending, g)
                buffered -= taken
                yield flush(batch_cols, g)
        if buffered and not self.drop_remainder:
            batch_cols, taken = self._take(pending, buffered)
            yield flush(batch_cols, taken)

    @staticmethod
    def _take(pending: List[List[np.ndarray]], want: int):
        """Pop ``want`` rows across the buffered shards (in order)."""
        out_parts: List[List[np.ndarray]] = []
        got = 0
        while got < want and pending:
            cols = pending[0]
            avail = cols[0].shape[0]
            take = min(want - got, avail)
            out_parts.append([a[:take] for a in cols])
            if take == avail:
                pending.pop(0)
            else:
                pending[0] = [a[take:] for a in cols]
            got += take
        merged = [
            np.concatenate([p[i] for p in out_parts])
            for i in range(len(out_parts[0]))
        ]
        return merged, got
