from .store import Store, LocalStore, FsspecStore  # noqa: F401
from .estimator import Estimator, EstimatorModel  # noqa: F401


def __getattr__(name):
    # torch/keras estimators import their framework lazily (reference
    # gates spark.keras / spark.torch the same way)
    if name in ("TorchEstimator", "TorchEstimatorModel", "KerasEstimator"):
        from . import frameworks

        return getattr(frameworks, name)
    if name in ("prepare_data", "read_schema"):
        from . import dataframe

        return getattr(dataframe, name)
    raise AttributeError(name)
