from .store import Store, LocalStore, FsspecStore  # noqa: F401
from .estimator import Estimator, EstimatorModel  # noqa: F401
