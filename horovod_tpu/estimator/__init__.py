from .store import Store, LocalStore  # noqa: F401
from .estimator import Estimator, EstimatorModel  # noqa: F401
