"""Framework-flavored estimators: TorchEstimator and KerasEstimator.

Mirror of the reference's estimator pair (reference
horovod/spark/torch/estimator.py:85 TorchEstimator,
spark/keras/estimator.py:105 KerasEstimator: Spark ML Estimators whose
``fit`` trains through the framework binding with data/checkpoints in
the Store).  TPU-era shape: the process gang comes from the launcher
(tpurun / spark.run) instead of Spark ML plumbing, data is
Store-materialized the same way as the flax Estimator
(estimator/data.py), and training goes through the SAME binding paths a
hand-written script would use — torch's ``DistributedOptimizer`` +
``broadcast_parameters``, Keras's dynamic optimizer subclass +
broadcast callback — so the estimators exercise exactly the reference's
glue.

Per-process batching: each controller process trains on its own row
shard (the ``DistributedSampler`` idiom the reference applies via
petastorm shard-by-rank); gradient averaging crosses processes on the
host data plane.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, List, Optional

import numpy as np

from .. import core
from ..utils.logging import get_logger
from .store import Store

log = get_logger(__name__)


def _shard_range(n: int) -> tuple:
    """This process's row range with EQUAL length on every rank
    (``n // k`` rows each; the global tail is dropped, drop_remainder
    semantics).  Equal shard sizes keep per-batch gradient collectives
    count-matched across ranks — unequal shards would deadlock the
    DistributedOptimizer's allreduce."""
    k = core.process_size()
    per = n // k
    r = core.process_rank()
    return r * per, (r + 1) * per


def _load_process_shard(store, run_id, x, y):
    """The rows this process trains on: when a Store is configured the
    data is materialized (rank 0) and each rank streams back ONLY its
    slice (estimator/data.py read_rows); otherwise slice the in-memory
    arrays."""
    x = np.asarray(x)
    y = np.asarray(y)
    if store is not None:
        from .data import materialize_with_barrier, read_manifest, read_rows

        run_id = materialize_with_barrier(store, run_id,
                                          {"x": x, "y": y})
        # row count from the MANIFEST, not the local array: only rank
        # 0's arrays were materialized, and a rank passing a
        # different-length x would otherwise slice a wrong/unequal
        # range and count-mismatch the gradient collectives
        n = read_manifest(store, run_id)["n_rows"]
        start, stop = _shard_range(n)
        xs, ys = read_rows(store, run_id, ["x", "y"], start, stop)
        return xs, ys, run_id
    start, stop = _shard_range(x.shape[0])
    return x[start:stop], y[start:stop], run_id


class TorchEstimatorModel:
    """Fitted artifact: torch module + predict + Store round-trip
    (reference spark/torch/estimator.py TorchModel counterpart)."""

    def __init__(self, model):
        self.model = model
        self.history: List[dict] = []

    def predict(self, x) -> np.ndarray:
        import torch

        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.as_tensor(np.asarray(x)))
        return out.numpy()

    def save(self, store: Store, run_id: str,
             name: str = "torch_model.ckpt") -> str:
        path = os.path.join(store.get_checkpoint_path(run_id), name)
        store.save_obj(path, self.model.state_dict())
        return path

    def load_state(self, store: Store, run_id: str,
                   name: str = "torch_model.ckpt") -> None:
        path = os.path.join(store.get_checkpoint_path(run_id), name)
        self.model.load_state_dict(store.load_obj(path))


class TorchEstimator:
    """fit(x, y) → TorchEstimatorModel via the torch binding (reference
    TorchEstimator params kept where they transfer: model, optimizer,
    loss, store, batch_size, epochs, run_id, backward_passes_per_step)."""

    def __init__(self, *, model, optimizer_factory: Callable,
                 loss: Callable, store: Optional[Store] = None,
                 batch_size: int = 32, epochs: int = 1,
                 run_id: Optional[str] = None,
                 backward_passes_per_step: int = 1,
                 op: Optional[str] = None,
                 shuffle: bool = True, verbose: int = 1):
        self.model = model
        self.optimizer_factory = optimizer_factory
        self.loss = loss
        self.store = store
        self.batch_size = batch_size
        self.epochs = epochs
        self.run_id = run_id or f"torch_run_{int(time.time())}"
        self.backward_passes_per_step = backward_passes_per_step
        self.op = op
        self.shuffle = shuffle
        self.verbose = verbose

    def fit(self, x, y) -> TorchEstimatorModel:
        import torch

        import horovod_tpu.torch as hvd_torch

        if not core.is_initialized():
            core.init()
        xs, ys, self.run_id = _load_process_shard(
            self.store, self.run_id, x, y,
        )

        opt = self.optimizer_factory(self.model.parameters())
        kwargs = {} if self.op is None else {"op": self.op}
        opt = hvd_torch.DistributedOptimizer(
            opt, named_parameters=self.model.named_parameters(),
            backward_passes_per_step=self.backward_passes_per_step,
            **kwargs,
        )
        hvd_torch.broadcast_parameters(self.model.state_dict(), root_rank=0)

        fitted = TorchEstimatorModel(self.model)
        rng = np.random.default_rng(0)
        n = xs.shape[0]
        for epoch in range(self.epochs):
            order = np.arange(n)
            if self.shuffle:
                rng.shuffle(order)  # same seed: balanced, deterministic
            losses = []
            self.model.train()
            for start in range(0, n - self.batch_size + 1,
                               self.batch_size):
                take = order[start: start + self.batch_size]
                opt.zero_grad()
                loss = self.loss(
                    self.model(torch.as_tensor(xs[take])),
                    torch.as_tensor(ys[take]),
                )
                loss.backward()
                opt.step()
                losses.append(float(loss))
            metrics = {"loss": float(np.mean(losses)) if losses
                       else float("nan")}
            fitted.history.append(metrics)
            if self.verbose and core.process_rank() == 0:
                log.info("epoch %d: %s", epoch, metrics)

        if self.store is not None and core.process_rank() == 0:
            fitted.save(self.store, self.run_id)
        return fitted


class KerasEstimator:
    """fit(x, y) → trained tf.keras model via the TF binding (reference
    KerasEstimator counterpart): DistributedOptimizer subclass +
    broadcast callback + rank-0 Store checkpoint."""

    def __init__(self, *, model, optimizer, loss,
                 store: Optional[Store] = None, batch_size: int = 32,
                 epochs: int = 1, run_id: Optional[str] = None,
                 metrics: Optional[list] = None, verbose: int = 0):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.store = store
        self.batch_size = batch_size
        self.epochs = epochs
        self.run_id = run_id or f"keras_run_{int(time.time())}"
        self.metrics = metrics or []
        self.verbose = verbose

    def fit(self, x, y):
        import horovod_tpu.tensorflow as hvd_tf
        from horovod_tpu.tensorflow.keras import callbacks as hvd_cb

        if not core.is_initialized():
            core.init()
        xs, ys, self.run_id = _load_process_shard(
            self.store, self.run_id, x, y,
        )

        opt = hvd_tf.DistributedOptimizer(self.optimizer)
        self.model.compile(optimizer=opt, loss=self.loss,
                           metrics=self.metrics)
        history = self.model.fit(
            xs, ys, batch_size=self.batch_size, epochs=self.epochs,
            verbose=self.verbose,
            callbacks=[hvd_cb.BroadcastGlobalVariablesCallback(0)],
        )
        if self.store is not None and core.process_rank() == 0:
            path = os.path.join(
                self.store.get_checkpoint_path(self.run_id),
                "keras_weights.ckpt",
            )
            self.store.save_obj(path, self.model.get_weights())
        self.model.history_ = history.history
        return self.model
