"""Framework-flavored estimators: TorchEstimator and KerasEstimator.

Mirror of the reference's estimator pair (reference
horovod/spark/torch/estimator.py:85 TorchEstimator,
spark/keras/estimator.py:105 KerasEstimator: Spark ML Estimators whose
``fit`` trains through the framework binding with data/checkpoints in
the Store).  TPU-era shape: the process gang comes from the launcher
(tpurun / spark.run) instead of Spark ML plumbing, data is
Store-materialized the same way as the flax Estimator
(estimator/data.py), and training goes through the SAME binding paths a
hand-written script would use — torch's ``DistributedOptimizer`` +
``broadcast_parameters``, Keras's dynamic optimizer subclass +
broadcast callback — so the estimators exercise exactly the reference's
glue.

Per-process batching: each controller process trains on its own row
shard (the ``DistributedSampler`` idiom the reference applies via
petastorm shard-by-rank); gradient averaging crosses processes on the
host data plane.
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

import numpy as np

from .. import core
from ..utils.logging import get_logger
from .store import Store

log = get_logger(__name__)


def _shard_range(n: int) -> tuple:
    """This process's row range with EQUAL length on every rank
    (``n // k`` rows each; the global tail is dropped, drop_remainder
    semantics).  Equal shard sizes keep per-batch gradient collectives
    count-matched across ranks — unequal shards would deadlock the
    DistributedOptimizer's allreduce."""
    k = core.process_size()
    per = n // k
    r = core.process_rank()
    return r * per, (r + 1) * per


def _is_dataframe(obj) -> bool:
    """Duck-typed DataFrame detection: pyspark DataFrames (and the test
    stub) expose .columns + .collect(); arrays do not."""
    return hasattr(obj, "collect") and hasattr(obj, "columns")


def _prepare_df_with_barrier(store, run_id, df, label_cols, feature_cols,
                             validation):
    """Rank-0 DataFrame ingestion with run_id agreement + completion
    barrier (the multi-process shape of reference prepare_data, which
    runs once on the Spark driver before the gang trains from the
    Store).  Schema-validation errors raised on rank 0 are re-raised on
    EVERY rank — the alternative is n-1 ranks hanging on a barrier for a
    dataset that will never exist.  Returns (run_id, n_val_rows)."""
    from .dataframe import prepare_data

    if core.is_initialized() and core.process_size() > 1:
        from .. import eager

        run_id = eager.broadcast_object(run_id)
        outcome = ("ok", 0)
        if core.process_rank() == 0:
            try:
                manifest = prepare_data(
                    store, df, label_cols, feature_cols,
                    run_id=run_id, validation=validation,
                )
                outcome = ("ok", int(manifest.get("n_val_rows", 0)))
            except Exception as e:  # noqa: BLE001 — re-raised everywhere
                outcome = ("err", f"{type(e).__name__}: {e}")
        outcome = eager.broadcast_object(outcome)  # doubles as barrier
        if outcome[0] == "err":
            raise ValueError(
                f"DataFrame ingestion failed on rank 0: {outcome[1]}"
            )
        return run_id, outcome[1]
    manifest = prepare_data(
        store, df, label_cols, feature_cols,
        run_id=run_id, validation=validation,
    )
    return run_id, int(manifest.get("n_val_rows", 0))


def _load_df_shards(store, run_id, n_val):
    """This process's train rows plus the (small, replicated) validation
    set from a prepared-DataFrame dataset."""
    from .data import read_manifest, read_rows

    n = read_manifest(store, run_id)["n_rows"]
    start, stop = _shard_range(n)
    xs, ys = read_rows(store, run_id, ["x", "y"], start, stop)
    val = None
    if n_val:
        vx, vy = read_rows(
            store, run_id, ["x", "y"], 0, n_val,
            path=store.get_val_data_path(run_id),
        )
        val = (vx, vy)
    return xs, ys, val


def _resolve_fit_inputs(est, x, y):
    """Shared ``fit`` dispatch for both estimators: a single DataFrame
    argument goes through Store ingestion (+ per-rank shard load), array
    pairs through the in-memory/Store shard path.  Mutates ``est.run_id``
    to the agreed id.  Returns ``(xs, ys, val)``."""
    if y is None and _is_dataframe(x):
        if est.store is None:
            raise ValueError(
                "fit(df) requires a store: the DataFrame is materialized "
                "through it (reference estimators carry the same "
                "requirement)"
            )
        est.run_id, n_val = _prepare_df_with_barrier(
            est.store, est.run_id, x, est.label_cols, est.feature_cols,
            est.validation,
        )
        return _load_df_shards(est.store, est.run_id, n_val)
    if y is None:
        raise TypeError(
            "fit() needs y for array inputs; a single argument must "
            "be a DataFrame (.columns/.collect())"
        )
    xs, ys, est.run_id = _load_process_shard(est.store, est.run_id, x, y)
    return xs, ys, None


def _load_process_shard(store, run_id, x, y):
    """The rows this process trains on: when a Store is configured the
    data is materialized (rank 0) and each rank streams back ONLY its
    slice (estimator/data.py read_rows); otherwise slice the in-memory
    arrays."""
    x = np.asarray(x)
    y = np.asarray(y)
    if store is not None:
        from .data import materialize_with_barrier, read_manifest, read_rows

        run_id = materialize_with_barrier(store, run_id,
                                          {"x": x, "y": y})
        # row count from the MANIFEST, not the local array: only rank
        # 0's arrays were materialized, and a rank passing a
        # different-length x would otherwise slice a wrong/unequal
        # range and count-mismatch the gradient collectives
        n = read_manifest(store, run_id)["n_rows"]
        start, stop = _shard_range(n)
        xs, ys = read_rows(store, run_id, ["x", "y"], start, stop)
        return xs, ys, run_id
    start, stop = _shard_range(x.shape[0])
    return x[start:stop], y[start:stop], run_id


class TorchEstimatorModel:
    """Fitted artifact: torch module + predict + Store round-trip
    (reference spark/torch/estimator.py TorchModel counterpart)."""

    def __init__(self, model):
        self.model = model
        self.history: List[dict] = []

    def predict(self, x) -> np.ndarray:
        import torch

        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.as_tensor(np.asarray(x)))
        return out.numpy()

    def save(self, store: Store, run_id: str,
             name: str = "torch_model.ckpt") -> str:
        path = os.path.join(store.get_checkpoint_path(run_id), name)
        store.save_obj(path, self.model.state_dict())
        return path

    def load_state(self, store: Store, run_id: str,
                   name: str = "torch_model.ckpt") -> None:
        path = os.path.join(store.get_checkpoint_path(run_id), name)
        self.model.load_state_dict(store.load_obj(path))


class TorchEstimator:
    """fit(x, y) → TorchEstimatorModel via the torch binding (reference
    TorchEstimator params kept where they transfer: model, optimizer,
    loss, store, batch_size, epochs, run_id, backward_passes_per_step)."""

    def __init__(self, *, model, optimizer_factory: Callable,
                 loss: Callable, store: Optional[Store] = None,
                 batch_size: int = 32, epochs: int = 1,
                 run_id: Optional[str] = None,
                 backward_passes_per_step: int = 1,
                 op: Optional[str] = None,
                 feature_cols: Optional[List[str]] = None,
                 label_cols: Optional[List[str]] = None,
                 validation=None,
                 shuffle: bool = True, verbose: int = 1):
        self.model = model
        self.optimizer_factory = optimizer_factory
        self.loss = loss
        self.store = store
        self.batch_size = batch_size
        self.epochs = epochs
        self.run_id = run_id or f"torch_run_{int(time.time())}"
        self.backward_passes_per_step = backward_passes_per_step
        self.op = op
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.validation = validation
        self.shuffle = shuffle
        self.verbose = verbose

    def fit(self, x, y=None) -> TorchEstimatorModel:
        """``fit(x, y)`` on arrays, or ``fit(df)`` on a (py)Spark-style
        DataFrame (reference spark/torch/estimator.py TorchEstimator.fit:
        the DataFrame is validated + materialized through the Store,
        then every rank trains from its shard)."""
        if not core.is_initialized():
            core.init()
        xs, ys, val = _resolve_fit_inputs(self, x, y)
        return self._train_arrays(xs, ys, val=val)

    def _train_arrays(self, xs, ys, val=None) -> TorchEstimatorModel:
        import torch

        import horovod_tpu.torch as hvd_torch

        opt = self.optimizer_factory(self.model.parameters())
        kwargs = {} if self.op is None else {"op": self.op}
        opt = hvd_torch.DistributedOptimizer(
            opt, named_parameters=self.model.named_parameters(),
            backward_passes_per_step=self.backward_passes_per_step,
            **kwargs,
        )
        hvd_torch.broadcast_parameters(self.model.state_dict(), root_rank=0)

        fitted = TorchEstimatorModel(self.model)
        rng = np.random.default_rng(0)
        n = xs.shape[0]
        for epoch in range(self.epochs):
            order = np.arange(n)
            if self.shuffle:
                rng.shuffle(order)  # same seed: balanced, deterministic
            losses = []
            self.model.train()
            for start in range(0, n - self.batch_size + 1,
                               self.batch_size):
                take = order[start: start + self.batch_size]
                opt.zero_grad()
                loss = self.loss(
                    self.model(torch.as_tensor(xs[take])),
                    torch.as_tensor(ys[take]),
                )
                loss.backward()
                opt.step()
                losses.append(float(loss.detach()))
            metrics = {"loss": float(np.mean(losses)) if losses
                       else float("nan")}
            if val is not None:
                self.model.eval()
                with torch.no_grad():
                    vloss = self.loss(
                        self.model(torch.as_tensor(val[0])),
                        torch.as_tensor(val[1]),
                    )
                metrics["val_loss"] = float(vloss)
            fitted.history.append(metrics)
            if self.verbose and core.process_rank() == 0:
                log.info("epoch %d: %s", epoch, metrics)

        if self.store is not None and core.process_rank() == 0:
            fitted.save(self.store, self.run_id)
        return fitted


class KerasEstimator:
    """fit(x, y) → trained tf.keras model via the TF binding (reference
    KerasEstimator counterpart): DistributedOptimizer subclass +
    broadcast callback + rank-0 Store checkpoint."""

    def __init__(self, *, model, optimizer, loss,
                 store: Optional[Store] = None, batch_size: int = 32,
                 epochs: int = 1, run_id: Optional[str] = None,
                 metrics: Optional[list] = None,
                 feature_cols: Optional[List[str]] = None,
                 label_cols: Optional[List[str]] = None,
                 validation=None, verbose: int = 0):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.store = store
        self.batch_size = batch_size
        self.epochs = epochs
        self.run_id = run_id or f"keras_run_{int(time.time())}"
        self.metrics = metrics or []
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.validation = validation
        self.verbose = verbose

    def fit(self, x, y=None):
        """``fit(x, y)`` on arrays, or ``fit(df)`` on a (py)Spark-style
        DataFrame (reference spark/keras/estimator.py KerasEstimator:
        prepare_data through the Store, then the gang trains from it)."""
        import horovod_tpu.tensorflow as hvd_tf
        from horovod_tpu.tensorflow.keras import callbacks as hvd_cb

        if not core.is_initialized():
            core.init()
        xs, ys, val = _resolve_fit_inputs(self, x, y)

        opt = hvd_tf.DistributedOptimizer(self.optimizer)
        self.model.compile(optimizer=opt, loss=self.loss,
                           metrics=self.metrics)
        history = self.model.fit(
            xs, ys, batch_size=self.batch_size, epochs=self.epochs,
            verbose=self.verbose,
            validation_data=val,
            callbacks=[hvd_cb.BroadcastGlobalVariablesCallback(0)],
        )
        if self.store is not None and core.process_rank() == 0:
            path = os.path.join(
                self.store.get_checkpoint_path(self.run_id),
                "keras_weights.ckpt",
            )
            self.store.save_obj(path, self.model.get_weights())
        self.model.history_ = history.history
        return self.model
