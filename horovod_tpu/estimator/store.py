"""Store: persistent storage abstraction for estimator data/checkpoints.

Mirror of horovod/spark/common/store.py (reference): a ``Store`` exposes
train-data, checkpoint, and run-output locations plus filesystem helpers;
``LocalStore`` is the local-FS implementation (reference LocalStore; the
HDFS variant maps to GCS/fuse mounts on TPU VMs — same interface, prefix
swap)."""

from __future__ import annotations

import json
import os
import pickle
import shutil
from typing import Any, Optional


class Store:
    """Interface (reference spark/common/store.py Store)."""

    def get_train_data_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Factory (reference Store.create dispatches on URL scheme)."""
        return LocalStore(prefix_path)


class LocalStore(Store):
    def __init__(self, prefix_path: str):
        self.prefix = prefix_path
        os.makedirs(prefix_path, exist_ok=True)

    def _sub(self, run_id: str, name: str) -> str:
        p = os.path.join(self.prefix, run_id, name)
        os.makedirs(p, exist_ok=True)
        return p

    def get_train_data_path(self, run_id: str) -> str:
        return self._sub(run_id, "train_data")

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._sub(run_id, "checkpoints")

    def get_logs_path(self, run_id: str) -> str:
        return self._sub(run_id, "logs")

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def save_obj(self, path: str, obj: Any) -> None:
        self.write(path, pickle.dumps(obj))

    def load_obj(self, path: str) -> Any:
        return pickle.loads(self.read(path))
