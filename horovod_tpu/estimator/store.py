"""Store: persistent storage abstraction for estimator data/checkpoints.

Mirror of horovod/spark/common/store.py (reference): a ``Store`` exposes
train-data, checkpoint, and run-output locations plus filesystem helpers;
``LocalStore`` is the local-FS implementation (reference LocalStore; the
HDFS variant maps to GCS/fuse mounts on TPU VMs — same interface, prefix
swap)."""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional


class Store:
    """Interface (reference spark/common/store.py Store)."""

    def get_train_data_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_val_data_path(self, run_id: str) -> str:
        """Validation split location (reference spark/common/store.py
        get_val_data_path)."""
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def save_obj(self, path: str, obj: Any) -> None:
        self.write(path, pickle.dumps(obj))

    def load_obj(self, path: str) -> Any:
        return pickle.loads(self.read(path))

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Factory dispatching on URL scheme (reference Store.create,
        spark/common/store.py: local paths -> LocalStore, remote URLs ->
        HDFSStore; here remote URLs -> FsspecStore, with gs:// as the
        TPU-era primary remote instead of hdfs://)."""
        scheme = prefix_path.split("://", 1)[0] if "://" in prefix_path else ""
        if scheme in ("", "file"):
            return LocalStore(prefix_path.removeprefix("file://"))
        return FsspecStore(prefix_path)


class LocalStore(Store):
    def __init__(self, prefix_path: str):
        self.prefix = prefix_path
        os.makedirs(prefix_path, exist_ok=True)

    def _sub(self, run_id: str, name: str) -> str:
        p = os.path.join(self.prefix, run_id, name)
        os.makedirs(p, exist_ok=True)
        return p

    def get_train_data_path(self, run_id: str) -> str:
        return self._sub(run_id, "train_data")

    def get_val_data_path(self, run_id: str) -> str:
        return self._sub(run_id, "val_data")

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._sub(run_id, "checkpoints")

    def get_logs_path(self, run_id: str) -> str:
        return self._sub(run_id, "logs")

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)


class FsspecStore(Store):
    """Remote store over any fsspec filesystem: ``gs://``, ``s3://``,
    ``hdfs://``, ``memory://``, ... (reference HDFSStore,
    spark/common/store.py — pyarrow hdfs client there, fsspec here; GCS is
    the natural remote for TPU VMs).

    Paths handed out and accepted are full URLs; directories are created
    lazily on write (object stores have no real directories)."""

    def __init__(self, prefix_url: str):
        import fsspec

        self.prefix = prefix_url.rstrip("/")
        self.fs, _ = fsspec.core.url_to_fs(self.prefix)

    def _url(self, path: str) -> str:
        """fs-native path for a full URL (handles schemes with a netloc,
        e.g. hdfs://namenode:8020/data, which a bare scheme-strip would
        mangle)."""
        return self.fs._strip_protocol(path)

    def _sub(self, run_id: str, name: str) -> str:
        return f"{self.prefix}/{run_id}/{name}"

    def get_train_data_path(self, run_id: str) -> str:
        return self._sub(run_id, "train_data")

    def get_val_data_path(self, run_id: str) -> str:
        return self._sub(run_id, "val_data")

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._sub(run_id, "checkpoints")

    def get_logs_path(self, run_id: str) -> str:
        return self._sub(run_id, "logs")

    def exists(self, path: str) -> bool:
        return self.fs.exists(self._url(path))

    def read(self, path: str) -> bytes:
        with self.fs.open(self._url(path), "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        p = self._url(path)
        parent = p.rsplit("/", 1)[0]
        try:
            self.fs.makedirs(parent, exist_ok=True)
        except Exception:  # object stores may not support mkdir
            pass
        with self.fs.open(p, "wb") as f:
            f.write(data)
