"""High-level Estimator: fit a flax model over the mesh, checkpoint to a
Store, return a servable model.

Re-design of the Spark estimator slice (reference
horovod/spark/common/estimator.py + spark/keras/estimator.py /
spark/torch/estimator.py: a Spark ML ``Estimator`` whose ``fit(df)`` runs
``horovod.spark.run`` training with data and checkpoints in the ``Store``,
returning a Spark ML ``Model``).  TPU translation: the cluster scheduler
role Spark played is the ``tpurun`` launcher; data arrives as arrays (or a
ShardedLoader), checkpoints round-trip through the Store (local FS or GCS
prefix), and the returned :class:`EstimatorModel` serves predictions with
the trained params — same shape: estimator.fit(data) → model.predict.

Checkpoint format: msgpack-free pickle of the param pytree (orbax is
available for production use; pickle keeps the Store interface trivially
portable).  Rank-0-writes semantics (reference: checkpoint callbacks gated
on rank 0, examples/keras_mnist.py) apply in multi-controller runs.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import core
from ..core import Average
from ..ops.compression import Compression
from ..training import init_train_state, make_train_step
from ..data.loader import ShardedLoader
from ..utils.logging import get_logger
from .store import Store

log = get_logger(__name__)


class EstimatorModel:
    """The fitted artifact (reference spark/common/estimator.py Model
    counterpart): holds params + apply_fn, serves predict(), reloadable
    from a Store checkpoint."""

    def __init__(self, model, params, model_state=None):
        self.model = model
        # normalize to host arrays: predictions then run on the default
        # backend regardless of which mesh trained the params
        self.params = jax.device_get(params)
        self.model_state = jax.device_get(model_state or {})

    def predict(self, x) -> np.ndarray:
        variables = {"params": self.params, **self.model_state}
        kw = {}
        if self.model_state:
            kw["train"] = False
        out = self.model.apply(variables, jnp.asarray(x), **kw)
        return np.asarray(jax.device_get(out))

    def save(self, store: Store, run_id: str, name: str = "model.ckpt"):
        path = os.path.join(store.get_checkpoint_path(run_id), name)
        store.save_obj(path, {
            "params": jax.device_get(self.params),
            "model_state": jax.device_get(self.model_state),
        })
        return path

    @classmethod
    def load(cls, store: Store, run_id: str, model,
             name: str = "model.ckpt") -> "EstimatorModel":
        path = os.path.join(store.get_checkpoint_path(run_id), name)
        blob = store.load_obj(path)
        return cls(model, blob["params"], blob["model_state"])


class Estimator:
    """fit(x, y) → EstimatorModel (reference KerasEstimator/TorchEstimator
    parameter names kept where they transfer: store, model, optimizer,
    loss, batch_size, epochs, callbacks, run_id)."""

    def __init__(
        self,
        *,
        model,
        optimizer,
        loss: Callable,
        store: Optional[Store] = None,
        batch_size: int = 32,
        epochs: int = 1,
        callbacks: Optional[list] = None,
        run_id: Optional[str] = None,
        compression=Compression.none,
        op: str = Average,
        has_batch_stats: bool = False,
        sample_input_shape: Optional[tuple] = None,
        shuffle: bool = True,
        verbose: int = 1,
    ):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.store = store
        self.batch_size = batch_size
        self.epochs = epochs
        self.callbacks = callbacks or []
        self.run_id = run_id or f"run_{int(time.time())}"
        self.compression = compression
        self.op = op
        self.has_batch_stats = has_batch_stats
        self.sample_input_shape = sample_input_shape
        self.shuffle = shuffle
        self.verbose = verbose

    def fit(self, x: np.ndarray, y: np.ndarray) -> EstimatorModel:
        """Train on arrays.  With a Store configured this is the
        reference's two-phase shape (reference spark/common/util.py
        prepare_data → spark/keras/remote.py trains from the store):
        the data is materialized into ``store.get_train_data_path`` and
        training reads it back shard-streamed, NOT from the arrays."""
        if not core.is_initialized():
            core.init()
        if self.store is not None:
            from .data import materialize_with_barrier

            self.run_id = materialize_with_barrier(
                self.store, self.run_id,
                {"x": np.asarray(x), "y": np.asarray(y)},
            )
            return self.fit_on_store(
                sample_shape=(2,) + tuple(np.asarray(x).shape[1:]),
                dtype=np.asarray(x).dtype,
            )
        return self._fit_loader(
            ShardedLoader(
                x, y, batch_size=self.batch_size, shuffle=self.shuffle,
                drop_remainder=True,
            ),
            sample_shape=self.sample_input_shape
            or (2,) + tuple(np.asarray(x).shape[1:]),
            dtype=np.asarray(x).dtype,
        )

    def fit_on_store(self, run_id: Optional[str] = None, *,
                     sample_shape: Optional[tuple] = None,
                     dtype=np.float32) -> EstimatorModel:
        """Train from data already materialized in the Store (columns
        'x'/'y'), streaming one shard at a time with Join tails."""
        from .data import StoreLoader, read_manifest

        if self.store is None:
            raise ValueError("fit_on_store requires a store")
        rid = run_id or self.run_id
        if sample_shape is None:
            meta = read_manifest(self.store, rid)
            sample_shape = (2,) + tuple(meta["columns"]["x"]["shape"])
            dtype = np.dtype(meta["columns"]["x"]["dtype"])
        loader = StoreLoader(
            self.store, rid, batch_size=self.batch_size,
            columns=["x", "y"], shuffle=self.shuffle,
            drop_remainder=True,  # epoch loop trains full batches only
        )
        return self._fit_loader(loader, sample_shape=sample_shape,
                                dtype=dtype)

    def _fit_loader(self, loader, *, sample_shape, dtype) -> EstimatorModel:
        if not core.is_initialized():
            core.init()

        step = make_train_step(
            apply_fn=self.model.apply,
            loss_fn=self.loss,
            optimizer=self.optimizer,
            op=self.op,
            compression=self.compression,
            has_batch_stats=self.has_batch_stats,
        )
        state = init_train_state(
            self.model, self.optimizer,
            jnp.zeros(self.sample_input_shape or sample_shape, dtype),
            has_batch_stats=self.has_batch_stats,
        )
        for cb in self.callbacks:
            state = cb.on_train_begin(state) or state

        history = []
        for epoch in range(self.epochs):
            losses = []
            for batch in loader:
                xb, yb, _active = batch
                state, loss = step(state, xb, yb)
                losses.append(loss)
            metrics = {
                "loss": float(np.mean([
                    np.asarray(jax.device_get(l)) for l in losses
                ]))
            }
            for cb in self.callbacks:
                metrics = cb.on_epoch_end(epoch, state, metrics) or metrics
            history.append(metrics)
            if self.verbose and core.rank() == 0:
                log.info("epoch %d: %s", epoch, metrics)

        fitted = EstimatorModel(
            self.model, state.params, state.model_state
        )
        fitted.history = history
        if self.store is not None and core.rank() == 0:
            fitted.save(self.store, self.run_id)
        return fitted
