"""horovod_tpu.spark.torch — import-path parity with the reference's
``horovod.spark.torch`` (reference horovod/spark/torch/__init__.py:
exposes TorchEstimator/TorchModel).  The implementation lives in
horovod_tpu/estimator/frameworks.py; this module is the reference-shaped
entry point."""

from ..estimator.frameworks import (  # noqa: F401
    TorchEstimator, TorchEstimatorModel,
)

# reference naming: horovod.spark.torch.TorchModel is the fitted artifact
TorchModel = TorchEstimatorModel
