"""horovod_tpu.spark.keras — import-path parity with the reference's
``horovod.spark.keras`` (reference horovod/spark/keras/__init__.py:
exposes KerasEstimator/KerasModel).  The implementation lives in
horovod_tpu/estimator/frameworks.py; this module is the reference-shaped
entry point."""

from ..estimator.frameworks import KerasEstimator  # noqa: F401
