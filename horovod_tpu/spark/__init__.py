"""horovod_tpu.spark: run a horovod_tpu function on Spark executors.

Mirror of ``horovod.spark.run`` (reference horovod/spark/__init__.py:104):
the reference launches ``num_proc`` Spark tasks that register with a
driver service, probe NICs ring-wise, and bootstrap mpirun through a
custom ``orted`` shell (spark/driver/mpirun_rsh.py).  TPU-era re-design:
there is no mpirun and no NIC probing — the driver hosts the native
controller server (the same transport ``tpurun`` uses,
run/run.py), Spark **barrier mode** gang-schedules one task per process,
and each task dials back with its ``HVD_PROCESS_ID``.

The Estimator layer (reference spark/keras/estimator.py,
spark/torch/estimator.py) lives in :mod:`horovod_tpu.estimator` with the
``Store`` abstraction (LocalStore / FsspecStore for gs://).

Import is gated: requires pyspark (not part of this image; exercised
where Spark exists, tests skip otherwise).
"""

from __future__ import annotations

import os
import socket
from typing import Callable, Optional

import pyspark  # gate: module import fails cleanly without Spark

from ..estimator import Estimator, EstimatorModel, Store  # noqa: F401


def __getattr__(name):
    # reference-shaped access: horovod.spark.torch.TorchEstimator /
    # horovod.spark.keras.KerasEstimator / spark.common.util.prepare_data
    # map to the estimator package's lazy exports
    if name in ("TorchEstimator", "TorchEstimatorModel", "KerasEstimator",
                "prepare_data", "read_schema"):
        from .. import estimator

        return getattr(estimator, name)
    raise AttributeError(name)


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None, extra_env: Optional[dict] = None,
        verbose: int = 1):
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark executors as one
    horovod_tpu job; returns the list of per-process results in rank
    order (reference horovod.spark.run contract)."""
    kwargs = kwargs or {}
    sc = pyspark.SparkContext.getOrCreate()
    n = int(num_proc or sc.defaultParallelism)

    # the driver hosts the controller server, as tpurun's launcher does
    from ..runtime import native
    from ..runtime.controller import ControllerServer

    server = None
    addr = None
    if n > 1:
        if not native.available():
            # Without the native transport a >1-process gang would init
            # with no way to communicate — its host collectives would
            # hang or return single-process answers.  Fail fast instead.
            raise RuntimeError(
                "horovod_tpu.spark.run(num_proc=%d) needs the native "
                "controller extension (csrc build failed or was "
                "disabled); rebuild it or pass num_proc=1" % n
            )
        server = ControllerServer(n, port=0)
        host = socket.getfqdn()
        addr = f"{host}:{server.port}"

    base_env = dict(extra_env or {})

    def task(it):
        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        pid = ctx.partitionId()
        os.environ.update(base_env)
        os.environ["HVD_PROCESS_ID"] = str(pid)
        os.environ["HVD_NUM_PROCESSES"] = str(n)
        if addr:
            os.environ["HVD_CONTROLLER"] = "native"
            os.environ["HVD_CONTROLLER_ADDR"] = addr
            os.environ["HVD_CONTROLLER_SERVER"] = "external"
        ctx.barrier()  # gang start, as the reference's driver-service wait
        yield pid, fn(*args, **kwargs)

    try:
        pairs = (
            sc.parallelize(range(n), n).barrier().mapPartitions(task)
            .collect()
        )
    finally:
        if server is not None:
            server.stop()
    return [r for _, r in sorted(pairs)]
