"""Rank-sharded data loading with uneven-tail (Join) handling.

Analog of the fork's data loader shim (reference horovod/mxnet/dataloader.py
splits batches across ranks) plus the standard Horovod idiom of
``DistributedSampler``-style per-rank sharding; the uneven tail integrates
with Join semantics (elastic/join.py): the last partial global batch is
padded and accompanied by a per-rank ``active`` mask so
``join_allreduce`` divides by the true participant count — the compiled
analog of "rank r joined early" (reference controller.cc:253-264).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np


from .. import core
from ..training import shard_batch
from ..utils import env as env_util

_SENTINEL = object()


def prefetch_to_device(iterator: Iterable, depth: Optional[int] = None
                       ) -> Iterator:
    """Run ``iterator`` ``depth`` items ahead on a background thread so
    the device never waits on host-side batch assembly.

    The producer thread does the host work (index/pad/copy) AND the
    ``device_put`` dispatch — JAX transfers are async, so by the time
    the training loop pops a batch its H2D copy has been in flight for
    a full step (the double-buffering the compute-anatomy profiler's
    host-gap metric flags when it is missing, docs/profiling.md).
    ``depth`` defaults to ``HVD_PREFETCH_DEPTH`` (2); 0 degrades to the
    plain synchronous iterator.  Item order is preserved (single
    producer, FIFO queue) and a producer exception re-raises at the
    consumer's next pull instead of killing a daemon thread silently.
    """
    if depth is None:
        depth = env_util.get_int(env_util.HVD_PREFETCH_DEPTH,
                                 env_util.DEFAULT_PREFETCH_DEPTH)
    if depth <= 0:
        yield from iterator
        return
    q: queue.Queue = queue.Queue(maxsize=int(depth))
    err: List[BaseException] = []
    stop = threading.Event()

    def _put(item) -> bool:
        """Bounded put that gives up once the consumer is gone — a
        producer blocked forever on a full queue would leak the thread
        AND pin its staged device-resident batches."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce():
        try:
            for item in iterator:
                if not _put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at the consumer
            err.append(e)
        finally:
            _put(_SENTINEL)

    t = threading.Thread(target=_produce, name="hvd-prefetch", daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        # consumer exited (break / exception / generator close): release
        # the producer and drop any staged batches so nothing stays
        # pinned on device
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


def pad_tail(cols: List[np.ndarray], valid: int, batch_size: int,
             size: int) -> Tuple[List[np.ndarray], np.ndarray]:
    """THE Join-tail layout (single definition — ShardedLoader and the
    estimator's StoreLoader share it): zero-pad a partial global batch to
    ``batch_size * size`` rows, packing valid rows onto the lowest ranks,
    and return ``(cols, rows_per_rank)`` where ``rows_per_rank > 0`` is
    the active mask."""
    g = batch_size * size
    rows_per_rank = np.full((size,), batch_size, np.int32)
    if valid < g:
        full, rem = divmod(valid, batch_size)
        rows_per_rank = np.array(
            [batch_size] * full + ([rem] if rem else [])
            + [0] * (size - full - (1 if rem else 0)), np.int32,
        )
        pad = g - valid
        cols = [
            np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
            for a in cols
        ]
    return cols, rows_per_rank


class ShardedLoader:
    """Iterate (sharded_batch..., active_mask) over a host dataset.

    Each yield is a *global* batch of ``batch_size * size()`` rows placed
    so dim 0 is split across ranks.  When the data doesn't divide evenly,
    the final batch is zero-padded and ``active`` marks which ranks hold
    at least one real row (per-row validity is in ``valid_counts``).

    ``prefetch`` (default ``HVD_PREFETCH_DEPTH``, 2) keeps that many
    device-resident batches staged ahead of the training loop via
    :func:`prefetch_to_device`; 0 restores the synchronous iterator.
    """

    def __init__(self, *arrays: np.ndarray, batch_size: int,
                 shuffle: bool = False, seed: int = 0,
                 drop_remainder: bool = False,
                 prefetch: Optional[int] = None):
        assert arrays, "need at least one array"
        n = arrays[0].shape[0]
        assert all(a.shape[0] == n for a in arrays)
        self.arrays = [np.asarray(a) for a in arrays]
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.prefetch = prefetch
        self.n = n

    def __len__(self) -> int:
        g = self.batch_size * core.size()
        return self.n // g if self.drop_remainder else -(-self.n // g)

    def __iter__(self) -> Iterator[Tuple]:
        def produce():
            for cols, rows_per_rank in self._iterate_host():
                yield (core._require_init().epoch, cols, rows_per_rank,
                       tuple(shard_batch(a) for a in cols),
                       shard_batch(rows_per_rank > 0))

        for epoch, cols, rpr, shards, active in prefetch_to_device(
                produce(), self.prefetch):
            if epoch != core._require_init().epoch:
                # staged over a retired mesh: an elastic membership
                # epoch landed while this batch sat in the prefetch
                # queue, so its device placement names devices that may
                # be gone.  Re-place from the retained host columns —
                # one synchronous device_put per epoch flip, not a
                # silent skipped batch.  (A world-SIZE change still
                # needs the caller to restart its epoch iteration: the
                # Join-tail layout is per-size, like the train state
                # rebuild elastic loops already do.)
                shards = tuple(shard_batch(a) for a in cols)
                active = shard_batch(rpr > 0)
            yield (*shards, active)

    def _iterate_host(self) -> Iterator[Tuple[List[np.ndarray], np.ndarray]]:
        """Host-side batch assembly only (index + Join-tail pad) —
        placement happens in the prefetch producer so the H2D copy
        overlaps compute."""
        size = core.size()
        g = self.batch_size * size
        idx = np.arange(self.n)
        if self.shuffle:
            # same permutation on every controller: seeded, not entropy-based
            np.random.default_rng(self.seed).shuffle(idx)
            self.seed += 1
        stop = (self.n // g) * g if self.drop_remainder else self.n
        for start in range(0, stop, g):
            take = idx[start: start + g]
            valid = take.shape[0]
            yield pad_tail(
                [a[take] for a in self.arrays], valid, self.batch_size,
                size,
            )
