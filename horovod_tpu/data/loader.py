"""Rank-sharded data loading with uneven-tail (Join) handling.

Analog of the fork's data loader shim (reference horovod/mxnet/dataloader.py
splits batches across ranks) plus the standard Horovod idiom of
``DistributedSampler``-style per-rank sharding; the uneven tail integrates
with Join semantics (elastic/join.py): the last partial global batch is
padded and accompanied by a per-rank ``active`` mask so
``join_allreduce`` divides by the true participant count — the compiled
analog of "rank r joined early" (reference controller.cc:253-264).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np


from .. import core
from ..training import shard_batch


def pad_tail(cols: List[np.ndarray], valid: int, batch_size: int,
             size: int) -> Tuple[List[np.ndarray], np.ndarray]:
    """THE Join-tail layout (single definition — ShardedLoader and the
    estimator's StoreLoader share it): zero-pad a partial global batch to
    ``batch_size * size`` rows, packing valid rows onto the lowest ranks,
    and return ``(cols, rows_per_rank)`` where ``rows_per_rank > 0`` is
    the active mask."""
    g = batch_size * size
    rows_per_rank = np.full((size,), batch_size, np.int32)
    if valid < g:
        full, rem = divmod(valid, batch_size)
        rows_per_rank = np.array(
            [batch_size] * full + ([rem] if rem else [])
            + [0] * (size - full - (1 if rem else 0)), np.int32,
        )
        pad = g - valid
        cols = [
            np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
            for a in cols
        ]
    return cols, rows_per_rank


class ShardedLoader:
    """Iterate (sharded_batch..., active_mask) over a host dataset.

    Each yield is a *global* batch of ``batch_size * size()`` rows placed
    so dim 0 is split across ranks.  When the data doesn't divide evenly,
    the final batch is zero-padded and ``active`` marks which ranks hold
    at least one real row (per-row validity is in ``valid_counts``).
    """

    def __init__(self, *arrays: np.ndarray, batch_size: int,
                 shuffle: bool = False, seed: int = 0,
                 drop_remainder: bool = False):
        assert arrays, "need at least one array"
        n = arrays[0].shape[0]
        assert all(a.shape[0] == n for a in arrays)
        self.arrays = [np.asarray(a) for a in arrays]
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.n = n

    def __len__(self) -> int:
        g = self.batch_size * core.size()
        return self.n // g if self.drop_remainder else -(-self.n // g)

    def __iter__(self) -> Iterator[Tuple]:
        size = core.size()
        g = self.batch_size * size
        idx = np.arange(self.n)
        if self.shuffle:
            # same permutation on every controller: seeded, not entropy-based
            np.random.default_rng(self.seed).shuffle(idx)
            self.seed += 1
        stop = (self.n // g) * g if self.drop_remainder else self.n
        for start in range(0, stop, g):
            take = idx[start: start + g]
            valid = take.shape[0]
            cols, rows_per_rank = pad_tail(
                [a[take] for a in self.arrays], valid, self.batch_size,
                size,
            )
            shards = tuple(shard_batch(a) for a in cols)
            active = shard_batch(rows_per_rank > 0)
            yield (*shards, active)
