from .loader import ShardedLoader  # noqa: F401
