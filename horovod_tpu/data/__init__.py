from .loader import ShardedLoader, prefetch_to_device  # noqa: F401
