"""Pure anomaly detectors over telemetry time-series.

Each detector takes plain ``[(step, value), ...]`` sample lists (the
shape stored by :mod:`horovod_tpu.metrics.timeseries`) plus explicit
thresholds, and returns either ``None`` (quiet) or an alert record::

    {"severity": "warning" | "critical",
     "signal":   "<detector name>",
     "evidence": {...detector-specific numbers...},
     "window":   {"start_step": int, "end_step": int, "samples": int}}

No detector reads env vars, touches the registry, or mutates its
inputs — the watchdog owns wiring, cadence, and dedup; tests pin the
math on hand-computed fixtures (fixtures.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

Sample = Tuple[Any, float]

# Consistent with scipy's convention: sigma ~= 1.4826 * MAD for a
# normal distribution.
MAD_SIGMA = 1.4826

SIGNAL_STEP_TIME = "step_time_regression"
SIGNAL_STRAGGLER = "straggler_drift"
SIGNAL_MFU = "mfu_drop"
SIGNAL_BETA = "comm_beta_drift"
SIGNAL_SLO_BURN = "slo_burn_rate"

SIGNALS = (
    SIGNAL_STEP_TIME,
    SIGNAL_STRAGGLER,
    SIGNAL_MFU,
    SIGNAL_BETA,
    SIGNAL_SLO_BURN,
)


def _median(values: Sequence[float]) -> float:
    vals = sorted(values)
    n = len(vals)
    if not n:
        return 0.0
    mid = n // 2
    if n % 2:
        return float(vals[mid])
    return (vals[mid - 1] + vals[mid]) / 2.0


def _steps(samples: Sequence[Sample]) -> Tuple[int, int]:
    first = samples[0][0]
    last = samples[-1][0]
    return (int(first) if first is not None else 0,
            int(last) if last is not None else 0)


def _alert(signal: str, severity: str, evidence: Dict[str, Any],
           samples: Sequence[Sample]) -> Dict[str, Any]:
    start, end = _steps(samples)
    return {
        "signal": signal,
        "severity": severity,
        "evidence": evidence,
        "window": {"start_step": start, "end_step": end,
                   "samples": len(samples)},
    }


def ewma_mad_regression(
    samples: Sequence[Sample],
    *,
    alpha: float = 0.5,
    k: float = 5.0,
    warmup: int = 16,
    confirm: int = 3,
) -> Optional[Dict[str, Any]]:
    """EWMA step-time regression against a median+MAD baseline.

    The first ``warmup`` samples establish ``median`` and ``MAD``;
    the threshold is ``median + k * 1.4826 * MAD`` (with a ~5%-of-
    median floor on sigma when the baseline is perfectly flat). An
    EWMA (seeded at the baseline median) must sit above the threshold
    for ``confirm`` consecutive samples to fire; severity escalates
    to critical when the EWMA also clears ``median + 2k * sigma``.
    """
    if len(samples) < warmup + confirm:
        return None
    baseline = [v for _, v in samples[:warmup]]
    med = _median(baseline)
    mad = _median([abs(v - med) for v in baseline])
    sigma = MAD_SIGMA * mad
    if sigma <= 0:
        sigma = 0.05 * abs(med) or 1e-9
    threshold = med + k * sigma
    critical_at = med + 2.0 * k * sigma

    ewma = med
    streak = 0
    for idx in range(warmup, len(samples)):
        value = samples[idx][1]
        ewma = alpha * value + (1.0 - alpha) * ewma
        if ewma > threshold:
            streak += 1
        else:
            streak = 0
        if streak >= confirm:
            severity = "critical" if ewma > critical_at else "warning"
            step = samples[idx][0]
            return _alert(
                SIGNAL_STEP_TIME,
                severity,
                {
                    "baseline_median": med,
                    "baseline_mad": mad,
                    "threshold": threshold,
                    "ewma": ewma,
                    "fired_step": int(step) if step is not None else idx,
                    "confirm": confirm,
                },
                samples,
            )
    return None


def straggler_drift(
    per_rank: Dict[str, Sequence[Sample]],
    *,
    skew: float = 1.3,
    min_samples: int = 8,
    window: int = 64,
) -> Optional[Dict[str, Any]]:
    """Per-rank cadence skew vs the world median.

    For each rank, the mean step time over the trailing ``window``
    samples is compared to the median of those per-rank means; a rank
    whose ratio exceeds ``skew`` is a straggler. Critical when the
    ratio exceeds ``1 + 2 * (skew - 1)``.
    """
    means: Dict[str, float] = {}
    for rank, samples in per_rank.items():
        tail = list(samples)[-window:]
        if len(tail) < min_samples:
            continue
        means[rank] = sum(v for _, v in tail) / len(tail)
    if len(means) < 2:
        return None
    world_median = _median(list(means.values()))
    if world_median <= 0:
        return None
    critical_skew = 1.0 + 2.0 * (skew - 1.0)
    worst_rank = None
    worst_ratio = 0.0
    for rank, mean in means.items():
        ratio = mean / world_median
        if ratio > worst_ratio:
            worst_rank, worst_ratio = rank, ratio
    if worst_rank is None or worst_ratio <= skew:
        return None
    severity = "critical" if worst_ratio >= critical_skew else "warning"
    tail = list(per_rank[worst_rank])[-window:]
    return _alert(
        SIGNAL_STRAGGLER,
        severity,
        {
            "rank": worst_rank,
            "ratio": worst_ratio,
            "rank_mean": means[worst_rank],
            "world_median": world_median,
            "skew_threshold": skew,
            "ranks": len(means),
        },
        tail,
    )


def straggler_from_verdicts(
    verdicts: Dict[str, Dict[str, Any]],
    *,
    skew: float = 1.3,
) -> Optional[Dict[str, Any]]:
    """Straggler alert from a trace-merge per-rank verdict block.

    ``verdicts`` is the ``{"ranks": {rank: {"verdict", "skew", ...}}}``
    machine block emitted by ``timeline.merge.straggler_report``;
    this lifts a ``straggler`` verdict into the same alert shape as
    :func:`straggler_drift` so offline traces and the live watchdog
    share one consumer.
    """
    ranks = verdicts.get("ranks") if isinstance(verdicts, dict) else None
    if not isinstance(ranks, dict):
        return None
    worst_rank = None
    worst_ratio = 0.0
    for rank, row in ranks.items():
        if not isinstance(row, dict) or row.get("verdict") != "straggler":
            continue
        ratio = float(row.get("skew", 0.0))
        if ratio > worst_ratio:
            worst_rank, worst_ratio = str(rank), ratio
    if worst_rank is None:
        return None
    critical_skew = 1.0 + 2.0 * (skew - 1.0)
    severity = "critical" if worst_ratio >= critical_skew else "warning"
    return {
        "signal": SIGNAL_STRAGGLER,
        "severity": severity,
        "evidence": {
            "rank": worst_rank,
            "ratio": worst_ratio,
            "skew_threshold": skew,
            "source": "trace_verdicts",
        },
        "window": {"start_step": 0, "end_step": 0, "samples": 0},
    }


def mfu_drop(
    samples: Sequence[Sample],
    *,
    drop_pct: float = 20.0,
    min_samples: int = 8,
) -> Optional[Dict[str, Any]]:
    """MFU drop: trailing-quarter median vs first-half median.

    Fires when the recent median sits more than ``drop_pct`` percent
    below the baseline median; critical past ``2 * drop_pct``.
    """
    if len(samples) < min_samples:
        return None
    values = [v for _, v in samples]
    baseline = _median(values[: len(values) // 2])
    recent = _median(values[-max(1, len(values) // 4):])
    if baseline <= 0:
        return None
    drop = 100.0 * (baseline - recent) / baseline
    if drop <= drop_pct:
        return None
    severity = "critical" if drop > 2.0 * drop_pct else "warning"
    return _alert(
        SIGNAL_MFU,
        severity,
        {
            "baseline_mfu": baseline,
            "recent_mfu": recent,
            "drop_pct": drop,
            "threshold_pct": drop_pct,
        },
        samples,
    )


def comm_beta_drift(
    samples: Sequence[Sample],
    predicted_us_per_mib: float,
    *,
    drift: float = 2.0,
    min_samples: int = 8,
) -> Optional[Dict[str, Any]]:
    """Measured dispatch density vs the calibrated alpha-beta model.

    ``samples`` carry measured collective dispatch cost in us/MiB;
    fires when the measured median exceeds ``drift`` times the model
    prediction (critical past ``2 * drift``).
    """
    if len(samples) < min_samples or predicted_us_per_mib <= 0:
        return None
    measured = _median([v for _, v in samples])
    ratio = measured / predicted_us_per_mib
    if ratio <= drift:
        return None
    severity = "critical" if ratio > 2.0 * drift else "warning"
    return _alert(
        SIGNAL_BETA,
        severity,
        {
            "measured_us_per_mib": measured,
            "predicted_us_per_mib": predicted_us_per_mib,
            "ratio": ratio,
            "drift_threshold": drift,
        },
        samples,
    )


def slo_burn_rate(
    samples: Sequence[Sample],
    slo_ms: float,
    *,
    budget: float = 0.01,
    burn_threshold: float = 2.0,
    min_samples: int = 16,
) -> Optional[Dict[str, Any]]:
    """Serving SLO burn rate over the observed window.

    Burn rate is ``breach_fraction / budget`` where the budget is the
    allowed fraction of requests above ``slo_ms``. Fires past
    ``burn_threshold``; critical past ``2 * burn_threshold``.
    """
    if len(samples) < min_samples or slo_ms <= 0 or budget <= 0:
        return None
    values = [v for _, v in samples]
    breaches = sum(1 for v in values if v > slo_ms)
    fraction = breaches / len(values)
    burn = fraction / budget
    if burn <= burn_threshold:
        return None
    severity = "critical" if burn > 2.0 * burn_threshold else "warning"
    return _alert(
        SIGNAL_SLO_BURN,
        severity,
        {
            "slo_ms": slo_ms,
            "breaches": breaches,
            "breach_fraction": fraction,
            "budget": budget,
            "burn_rate": burn,
        },
        samples,
    )
