"""Hand-computed detector fixtures.

``watch_fixture()`` builds deterministic traces for every detector;
``WATCH_EXPECTED`` pins the exact values the detectors must produce
on them (thresholds, fire steps, severities), derived by hand:

* regression: 40 baseline samples alternating 0.100/0.102 s
  (median 0.101, MAD 0.001, sigma 0.0014826) give threshold
  0.101 + 5 * 0.0014826 = 0.1084130 and critical bar 0.115826;
  the 0.120 s regression starting at step 41 drives the EWMA
  (alpha 0.5, seeded at 0.101) through 0.1105, 0.11525, 0.117625 —
  the third consecutive breach fires at step 43, critical because
  0.117625 > 0.115826.
* straggler: ranks 0-3 at 0.100 s except rank 1 at 0.140 s — world
  median 0.100, ratio 1.4 > skew 1.3 but < critical bar 1.6.
* mfu: 8 x 0.40 then 8 x 0.30 — baseline median 0.40, trailing-
  quarter median 0.30, drop 25% > 20% but < 40%.
* beta: measured 120 us/MiB vs predicted 50 — ratio 2.4 > 2 but < 4.
* burn: 3 of 50 samples above the 250 ms SLO — breach fraction 0.06
  over budget 0.01 = burn 6.0 > 2 * threshold(2.0), critical.
* quiet: flat traces on which no detector may fire.

``evaluate_fixture()`` runs the detectors on these traces; the tests
and ``hvd_watch --check`` both compare its output to WATCH_EXPECTED.

``events_fixture()`` is the flight-recorder analog: a hand-written
incident chain (lease expiry on rank 1 → removal → abort → shrink
epoch → a survivor's observe → resume) plus one unrelated checkpoint
event that must stay OUT of the chain.  ``EVENTS_EXPECTED`` pins what
``extract_chain`` + ``chain_summary`` (observe/events.py) must say
about it: 6 chained events rooted at ``launcher-1-0``, failed rank 1,
3 steps lost, 1.5 s from expiry to resume.  The tests and
``hvd_events --check`` both compare against it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from . import detectors

Sample = Tuple[int, float]

REGRESSION_PARAMS = {"alpha": 0.5, "k": 5.0, "warmup": 40, "confirm": 3}
STRAGGLER_PARAMS = {"skew": 1.3, "min_samples": 8, "window": 64}
MFU_PARAMS = {"drop_pct": 20.0, "min_samples": 8}
BETA_PARAMS = {"drift": 2.0, "min_samples": 8}
BETA_PREDICTED_US_PER_MIB = 50.0
BURN_PARAMS = {"budget": 0.01, "burn_threshold": 2.0, "min_samples": 16}
BURN_SLO_MS = 250.0

WATCH_EXPECTED: Dict[str, Any] = {
    "regression": {
        "severity": "critical",
        "baseline_median": 0.101,
        "baseline_mad": 0.001,
        "threshold": 0.1084130,
        "ewma": 0.117625,
        "fired_step": 43,
    },
    "straggler": {
        "severity": "warning",
        "rank": "1",
        "ratio": 1.4,
        "world_median": 0.100,
    },
    "mfu": {
        "severity": "warning",
        "baseline_mfu": 0.40,
        "recent_mfu": 0.30,
        "drop_pct": 25.0,
    },
    "beta": {
        "severity": "warning",
        "measured_us_per_mib": 120.0,
        "ratio": 2.4,
    },
    "burn": {
        "severity": "critical",
        "breaches": 3,
        "breach_fraction": 0.06,
        "burn_rate": 6.0,
    },
    "quiet": None,
}


def _baseline(n: int = 40, lo: float = 0.100, hi: float = 0.102,
              start_step: int = 1) -> List[Sample]:
    return [(start_step + i, lo if i % 2 == 0 else hi) for i in range(n)]


def watch_fixture() -> Dict[str, Any]:
    regression = _baseline(40)
    regression += [(41 + i, 0.120) for i in range(8)]

    straggler = {
        rank: [(i + 1, 0.140 if rank == "1" else 0.100) for i in range(16)]
        for rank in ("0", "1", "2", "3")
    }

    mfu = [(i + 1, 0.40) for i in range(8)]
    mfu += [(9 + i, 0.30) for i in range(8)]

    beta = [(i + 1, 120.0) for i in range(16)]

    burn = [(i + 1, 200.0) for i in range(47)]
    burn += [(48 + i, 300.0) for i in range(3)]

    quiet = {
        "regression": _baseline(48),
        "straggler": {
            rank: [(i + 1, 0.100) for i in range(16)]
            for rank in ("0", "1", "2", "3")
        },
        "mfu": [(i + 1, 0.40) for i in range(16)],
        "beta": [(i + 1, 60.0) for i in range(16)],
        "burn": [(i + 1, 200.0) for i in range(50)],
    }

    return {
        "regression": regression,
        "straggler": straggler,
        "mfu": mfu,
        "beta": beta,
        "burn": burn,
        "quiet": quiet,
    }


def evaluate_fixture(fixture: Dict[str, Any] = None) -> Dict[str, Any]:
    """Run every detector on the fixture traces.

    Returns ``{"regression": alert, ..., "quiet": [alerts]}`` where
    the quiet entry collects any (unexpected) alerts from the flat
    traces.
    """
    fx = fixture if fixture is not None else watch_fixture()
    out: Dict[str, Any] = {
        "regression": detectors.ewma_mad_regression(
            fx["regression"], **REGRESSION_PARAMS),
        "straggler": detectors.straggler_drift(
            fx["straggler"], **STRAGGLER_PARAMS),
        "mfu": detectors.mfu_drop(fx["mfu"], **MFU_PARAMS),
        "beta": detectors.comm_beta_drift(
            fx["beta"], BETA_PREDICTED_US_PER_MIB, **BETA_PARAMS),
        "burn": detectors.slo_burn_rate(
            fx["burn"], BURN_SLO_MS, **BURN_PARAMS),
    }
    quiet = fx["quiet"]
    quiet_alerts = [
        a for a in (
            detectors.ewma_mad_regression(
                quiet["regression"], **REGRESSION_PARAMS),
            detectors.straggler_drift(
                quiet["straggler"], **STRAGGLER_PARAMS),
            detectors.mfu_drop(quiet["mfu"], **MFU_PARAMS),
            detectors.comm_beta_drift(
                quiet["beta"], BETA_PREDICTED_US_PER_MIB, **BETA_PARAMS),
            detectors.slo_burn_rate(quiet["burn"], BURN_SLO_MS,
                                    **BURN_PARAMS),
        ) if a is not None
    ]
    out["quiet"] = quiet_alerts
    return out


# ---------------------------------------------------------------------------
# flight-recorder fixture (hvd_events --check, tests/test_events.py)
# ---------------------------------------------------------------------------
EVENTS_EXPECTED: Dict[str, Any] = {
    "correlation_id": "launcher-1-0",
    "events": 6,
    "kinds": ["lease.expired", "epoch.remove", "abort.publish",
              "epoch.commit", "abort.observe", "restart.resume"],
    "failed_rank": 1,
    "steps_lost": 3,
    "duration_seconds": 1.5,
    "severities": ["critical", "info", "warning"],
}


def events_fixture() -> List[Dict[str, Any]]:
    """A deterministic incident: rank 1's lease expires at t=100.0; the
    driver removes it, publishes the abort, and commits the shrink
    epoch; a survivor (rank 2, its own process) observes the abort via
    the flag-carried event id and resumes at t=101.5 having replayed 3
    steps.  The checkpoint.save at t=100.4 is a different correlation
    and must not appear in the chain."""
    return [
        {"id": "launcher-1-0", "ts": 100.0, "host": "launcher", "rank": 1,
         "kind": "lease.expired", "severity": "critical",
         "correlation_id": "launcher-1-0", "cause_id": None,
         "payload": {"rank": 1, "worker": "1", "age_seconds": 6.2}},
        {"id": "launcher-1-1", "ts": 100.1, "host": "launcher", "rank": None,
         "kind": "epoch.remove", "severity": "warning",
         "correlation_id": "launcher-1-0", "cause_id": "launcher-1-0",
         "payload": {"worker": "1", "rank": 1,
                     "reason": "lease expired", "drain": False}},
        {"id": "launcher-1-2", "ts": 100.2, "host": "launcher", "rank": 1,
         "kind": "abort.publish", "severity": "critical",
         "correlation_id": "launcher-1-0", "cause_id": "launcher-1-1",
         "payload": {"reason": "worker 1 removed: lease expired",
                     "source": "elastic_driver", "rank": 1, "epoch": 1}},
        {"id": "launcher-1-3", "ts": 100.3, "host": "launcher", "rank": None,
         "kind": "epoch.commit", "severity": "warning",
         "correlation_id": "launcher-1-0", "cause_id": "launcher-1-1",
         "payload": {"epoch": 2, "size": 3, "removed": ["1"],
                     "admitted": [], "reason": "worker 1 removed"}},
        {"id": "launcher-1-4", "ts": 100.4, "host": "launcher", "rank": 0,
         "kind": "checkpoint.save", "severity": "info",
         "correlation_id": "launcher-1-4", "cause_id": None,
         "payload": {"path": "/ckpt/step_120", "step": 120}},
        {"id": "worker2-9-0", "ts": 100.5, "host": "worker2", "rank": 2,
         "kind": "abort.observe", "severity": "warning",
         "correlation_id": "launcher-1-0", "cause_id": "launcher-1-2",
         "payload": {"reason": "worker 1 removed: lease expired",
                     "source": "elastic_driver", "failed_rank": 1}},
        {"id": "worker2-9-1", "ts": 101.5, "host": "worker2", "rank": 2,
         "kind": "restart.resume", "severity": "info",
         "correlation_id": "launcher-1-0", "cause_id": "launcher-1-3",
         "payload": {"epoch": 2, "old_size": 4, "new_size": 3,
                     "step": 120, "steps_lost": 3}},
    ]


def evaluate_events_fixture(
        events: List[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Chain extraction + summary over the fixture, starting from the
    LAST chain event (the resume) so the walk crosses every cause
    link.  Compared against ``EVENTS_EXPECTED`` by the tests and
    ``hvd_events --check``."""
    from . import events as events_mod

    evs = events if events is not None else events_fixture()
    chain = events_mod.extract_chain(evs, "worker2-9-1")
    summary = events_mod.chain_summary(chain)
    return summary


# ---------------------------------------------------------------------------
# chaos invariant fixture (hvd_chaos --check)
# ---------------------------------------------------------------------------

#: what the invariant monitors (observe/invariants.py) must say about
#: ``chaos_fixture()``: the recovery chain itself is clean, but the
#: stream deliberately violates TWO promises — rank 0's resume reports
#: 17 steps lost (> the snapshot interval of 5, with the full causal
#: chain from the lease expiry as evidence) and request ``req-7``
#: completes twice across a drain.  Everything else must stay green.
CHAOS_EXPECTED: Dict[str, Any] = {
    "violated": ["serving-exactly-once", "steps-lost-bound"],
    "green": ["abort-propagation", "epoch-monotonic",
              "restore-source-agreement"],
    "steps_lost_chain_kinds": ["lease.expired", "epoch.remove",
                               "abort.publish", "epoch.commit",
                               "abort.observe", "restore.source",
                               "restart.resume", "restart.resume"],
    "steps_lost": 17,
    "duplicate_request": "req-7",
    "completions": 2,
}

#: parameters ``evaluate_chaos_fixture`` checks the stream against
CHAOS_PARAMS = {"hb_interval": 0.5, "snapshot_every": 5}


def chaos_fixture() -> List[Dict[str, Any]]:
    """A hand-written incident stream: lease expiry on rank 2 →
    removal → abort → shrink commit → survivor observes (0.3 s later,
    inside the 2 x 0.5 s bound) → restores from gen 4 → resumes
    reporting 17 steps lost (the planted steps-lost violation), plus a
    ``serve.complete`` pair for the same request id (the planted
    exactly-once violation) and a second, clean commit chain proving
    epoch monotonicity."""
    return [
        {"id": "launcher-2-0", "ts": 200.0, "host": "launcher", "rank": 2,
         "kind": "lease.expired", "severity": "critical",
         "correlation_id": "launcher-2-0", "cause_id": None,
         "payload": {"rank": 2, "worker": "2", "age_seconds": 2.1,
                     "interval": 0.5}},
        {"id": "launcher-2-1", "ts": 200.05, "host": "launcher", "rank": 2,
         "kind": "epoch.remove", "severity": "warning",
         "correlation_id": "launcher-2-0", "cause_id": "launcher-2-0",
         "payload": {"worker": "2", "rank": 2, "drain": False,
                     "reason": "rank 2 heartbeat lease expired"}},
        {"id": "launcher-2-2", "ts": 200.1, "host": "launcher", "rank": 2,
         "kind": "abort.publish", "severity": "critical",
         "correlation_id": "launcher-2-0", "cause_id": "launcher-2-1",
         "payload": {"reason": "rank 2 lease expired", "epoch": 3,
                     "source": "elastic_driver"}},
        {"id": "launcher-2-3", "ts": 200.15, "host": "launcher",
         "rank": None, "kind": "epoch.commit", "severity": "warning",
         "correlation_id": "launcher-2-0", "cause_id": "launcher-2-1",
         "payload": {"epoch": 4, "size": 2, "removed": ["2"],
                     "admitted": [], "reason": "rank 2 lease expired"}},
        {"id": "worker0-4-0", "ts": 200.4, "host": "worker0", "rank": 0,
         "kind": "abort.observe", "severity": "warning",
         "correlation_id": "launcher-2-0", "cause_id": "launcher-2-2",
         "payload": {"epoch": 3, "worker": "0",
                     "reason": "rank 2 lease expired"}},
        {"id": "worker0-4-1", "ts": 200.45, "host": "worker0", "rank": 0,
         "kind": "restore.source", "severity": "info",
         "correlation_id": "launcher-2-0", "cause_id": "launcher-2-3",
         "payload": {"epoch": 4, "gen": 4, "step": 40, "worker": "0",
                     "source": "peer"}},
        # the planted violation: 17 steps lost >> snapshot_every 5
        {"id": "worker0-4-2", "ts": 200.5, "host": "worker0", "rank": 0,
         "kind": "restart.resume", "severity": "info",
         "correlation_id": "launcher-2-0", "cause_id": "launcher-2-3",
         "payload": {"epoch": 4, "steps_lost": 17, "worker": "0"}},
        {"id": "worker1-5-0", "ts": 200.5, "host": "worker1", "rank": 1,
         "kind": "restart.resume", "severity": "info",
         "correlation_id": "launcher-2-0", "cause_id": "launcher-2-3",
         "payload": {"epoch": 4, "steps_lost": 3, "worker": "1"}},
        # a later, clean drain commit: epoch keeps moving forward
        {"id": "launcher-2-4", "ts": 201.0, "host": "launcher",
         "rank": None, "kind": "epoch.commit", "severity": "warning",
         "correlation_id": "launcher-2-4", "cause_id": None,
         "payload": {"epoch": 5, "size": 1, "removed": ["1"],
                     "admitted": [],
                     "reason": "autoscale shrink (drained: in-flight "
                               "work completed)"}},
        # the planted exactly-once violation: req-7 completes twice
        {"id": "serve-6-0", "ts": 200.8, "host": "serve0", "rank": 0,
         "kind": "serve.complete", "severity": "info",
         "correlation_id": "serve-6-0", "cause_id": None,
         "payload": {"request_id": "req-7"}},
        {"id": "serve-6-1", "ts": 201.1, "host": "serve1", "rank": 1,
         "kind": "serve.complete", "severity": "info",
         "correlation_id": "serve-6-1", "cause_id": None,
         "payload": {"request_id": "req-7"}},
        {"id": "serve-6-2", "ts": 201.2, "host": "serve1", "rank": 1,
         "kind": "serve.complete", "severity": "info",
         "correlation_id": "serve-6-2", "cause_id": None,
         "payload": {"request_id": "req-8"}},
    ]


def evaluate_chaos_fixture(
        events: List[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Run the full invariant catalogue over the fixture stream and
    distil the verdict shape ``CHAOS_EXPECTED`` pins: which invariants
    fired, which stayed green, and the causal chain behind the
    steps-lost violation."""
    from . import invariants as invariants_mod

    evs = events if events is not None else chaos_fixture()
    violations = invariants_mod.check_all(
        evs, hb_interval=CHAOS_PARAMS["hb_interval"],
        snapshot_every=CHAOS_PARAMS["snapshot_every"])
    violated = sorted({v.invariant for v in violations})
    steps = next((v for v in violations
                  if v.invariant == "steps-lost-bound"), None)
    dup = next((v for v in violations
                if v.invariant == "serving-exactly-once"), None)
    return {
        "violated": violated,
        "green": sorted(set(invariants_mod.INVARIANTS)
                        - set(violated) - {"no-hanging-rank"}),
        "steps_lost_chain_kinds": [e.get("kind")
                                   for e in (steps.chain if steps
                                             else [])],
        "steps_lost": (steps.evidence.get("steps_lost")
                       if steps else None),
        "duplicate_request": (dup.evidence.get("request_id")
                              if dup else None),
        "completions": (dup.evidence.get("completions")
                        if dup else None),
        "violations": violations,
    }
