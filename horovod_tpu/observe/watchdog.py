"""The launcher-side watchdog thread: detect → alert → arm → attribute.

Runs next to the rendezvous server (run/run.py starts one per job,
``HVD_WATCH=0`` disables).  Every ``HVD_WATCH_INTERVAL_SECONDS`` tick:

1. reads the flushed telemetry history straight off the in-process
   server handle (``server.timeseries_report()`` — no HTTP round
   trip) and runs the pure detectors (detectors.py) over it:
   EWMA/MAD step-time regression and comm-β drift per rank, straggler
   cadence skew across ranks, MFU drop, serving SLO burn rate;
2. publishes each fired alert to the ``alerts`` KV scope (key = a
   monotonically increasing id, so ``GET /alerts`` renders newest
   first) and bumps ``hvd_alerts_total{signal,severity}``; a
   per-signal cooldown (``HVD_WATCH_ARM_COOLDOWN_SECONDS``) stops a
   persisting condition from flooding the log;
3. a confirmed step-time or straggler alert **auto-arms** a
   trace+profile window: the arm record is broadcast through
   ``observe/arm`` (autoarm.py) with a start step far enough ahead
   (``HVD_WATCH_ARM_MARGIN_STEPS`` past the newest cadence step) that
   every rank applies it before the window opens;
4. once the armed window's anatomies land in the ``profile`` scope,
   the alert record is re-published with an ``attribution`` block —
   top segment, its slowest rank, mean MFU, worst host gap — so the
   alert names the block or rank instead of a bare number;
5. a *critical* straggler alert optionally feeds the elastic driver's
   removal path (``HVD_WATCH_EVICT=1`` + an attached driver).

The watchdog never touches the step path: workers only pay the
ring-buffer appends (metrics/timeseries.py).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import env as env_util
from ..utils.logging import get_logger
from . import autoarm, detectors

log = get_logger(__name__)

ALERTS_SCOPE = "alerts"

#: signals whose confirmed alerts auto-arm a trace+profile window
ARMING_SIGNALS = (detectors.SIGNAL_STEP_TIME, detectors.SIGNAL_STRAGGLER)


def _samples(doc: Any, name: str) -> List[Any]:
    """``[(step, value), ...]`` from one rank's pushed series doc."""
    if not isinstance(doc, dict):
        return []
    entry = (doc.get("series") or {}).get(name)
    if not isinstance(entry, dict):
        return []
    out = []
    for s in entry.get("samples") or []:
        if isinstance(s, (list, tuple)) and len(s) == 2:
            out.append((s[0], float(s[1])))
    return out


class Watchdog(threading.Thread):
    """One per job; ``start()`` after the rendezvous server is up,
    ``stop()`` in the launcher's finally."""

    def __init__(self, server: Any, driver: Any = None,
                 interval: Optional[float] = None):
        super().__init__(name="hvd-watchdog", daemon=True)
        self._server = server
        self._driver = driver
        self._stop = threading.Event()
        self.interval = interval if interval is not None else \
            env_util.get_float(env_util.HVD_WATCH_INTERVAL_SECONDS,
                               env_util.DEFAULT_WATCH_INTERVAL_SECONDS)
        self.window = env_util.get_int(env_util.HVD_WATCH_WINDOW,
                                       env_util.DEFAULT_WATCH_WINDOW)
        self.alpha = env_util.get_float(env_util.HVD_WATCH_EWMA_ALPHA,
                                        env_util.DEFAULT_WATCH_EWMA_ALPHA)
        self.mad_k = env_util.get_float(env_util.HVD_WATCH_MAD_K,
                                        env_util.DEFAULT_WATCH_MAD_K)
        self.confirm = env_util.get_int(env_util.HVD_WATCH_CONFIRM,
                                        env_util.DEFAULT_WATCH_CONFIRM)
        self.skew = env_util.get_float(env_util.HVD_WATCH_STRAGGLER_SKEW,
                                       env_util.DEFAULT_WATCH_STRAGGLER_SKEW)
        self.mfu_drop_pct = env_util.get_float(
            env_util.HVD_WATCH_MFU_DROP_PCT,
            env_util.DEFAULT_WATCH_MFU_DROP_PCT)
        self.beta_drift = env_util.get_float(env_util.HVD_WATCH_BETA_DRIFT,
                                             env_util.DEFAULT_WATCH_BETA_DRIFT)
        self.slo_ms = env_util.get_float(env_util.HVD_SERVE_SLO_MS,
                                         env_util.DEFAULT_SERVE_SLO_MS)
        self.slo_budget = env_util.get_float(
            env_util.HVD_WATCH_SLO_BUDGET,
            env_util.DEFAULT_WATCH_SLO_BUDGET)
        self.burn_threshold = env_util.get_float(
            env_util.HVD_WATCH_BURN_RATE, env_util.DEFAULT_WATCH_BURN_RATE)
        self.arm_enabled = env_util.get_bool(env_util.HVD_WATCH_ARM, True)
        self.arm_steps = env_util.get_int(env_util.HVD_WATCH_ARM_STEPS,
                                          env_util.DEFAULT_WATCH_ARM_STEPS)
        self.arm_margin = env_util.get_int(
            env_util.HVD_WATCH_ARM_MARGIN_STEPS,
            env_util.DEFAULT_WATCH_ARM_MARGIN_STEPS)
        self.cooldown = env_util.get_float(
            env_util.HVD_WATCH_ARM_COOLDOWN_SECONDS,
            env_util.DEFAULT_WATCH_ARM_COOLDOWN_SECONDS)
        self.evict = env_util.get_bool(env_util.HVD_WATCH_EVICT)
        self._next_id = 0
        self._last_emit: Dict[str, float] = {}   # signal key -> mono time
        self._last_arm = 0.0
        self._arm_seq = 0
        self._pending_attribution: List[Dict[str, Any]] = []
        self.alerts_emitted = 0
        self.arms = 0
        self.evictions = 0

    # -- lifecycle -----------------------------------------------------------
    def stop(self) -> None:
        self._stop.set()

    def attach_driver(self, driver: Any) -> None:
        """The elastic supervisor re-creates its driver per restart
        attempt; each new incarnation re-attaches here."""
        self._driver = driver

    def run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the watchdog must outlive a bad tick
                log.debug("watchdog tick failed: %s", e)

    # -- one tick ------------------------------------------------------------
    def tick(self) -> List[Dict[str, Any]]:
        """Run every detector over the flushed history; returns the
        alerts published this tick (the tests drive this directly)."""
        report = self._server.timeseries_report()
        ranks = report.get("ranks") or {}
        fired: List[Any] = []            # (dedup key, alert record)

        cadence: Dict[str, List[Any]] = {}
        for rank, doc in ranks.items():
            samples = _samples(doc, "step_seconds")
            if samples:
                cadence[rank] = samples

        # per-rank step-time regression
        for rank, samples in cadence.items():
            alert = detectors.ewma_mad_regression(
                samples[-self.window:], alpha=self.alpha, k=self.mad_k,
                warmup=max(8, min(len(samples) - self.confirm,
                                  self.window // 2)),
                confirm=self.confirm)
            if alert:
                alert["evidence"]["rank"] = rank
                fired.append((f"{alert['signal']}:{rank}", alert))

        # cross-rank straggler drift
        alert = detectors.straggler_drift(cadence, skew=self.skew,
                                          window=self.window)
        if alert:
            fired.append((f"{alert['signal']}:{alert['evidence']['rank']}",
                          alert))

        # MFU drop + comm-beta drift + SLO burn, per reporting rank
        for rank, doc in ranks.items():
            mfu = _samples(doc, "mfu")
            alert = detectors.mfu_drop(mfu[-self.window:],
                                       drop_pct=self.mfu_drop_pct)
            if alert:
                alert["evidence"]["rank"] = rank
                fired.append((f"{alert['signal']}:{rank}", alert))

            beta = _samples(doc, "dispatch_us_per_mib")
            if len(beta) >= 16:
                # self-calibrated model point: the window's own early
                # samples are the "healthy β" baseline (a launcher has
                # no per-op α–β inputs; docs/observe.md)
                baseline = sorted(v for _, v in beta[:8])
                predicted = baseline[len(baseline) // 2]
                alert = detectors.comm_beta_drift(
                    beta[-self.window:], predicted,
                    drift=self.beta_drift)
                if alert:
                    alert["evidence"]["rank"] = rank
                    alert["evidence"]["predicted_source"] = "baseline"
                    fired.append((f"{alert['signal']}:{rank}", alert))

            p99 = _samples(doc, "serve_p99_ms")
            alert = detectors.slo_burn_rate(
                p99[-self.window:], self.slo_ms, budget=self.slo_budget,
                burn_threshold=self.burn_threshold)
            if alert:
                alert["evidence"]["rank"] = rank
                fired.append((f"{alert['signal']}:{rank}", alert))

        published = []
        now = time.monotonic()
        for key, alert in fired:
            last = self._last_emit.get(key, 0.0)
            if now - last < self.cooldown:
                continue
            self._last_emit[key] = now
            published.append(self._publish(alert, cadence))
        self._enrich_pending()
        return published

    # -- publish / arm / evict ----------------------------------------------
    def _publish(self, alert: Dict[str, Any],
                 cadence: Dict[str, List[Any]]) -> Dict[str, Any]:
        alert_id = self._next_id
        self._next_id += 1
        record = dict(alert)
        record["id"] = str(alert_id)
        record["ts"] = time.time()
        try:
            from .. import metrics

            if metrics.on():
                metrics.ALERTS_TOTAL.labels(record["signal"],
                                            record["severity"]).inc()
        except Exception as e:  # noqa: BLE001
            log.debug("alert counter failed: %s", e)
        # flight-recorder: the alert event roots a causal chain — the
        # arm window and any eviction chain onto it (observe/events.py)
        alert_eid = None
        try:
            from . import events as events_mod

            alert_eid = events_mod.record_event(
                "watchdog.alert",
                severity=record.get("severity", "warning"),
                payload={"signal": record["signal"],
                         "alert_id": record["id"],
                         "evidence": record.get("evidence")})
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass
        if alert_eid:
            record["event_id"] = alert_eid
        if self.arm_enabled and record["signal"] in ARMING_SIGNALS:
            self._maybe_arm(record, cadence)
        if record["signal"] == detectors.SIGNAL_STRAGGLER:
            self._maybe_evict(record)
        self._put_alert(record)
        self.alerts_emitted += 1
        log.warning("watchdog alert #%s: %s (%s) %s", record["id"],
                    record["signal"], record["severity"],
                    record["evidence"])
        return record

    def _put_alert(self, record: Dict[str, Any]) -> None:
        try:
            self._server.put(ALERTS_SCOPE, record["id"],
                             json.dumps(record).encode())
        except Exception as e:  # noqa: BLE001
            log.debug("alert publish failed: %s", e)

    def _maybe_arm(self, record: Dict[str, Any],
                   cadence: Dict[str, List[Any]]) -> None:
        now = time.monotonic()
        if now - self._last_arm < self.cooldown:
            return
        newest = 0
        for samples in cadence.values():
            step = samples[-1][0]
            if isinstance(step, (int, float)) and int(step) > newest:
                newest = int(step)
        start = newest + self.arm_margin
        end = start + self.arm_steps - 1
        self._arm_seq += 1
        arm_id = f"arm-{self._arm_seq}"
        trace_dir = env_util.get_str(env_util.HVD_TIMELINE) or \
            env_util.get_str(env_util.HVD_TRACE_DIR)
        if not trace_dir:
            import os
            import tempfile

            trace_dir = os.path.join(tempfile.gettempdir(),
                                     "hvd_watch_trace", arm_id)
        try:
            autoarm.broadcast_arm(self._server, arm_id, start, end,
                                  record["signal"], trace_dir)
        except Exception as e:  # noqa: BLE001
            log.debug("arm broadcast failed: %s", e)
            return
        self._last_arm = now
        self.arms += 1
        record["armed"] = {"id": arm_id, "start_step": start,
                           "end_step": end, "trace_dir": trace_dir}
        try:
            from . import events as events_mod

            events_mod.record_event(
                "watchdog.arm", severity="info",
                payload={"arm_id": arm_id, "start_step": start,
                         "end_step": end, "signal": record["signal"],
                         "trace_dir": trace_dir},
                cause_id=record.get("event_id"))
        except Exception:  # noqa: BLE001 — recording is best-effort
            pass
        self._pending_attribution.append(record)
        try:
            from .. import metrics

            if metrics.on():
                metrics.WATCH_ARMS.inc()
        except Exception as e:  # noqa: BLE001
            log.debug("arm counter failed: %s", e)
        log.warning("watchdog armed trace+profile window [%d, %d] "
                    "(%s, alert #%s)", start, end, record["signal"],
                    record["id"])

    def _enrich_pending(self) -> None:
        """Attach profile attribution to armed alerts once the window's
        anatomies land in the ``profile`` scope, then re-publish."""
        if not self._pending_attribution:
            return
        try:
            profile = self._server.profile_report()
        except Exception as e:  # noqa: BLE001
            log.debug("profile report read failed: %s", e)
            return
        agg = (profile or {}).get("aggregate") or {}
        top = agg.get("top_segments") or []
        if not top:
            return
        segments = agg.get("segments") or {}
        top_name = top[0]
        seg = segments.get(top_name) or {}
        mfu = agg.get("mfu") or {}
        gap = agg.get("host_gap_per_step_us") or {}
        attribution = {
            "top_segment": top_name,
            "slowest_rank": seg.get("slowest_rank"),
            "spread_us": seg.get("spread_us"),
            "mean_device_us": seg.get("mean_device_us"),
            "mfu_mean": mfu.get("mean"),
            "host_gap_max_rank": gap.get("max_rank"),
        }
        for record in self._pending_attribution:
            record["attribution"] = attribution
            self._put_alert(record)
            log.info("alert #%s attributed: top segment %s (slowest "
                     "rank %s)", record["id"], top_name,
                     seg.get("slowest_rank"))
        self._pending_attribution = []

    def _maybe_evict(self, record: Dict[str, Any]) -> None:
        """Critical straggler + HVD_WATCH_EVICT=1 + an attached elastic
        driver → hand the rank to the driver's (drained) removal path;
        the driver's own min_np floor and flap blocklist still apply."""
        if not self.evict or record["severity"] != "critical":
            return
        driver = self._driver
        if driver is None:
            return
        rank_s = str(record["evidence"].get("rank", ""))
        try:
            world = list(getattr(driver, "world", []) or [])
            worker = world[int(rank_s)] if rank_s.isdigit() \
                and int(rank_s) < len(world) else rank_s
            ok = driver.remove(
                worker, f"watchdog: straggler rank {rank_s} at "
                f"{record['evidence'].get('ratio', 0):.2f}x world median",
                drain=True, cause_id=record.get("event_id"))
            if ok:
                self.evictions += 1
                record["evicted"] = worker
                log.warning("watchdog evicted straggler %s (rank %s)",
                            worker, rank_s)
        except Exception as e:  # noqa: BLE001
            log.warning("watchdog eviction failed: %s", e)


def start_from_env(server: Any, driver: Any = None) -> Optional[Watchdog]:
    """A started Watchdog when ``HVD_WATCH`` (default on) allows it."""
    if not env_util.get_bool(env_util.HVD_WATCH, True):
        return None
    dog = Watchdog(server, driver=driver)
    dog.start()
    return dog
